"""Continuous-batching generative decode engine.

The Orca insight applied to the serving stack: autoregressive decode
is *iteration-level* work — the scheduling unit is one token step over
all live sequences, not one request. This module owns that loop:

- **Prefill**: a new request's prompt runs full causal attention
  (through ``sdpa_core``, so the flash-attention ladder applies) on a
  per-prompt-bucket compiled program, its K/V scattered into the paged
  :class:`~deeplearning4j_tpu.serving.kvcache.KVBlockPool`, and its
  first token sampled — the time-to-first-token span.
- **Decode**: every engine iteration runs ONE fused step over all live
  sequences — gather KV blocks via block tables, paged attention
  (Pallas kernel or dense-gather fallback via the ``paged_attention``
  kernel-select family), sample, append — compiled once per decode
  bucket, so steady state never retraces while sequences join and
  leave mid-batch (the zero-post-warmup-retrace acceptance bar).
- **Retire**: a sequence leaves on EOS / ``max_tokens`` / client
  disconnect / deadline, and its blocks return to the pool *mid-batch*
  — the remaining sequences keep decoding, the freed blocks admit the
  next prefill.

Consumers read a :class:`TokenStream`: a queue the engine thread
pushes token ids into as they decode — the producer side of the HTTP
chunked-transfer streaming in ``serving.server``. Cancelling the
stream (client disconnect) retires the sequence on the next
iteration.

Dispatch signatures are recorded into the batcher's ``RetraceGuard``,
so ``retraces_since_warmup() == 0`` covers the generative path with
the same proof obligation as predict.
"""
from __future__ import annotations

import itertools
import queue as _queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.compilecache import RetraceGuard
from deeplearning4j_tpu.serving.admission import DeadlineExceeded
from deeplearning4j_tpu.serving.kvcache import KVBlockPool

#: terminal reasons a TokenStream closes with
END_REASONS = ("eos", "max_tokens", "cancelled", "deadline", "kv_pool",
               "error")


def _ttft_hist() -> telemetry.Histogram:
    return telemetry.histogram(
        "dl4j_generate_ttft_seconds",
        "time-to-first-token of generate requests: submit -> first "
        "sampled token (prefill queue + prefill compute), per model "
        "(seconds)")


def _intertoken_hist() -> telemetry.Histogram:
    return telemetry.histogram(
        "dl4j_generate_intertoken_seconds",
        "gap between consecutive streamed tokens of one sequence — "
        "the decode-iteration latency a streaming client experiences "
        "(seconds)")


def _tokens_counter() -> telemetry.Counter:
    return telemetry.counter(
        "dl4j_generate_tokens_total",
        "tokens decoded and streamed, per model — the goodput "
        "numerator")


def _requests_counter() -> telemetry.Counter:
    return telemetry.counter(
        "dl4j_generate_requests_total",
        "generate requests finished, by model and outcome (eos | "
        "max_tokens | cancelled | deadline | kv_pool | error)")


def _live_gauge() -> telemetry.Gauge:
    return telemetry.gauge(
        "dl4j_generate_live_sequences",
        "sequences currently in the continuous decode batch, per "
        "model")


def _disconnects_counter() -> telemetry.Counter:
    return telemetry.counter(
        "dl4j_generate_stream_disconnects_total",
        "generate streams cancelled mid-decode by client disconnect — "
        "their KV blocks return to the pool on the next iteration")


class TokenStream:
    """Consumer handle of one generate request: iterate token ids as
    the engine decodes them; ``reason`` tells how the sequence ended.
    ``cancel()`` (client disconnect) retires the sequence and frees
    its KV blocks on the engine's next iteration."""

    _DONE = object()

    def __init__(self, seq_id: int, prompt_len: int):
        self.seq_id = seq_id
        self.prompt_len = prompt_len
        self.reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.cancelled = False
        self._q: "_queue.Queue" = _queue.Queue()

    # engine side ------------------------------------------------------
    def _put(self, token: int) -> None:
        self._q.put(int(token))

    def _close(self, reason: str,
               error: Optional[BaseException] = None) -> None:
        if self.reason is None:
            self.reason = reason
            self.error = error
            self._q.put(self._DONE)

    # consumer side ----------------------------------------------------
    def cancel(self) -> None:
        self.cancelled = True

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._DONE:
                if self.error is not None:
                    raise self.error
                return
            yield item

    def next(self, timeout: Optional[float] = None) -> Optional[int]:
        """The next token id, or None when the stream has closed
        (check ``reason``). Raises the stream error on a failed
        sequence, ``queue.Empty`` on timeout — the server's per-token
        wait primitive."""
        item = self._q.get(timeout=timeout)
        if item is self._DONE:
            if self.error is not None:
                raise self.error
            return None
        return item

    def tokens(self, timeout: Optional[float] = None) -> List[int]:
        """Drain the whole stream (blocking); raises the stream error
        if the sequence failed."""
        out: List[int] = []
        deadline = None if timeout is None else (time.monotonic()
                                                 + timeout)
        while True:
            left = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            item = self._q.get(timeout=left)
            if item is self._DONE:
                if self.error is not None:
                    raise self.error
                return out
            out.append(item)


class _Sequence:
    """Engine-internal live-sequence state."""

    __slots__ = ("seq_id", "stream", "next_token", "position",
                 "generated", "max_tokens", "temperature", "top_k",
                 "deadline", "t_last", "ctx")

    def __init__(self, seq_id, stream, next_token, position,
                 max_tokens, temperature, top_k, deadline, t_last,
                 ctx=None):
        self.seq_id = seq_id
        self.stream = stream
        self.next_token = int(next_token)   # fed to the next step
        self.position = int(position)       # its index in the sequence
        self.generated = 1                  # the prefill-sampled token
        self.max_tokens = int(max_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.deadline = deadline
        self.t_last = t_last                # last token emit instant
        self.ctx = ctx                      # request TraceContext


class DecodeEngine:
    """The prefill/decode continuous-batching loop over one model.

    ``model`` exposes the :class:`~deeplearning4j_tpu.models.decoder.
    DecoderLM` contract (``prefill`` / ``decode_step`` / ``conf``);
    ``params`` is the (possibly resident-sharded) tree the jitted
    programs consume, ``view_fn`` the in-jit params adapter
    (``serving.residency.serving_param_view`` partial, or None for
    dense). One compiled program per prompt bucket (prefill+commit)
    and per decode bucket; ``warmup()`` compiles them all so the guard
    count freezes before the first real request."""

    def __init__(self, model, params, pool: KVBlockPool, *,
                 view_fn=None, name: str = "model",
                 prompt_buckets: Sequence[int] = (16, 64),
                 decode_buckets: Sequence[int] = (4, 8),
                 max_seq_len: Optional[int] = None,
                 paged: Optional[bool] = None,
                 guard: Optional[RetraceGuard] = None,
                 rng_seed: int = 0):
        self.model = model
        self.params = params
        self.pool = pool
        self.view_fn = view_fn
        self.name = name
        self.prompt_buckets = tuple(sorted(int(b)
                                           for b in set(prompt_buckets)))
        self.decode_buckets = tuple(sorted(int(b)
                                           for b in set(decode_buckets)))
        cap = pool.usable_blocks * pool.block_size
        self.max_seq_len = int(min(max_seq_len or model.conf.max_len,
                                   model.conf.max_len, cap))
        #: fixed block-table width — part of every decode signature
        self.max_blocks = pool.blocks_for(self.max_seq_len)
        self.guard = guard if guard is not None else RetraceGuard(
            f"generate:{name}",
            threshold=len(self.prompt_buckets)
            + len(self.decode_buckets) + 2)
        self._paged = paged
        self._seq_ids = itertools.count(1)
        self._pending: "_queue.Queue" = _queue.Queue()
        self._live: Dict[int, _Sequence] = {}
        self._lock = threading.Lock()
        self._worker: Optional[threading.Thread] = None
        #: the worker shutdown() swapped out, until it finishes its
        #: drain — _ensure_worker joins it so two workers never touch
        #: _pending/_live concurrently
        self._draining: Optional[threading.Thread] = None
        self._work = threading.Event()
        self._shutdown = False
        self._step = 0
        self._warmed = False
        self.warm_signatures = 0
        self._jits: dict = {}
        import jax
        self._rng = jax.random.PRNGKey(rng_seed)

    # -- compiled programs ---------------------------------------------
    def _paged_now(self) -> bool:
        """Resolve the paged-vs-dense decode backend once per compile
        (trace-time, like every kernel_select decision)."""
        if self._paged is not None:
            return bool(self._paged)
        from deeplearning4j_tpu.ops.attention_pallas import \
            select_paged_backend
        backend, _ = select_paged_backend(1, self.max_blocks)
        return backend == "paged"

    def _view(self, params):
        return self.view_fn(params) if self.view_fn is not None \
            else params

    def _prefill_jit(self):
        import jax
        if "prefill" not in self._jits:
            def fn(params, tokens, length):
                return self.model.prefill(self._view(params), tokens,
                                          length)
            self._jits["prefill"] = jax.jit(fn)
        return self._jits["prefill"]

    def _commit_jit(self):
        import jax
        if "commit" not in self._jits:
            def fn(kp, vp, k, v, slots):
                nl, nb, bs = kp.shape[0], kp.shape[1], kp.shape[2]
                tail = kp.shape[3:]
                kf = kp.reshape((nl, nb * bs) + tail)
                vf = vp.reshape((nl, nb * bs) + tail)
                # low-precision pools (kv_dtype=bf16) take the write
                # in the pool's own dtype
                kf = kf.at[:, slots].set(k[:, 0].astype(kp.dtype))
                vf = vf.at[:, slots].set(v[:, 0].astype(vp.dtype))
                return (kf.reshape(kp.shape), vf.reshape(vp.shape))
            self._jits["commit"] = jax.jit(fn)
        return self._jits["commit"]

    def _sample_jit(self):
        import jax
        if "sample" not in self._jits:
            from deeplearning4j_tpu.ops.sampling import sample_logits
            self._jits["sample"] = jax.jit(sample_logits)
        return self._jits["sample"]

    def _decode_jit(self):
        import jax

        from deeplearning4j_tpu.ops.sampling import sample_logits
        if "decode" not in self._jits:
            paged = self._paged_now()

            def fn(params, kp, vp, tokens, positions, tables, key,
                   temps, topks):
                logits, kp, vp = self.model.decode_step(
                    self._view(params), tokens, positions, kp, vp,
                    tables, paged=paged)
                ids = sample_logits(logits, key, temps, topks)
                return ids, kp, vp
            self._jits["decode"] = jax.jit(fn)
        return self._jits["decode"]

    # -- warmup --------------------------------------------------------
    def warmup(self) -> float:
        """Compile every prompt bucket's prefill+commit and every
        decode bucket's fused step (dummy data, blocked to
        completion). The guard count freezes here — any later new
        signature is a bucket miss."""
        import jax
        t0 = time.perf_counter()
        for t in self.prompt_buckets:
            tokens = np.zeros((1, t), np.int32)
            length = np.asarray([1], np.int32)
            self.guard.record(tokens, length)
            last, k, v = self._prefill_jit()(self.params, tokens,
                                             length)
            slots = np.zeros((t,), np.int32)
            self.guard.record(k, slots)
            kp, vp = self._commit_jit()(self.pool.k, self.pool.v, k, v,
                                        slots)
            # the first-token sampler compiles once here (its [1,
            # vocab] signature never varies with the prompt bucket)
            first = self._sample_jit()(
                last, jax.random.fold_in(self._rng, 0),
                np.zeros((1,), np.float32), np.zeros((1,), np.int32))
            jax.block_until_ready((last, kp, vp, first))
            # scratch-block writes only: pool arrays unchanged where
            # it matters, but keep the functional update discipline
            self.pool.update_arrays(kp, vp)
        for b in self.decode_buckets:
            tokens = np.zeros((b,), np.int32)
            positions = np.zeros((b,), np.int32)
            tables = np.zeros((b, self.max_blocks), np.int32)
            temps = np.zeros((b,), np.float32)
            topks = np.zeros((b,), np.int32)
            self.guard.record(tokens, positions, tables, temps, topks)
            import jax as _jax
            key = _jax.random.fold_in(self._rng, 0)
            ids, kp, vp = self._decode_jit()(
                self.params, self.pool.k, self.pool.v, tokens,
                positions, tables, key, temps, topks)
            jax.block_until_ready(ids)
            self.pool.update_arrays(kp, vp)
        self._warmed = True
        self.warm_signatures = self.guard.n_signatures
        return time.perf_counter() - t0

    def retraces_since_warmup(self) -> int:
        """Distinct signatures compiled after warmup — must stay 0 in
        steady state across any join/leave churn (the zero-retrace
        proof for the decode loop)."""
        return self.guard.n_signatures - self.warm_signatures

    # -- request intake ------------------------------------------------
    def generate_cost(self, prompt_len: int, max_tokens: int = 0
                      ) -> int:
        """Admission cost of a generate request: the KV blocks its
        prompt occupies (token-cost admission — a long prompt spends
        the AIMD budget many short ones would)."""
        return self.pool.blocks_for(int(prompt_len) + int(max_tokens))

    def submit(self, prompt, max_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0,
               deadline: Optional[float] = None,
               ctx=None) -> TokenStream:
        """Enqueue a generate request. Allocates the prompt's KV
        blocks synchronously — :class:`~deeplearning4j_tpu.serving.
        kvcache.PoolExhausted` (HTTP 429 upstream) raises HERE, before
        the caller starts streaming. Returns the token stream. ``ctx``
        (the request's TraceContext) rides the pending entry so the
        engine thread can attribute queue/device phases and per-token
        instants back onto the request timeline."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must carry at least one token")
        if prompt.size >= self.max_seq_len:
            raise ValueError(
                f"prompt of {prompt.size} tokens >= max_seq_len "
                f"{self.max_seq_len}")
        if deadline is not None and time.monotonic() >= deadline:
            raise DeadlineExceeded(
                "deadline already expired at generate submit")
        max_tokens = int(min(max_tokens,
                             self.max_seq_len - prompt.size))
        seq_id = next(self._seq_ids)
        # reserve the prompt's blocks NOW: exhaustion is a synchronous
        # shed, not a mid-stream surprise
        self.pool.alloc(seq_id, int(prompt.size))
        stream = TokenStream(seq_id, int(prompt.size))
        with self._lock:
            self._ensure_worker()
            self._pending.put((seq_id, prompt, max_tokens,
                               float(temperature), int(top_k),
                               deadline, stream, time.monotonic(),
                               ctx))
        self._work.set()
        return stream

    def _ensure_worker(self):
        if self._worker is not None:
            return
        prev, self._draining = self._draining, None
        if prev is not None:
            # the old worker drains _pending/_live single-threaded;
            # it never takes this lock, so waiting here cannot deadlock
            prev.join()
        self._shutdown = False
        # caller (submit) holds self._lock: worker startup and the
        # queue insertion that wakes it stay atomic
        # dl4j-lint: disable=lock-discipline
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name=f"dl4j-generate-"
                                             f"{self.name}")
        self._worker.start()

    def shutdown(self, timeout: float = 30.0):
        """Stop the engine worker after it drains every admitted and
        pending sequence (bounded by ``timeout``). A concurrent submit
        either reaches the old worker's drain, or sees ``_worker``
        None and starts a fresh one — it can no longer enqueue onto a
        joined worker and strand its stream."""
        with self._lock:
            self._shutdown = True
            w, self._worker = self._worker, None
            if w is not None:
                self._draining = w
        self._work.set()
        if w is not None:
            w.join(timeout)

    # -- the continuous loop -------------------------------------------
    def _loop(self):
        me = threading.current_thread()
        while True:
            # Clear BEFORE draining: a submit that lands after the
            # drain re-sets the event, so the wait below returns
            # immediately instead of losing the wake-up.
            self._work.clear()
            admitted = self._admit_pending()
            stepped = self._decode_iteration()
            if admitted or stepped:
                continue
            # Idle — and only exit on shutdown/supersession while
            # idle: every pending request was admitted and every
            # admitted sequence retired, so no stream is stranded.
            if self._shutdown or self._worker is not me:
                return
            # Block until a submit wakes us (bounded so queued
            # deadline/cancel checks still tick over).
            self._work.wait(0.05)

    def _admit_pending(self) -> bool:
        """Prefill every queued request (each its own bucket-padded
        pass), then join it to the decode batch."""
        admitted = False
        while True:
            try:
                item = self._pending.get_nowait()
            except _queue.Empty:
                return admitted
            admitted = True
            (seq_id, prompt, max_tokens, temperature, top_k, deadline,
             stream, t_submit, ctx) = item
            if stream.cancelled or (deadline is not None
                                    and time.monotonic() >= deadline):
                reason = "cancelled" if stream.cancelled else "deadline"
                self.pool.free(seq_id)
                if ctx is not None:
                    ctx.phase_at("queue", t_submit, time.monotonic())
                self._finish(stream, reason)
                continue
            try:
                self._prefill_one(seq_id, prompt, max_tokens,
                                  temperature, top_k, deadline, stream,
                                  t_submit, ctx)
            except BaseException as e:      # noqa: BLE001
                self.pool.free(seq_id)
                self._finish(stream, "error", e)

    def _prompt_bucket(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        return n                    # oversized prompt: cold compile

    def _prefill_one(self, seq_id, prompt, max_tokens, temperature,
                     top_k, deadline, stream, t_submit, ctx=None):
        import jax

        from deeplearning4j_tpu.ops.sampling import sample_logits
        t_prefill = time.monotonic()
        if ctx is not None:
            # engine-side queue phase: submit -> prefill start
            ctx.phase_at("queue", t_submit, t_prefill)
        t = self._prompt_bucket(prompt.size)
        tokens = np.zeros((1, t), np.int32)
        tokens[0, :prompt.size] = prompt
        length = np.asarray([prompt.size], np.int32)
        self._record(tokens, length)
        with telemetry.span("generate.prefill", model=self.name,
                            tokens=int(prompt.size)):
            last, k, v = self._prefill_jit()(self.params, tokens,
                                             length)
            # scatter the prompt's K/V into its pool blocks (padded
            # positions land in scratch block 0)
            table = self.pool.table(seq_id)
            idx = np.arange(t)
            slots = np.where(
                idx < prompt.size,
                np.asarray(table, np.int64)[
                    np.minimum(idx // self.pool.block_size,
                               len(table) - 1)]
                * self.pool.block_size + idx % self.pool.block_size,
                0).astype(np.int32)
            self._record(k, slots)
            kp, vp = self._commit_jit()(self.pool.k, self.pool.v, k, v,
                                        slots)
            self.pool.update_arrays(kp, vp)
            self._step += 1
            key = jax.random.fold_in(self._rng, self._step)
            first = int(np.asarray(self._sample_jit()(
                last, key,
                np.asarray([temperature], np.float32),
                np.asarray([top_k], np.int32)))[0])
        now = time.monotonic()
        _ttft_hist().observe(now - t_submit, model=self.name)
        if ctx is not None:
            # the prefill forward + commit + first-token sample is
            # this request's device phase (decode steps are shared
            # across the live batch, attributed as instants instead)
            ctx.phase_at("device", t_prefill, now)
            ctx.note(kv_blocks=len(self.pool.table(seq_id)),
                     prompt_tokens=int(prompt.size))
        stream._put(first)
        _tokens_counter().inc(model=self.name)
        eos = self.model.conf.eos_id
        if first == eos or max_tokens <= 1:
            self.pool.free(seq_id)
            self._finish(stream,
                         "eos" if first == eos else "max_tokens")
            return
        self._live[seq_id] = _Sequence(
            seq_id, stream, first, int(prompt.size), max_tokens,
            temperature, top_k, deadline, now, ctx)
        _live_gauge().set(len(self._live), model=self.name)

    def _decode_bucket(self, n: int) -> int:
        for b in self.decode_buckets:
            if n <= b:
                return b
        return self.decode_buckets[-1]

    def _retire(self, seq: _Sequence, reason: str,
                error: Optional[BaseException] = None) -> None:
        self._live.pop(seq.seq_id, None)
        freed = self.pool.free(seq.seq_id)
        del freed
        self._finish(seq.stream, reason, error)
        _live_gauge().set(len(self._live), model=self.name)

    def _finish(self, stream: TokenStream, reason: str,
                error: Optional[BaseException] = None) -> None:
        stream._close(reason, error)
        if reason == "cancelled":
            _disconnects_counter().inc(model=self.name)
        _requests_counter().inc(model=self.name, outcome=reason)

    def _decode_iteration(self) -> bool:
        """ONE fused step over all live sequences (the iteration of
        iteration-level scheduling). Returns False when idle."""
        import jax
        if not self._live:
            return False
        now = time.monotonic()
        # pre-step retirement: cancelled / deadline sequences leave
        # and their blocks free before we spend device time
        for seq in list(self._live.values()):
            if seq.stream.cancelled:
                self._retire(seq, "cancelled")
            elif seq.deadline is not None and now >= seq.deadline:
                self._retire(seq, "deadline")
        if not self._live:
            return True
        # grow every sequence by one token slot; a pool with no free
        # block sheds THAT sequence mid-batch, the rest keep decoding
        from deeplearning4j_tpu.serving.kvcache import PoolExhausted
        for seq in list(self._live.values()):
            try:
                self.pool.extend(seq.seq_id, 1)
            except PoolExhausted as e:
                self._retire(seq, "kv_pool", e)
        if not self._live:
            return True
        seqs = list(self._live.values())[:self.decode_buckets[-1]]
        b = self._decode_bucket(len(seqs))
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        tables = np.zeros((b, self.max_blocks), np.int32)
        temps = np.zeros((b,), np.float32)
        topks = np.zeros((b,), np.int32)
        for i, seq in enumerate(seqs):
            tokens[i] = seq.next_token
            positions[i] = seq.position
            tables[i] = self.pool.padded_table(seq.seq_id,
                                               self.max_blocks)
            temps[i] = seq.temperature
            topks[i] = seq.top_k
        self._record(tokens, positions, tables, temps, topks)
        self._step += 1
        key = jax.random.fold_in(self._rng, self._step)
        t0 = time.perf_counter()
        with telemetry.span("generate.decode_step", model=self.name,
                            live=len(seqs), bucket=b):
            ids, kp, vp = self._decode_jit()(
                self.params, self.pool.k, self.pool.v, tokens,
                positions, tables, key, temps, topks)
            ids = np.asarray(ids)
        self.pool.update_arrays(kp, vp)
        if telemetry.enabled():
            telemetry.histogram(
                "dl4j_generate_decode_step_seconds",
                "wall time of one fused decode iteration over the "
                "live batch (gather + paged attention + sample + "
                "append), per model (seconds)").observe(
                    time.perf_counter() - t0, model=self.name)
            telemetry.histogram(
                "dl4j_serving_batch_occupancy",
                "live rows / bucket-padded rows per serving flush — "
                "how full the warm buckets actually run (1.0 = no "
                "padding waste; continuous batching should push this "
                "up under load)",
                buckets=telemetry.RATIO_BUCKETS).observe(
                    len(seqs) / max(1, b), model=self.name,
                    policy="decode")
        now = time.monotonic()
        eos = self.model.conf.eos_id
        for i, seq in enumerate(seqs):
            tok = int(ids[i])
            seq.stream._put(tok)
            _tokens_counter().inc(model=self.name)
            if seq.ctx is not None:
                seq.ctx.instant(
                    "inter_token", index=seq.generated,
                    gap_ms=round((now - seq.t_last) * 1e3, 3))
            _intertoken_hist().observe(now - seq.t_last,
                                       model=self.name)
            seq.t_last = now
            seq.position += 1
            seq.next_token = tok
            seq.generated += 1
            if tok == eos:
                self._retire(seq, "eos")
            elif seq.generated >= seq.max_tokens:
                self._retire(seq, "max_tokens")
        return True

    def _record(self, *arrays) -> None:
        hit = self.guard.record(*arrays)
        if self._warmed and not hit:
            telemetry.counter(
                "dl4j_serving_bucket_miss_total",
                "post-warmup flushes whose padded signature no warm "
                "bucket covered — a cold XLA compile on the serving "
                "path (shape/dtype drift, or grow the bucket set)"
            ).inc(model=self.name)
