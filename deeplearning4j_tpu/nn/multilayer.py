"""MultiLayerNetwork: a sequential layer stack compiled to one jitted step.

Reference parity: ``org.deeplearning4j.nn.multilayer.MultiLayerNetwork``
(SURVEY.md D2, call stack section 3.1) — ``init/fit/output/score/evaluate``
with listeners, per-layer updaters, gradient normalization, l1/l2.

TPU-first mapping of the reference's fit() loop (section 3.1):
- fwd/bwd/updater orchestration per minibatch -> ONE ``jax.jit`` function
  (value_and_grad over the whole stack + pure updater transforms), traced
  once per input signature, buffers donated so XLA reuses them
  (donation replaces the reference's workspace machinery D8/J6);
- the flattened param/gradient views -> params stay a pytree; flattening
  exists only as a serialization order (utils.ModelSerializer);
- cuDNN helper dispatch -> nothing: layers lower to XLA ops directly.
"""
from __future__ import annotations

import logging
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common import layerprof
from deeplearning4j_tpu.common.dtypes import to_jnp_dtype
from deeplearning4j_tpu.nn.conf.constraints import apply_constraints
from deeplearning4j_tpu.nn.conf.builders import (BackpropType,
                                                 MultiLayerConfiguration)
from deeplearning4j_tpu.nn.conf.layers import BaseOutputLayer
from deeplearning4j_tpu.nn.gradient import apply_gradient_normalization
from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger("deeplearning4j_tpu")


def _as_jnp(x, dtype=None):
    from deeplearning4j_tpu.ndarray.ndarray import INDArray
    if isinstance(x, INDArray):
        x = x.data
    arr = jnp.asarray(x)
    if dtype is not None and jnp.issubdtype(arr.dtype, jnp.floating):
        arr = arr.astype(dtype)
    return arr


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.params: dict = {}
        self.states: dict = {}
        self.updater_states: dict = {}
        self.listeners: List[TrainingListener] = []
        self.iteration_count = 0
        self.epoch_count = 0
        self.last_batch_size = 0
        self._score = float("nan")
        self._rng = jax.random.PRNGKey(conf.seed)
        self._train_step = None
        self._step_gnorm = False    # step emits a real grad norm
        self._initialized = False
        self._dtype = to_jnp_dtype(conf.dtype)
        self._retrace_guard = None
        # ZeRO-1 sharded update (parallel.zero): when a dp mesh is
        # installed the step tail runs the updater on 1/N param shards
        self._dp_mesh = None
        self._dp_axis = "data"
        # full FSDP / ZeRO-3 (parallel.zero): params live as 1/N flat
        # shards ({FSDP_KEY: {dtype: flat}} per layer), gathered
        # per-layer just-in-time in the forward; _fsdp_specs keeps the
        # per-layer DpFlatSpec needed to densify
        self._dp_fsdp = False
        self._fsdp_specs = {}
        # dense update tail WITH a mesh installed (dense x tp 2D mode:
        # the step needs the mesh for tp pins but must not run ZeRO-1)
        self._dp_dense = False
        # encoded update exchange (parallel.encoding): the ZeRO-1 tail
        # with the flat gradient compressed before the data-axis
        # collective; _dp_encoding holds the static EncodingSpec
        self._dp_encoded = False
        self._dp_encoding = None
        # tensor parallelism (parallel.speclayout): per-layer
        # {name: TpLeafSpec} for model-axis sharded leaves
        self._tp_model_axis = None
        self._tp_specs = {}
        # gradient accumulation (reference: GradientsAccumulator)
        self._accum_steps = 1
        self._accum_grads = None
        self._accum_count = 0
        self._updates_applied = 0

    # ------------------------------------------------------------------
    def init(self) -> "MultiLayerNetwork":
        if self._initialized:
            return self
        conf = self.conf
        conf.resolve_shapes()
        key = jax.random.PRNGKey(conf.seed)
        cur = conf.input_type
        for i, layer in enumerate(conf.layers):
            if i in conf.input_preprocessors and cur is not None:
                cur = conf.input_preprocessors[i].get_output_type(cur)
            key, sub = jax.random.split(key)
            self.params[f"layer_{i}"] = layer.init_params(
                sub, cur, self._dtype) if layer.has_params() else {}
            self.states[f"layer_{i}"] = layer.init_state(
                cur, self._dtype) if layer.has_state() else {}
            if cur is not None:
                cur = layer.get_output_type(cur)
        for i, layer in enumerate(conf.layers):
            up = layer.updater or conf.updater
            self.updater_states[f"layer_{i}"] = up.init_state(
                self.params[f"layer_{i}"])
        self._initialized = True
        return self

    # ------------------------------------------------------------------
    def set_listeners(self, *listeners: TrainingListener):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners: TrainingListener):
        self.listeners.extend(listeners)
        return self

    # ------------------------------------------------------------------
    @property
    def output_layer_conf(self) -> BaseOutputLayer:
        last = self.conf.layers[-1]
        if not isinstance(last, BaseOutputLayer):
            raise ValueError("last layer is not an output layer")
        return last

    def n_layers(self) -> int:
        return len(self.conf.layers)

    # ------------------------------------------------------------------
    def _forward(self, params, states, x, *, training: bool, rng,
                 stop_at: Optional[int] = None, want_logits: bool,
                 mask=None, start_at: int = 0):
        """Walk the stack. ``mask`` is the per-timestep features mask,
        passed to layers that accept one (recurrent/pooling).
        ``start_at``/``stop_at`` bound the walk to ``[start_at,
        stop_at)`` — the pipeline-stage slice (parallel/pipeline.py);
        ``x`` is then the incoming stage activation, and per-layer RNG
        stays folded on the ABSOLUTE layer index so a sliced walk
        reproduces the whole-stack random stream.
        Returns (out, new_states)."""
        conf = self.conf
        if conf.compute_dtype:
            # mixed precision: compute in (usually) bfloat16, master
            # params stay float32; the cast transposes to a cast-back,
            # so gradients/updates remain float32 (SURVEY.md section 7
            # "bfloat16 on the MXU" design stance). States (BN running
            # stats) are NOT cast: their (1-decay)*delta updates would
            # round to zero at bf16 ulp — normalization statistics stay
            # f32, the standard mixed-precision rule.
            from deeplearning4j_tpu.common.dtypes import cast_floats
            cd = conf.compute_dtype
            # an FsdpParamView casts per-layer post-gather (gathering
            # the master dtype then casting would defeat nothing, but
            # the view must stay a view to keep gathers just-in-time)
            params = (params.cast(cd) if hasattr(params, "cast")
                      else cast_floats(params, cd))
            x = cast_floats(x, cd)
        new_states = {}
        h = x
        n = len(conf.layers)

        def run_layer(i, h, lrng):
            # layer-attribution scope (common.layerprof): every op this
            # layer traces — forward AND its autodiff transpose —
            # carries dl4j.layer_<i> in compiled-HLO metadata; both the
            # remat-segmented and the plain walk funnel through here
            with layerprof.scope(f"layer_{i}"):
                return _run_layer(i, h, lrng)

        def _run_layer(i, h, lrng):
            layer = conf.layers[i]
            if i in conf.input_preprocessors:
                h = conf.input_preprocessors[i].pre_process(h)
            lp = params.get(f"layer_{i}", {})
            ls = states.get(f"layer_{i}", {})
            if training and layer.weight_noise is not None and \
                    lrng is not None and lp:
                # reference: conf.weightnoise — params perturbed per
                # forward pass; gradients flow to the clean params
                lrng, wn_rng = jax.random.split(lrng)
                lp = layer.weight_noise.apply(lp, wn_rng)
            kw = {}
            if mask is not None and layer.accepts_mask():
                kw["mask"] = mask
            is_last = i == n - 1
            if is_last and want_logits and isinstance(layer,
                                                      BaseOutputLayer) \
                    and layer.wants_logits():
                h, ns = layer.forward_logits(lp, h, training=training,
                                             rng=lrng, state=ls or None)
            else:
                h, ns = layer.forward(lp, h, training=training, rng=lrng,
                                      state=ls or None, **kw)
            return h, ns if ns is not None else {}

        if training and stop_at is None and start_at == 0 and \
                conf.remat_segments > 1 and n > 1:
            # sqrt(N) checkpointing: only segment-boundary activations
            # are stored for backward; interiors are recomputed.
            # Per-layer RNG is fold_in(rng, layer index) — the SAME
            # derivation as the plain path below, so toggling
            # remat_segments does not change the dropout/weight-noise
            # stream (it used to: pre-split here vs sequential split
            # there)
            from deeplearning4j_tpu.common.remat import segment_plan
            keys = ([jax.random.fold_in(rng, j) for j in range(n)]
                    if rng is not None else [None] * n)

            def make_seg(lo, hi):
                def seg_fn(h, seg_keys):
                    ns = {}
                    for j in range(lo, hi):
                        h, s = run_layer(j, h, seg_keys[j - lo])
                        ns[f"layer_{j}"] = s
                    return h, ns
                return seg_fn

            for lo, hi, wrap in segment_plan(n, conf.remat_segments):
                seg_fn = make_seg(lo, hi)
                if wrap:
                    seg_fn = jax.checkpoint(seg_fn)
                h, ns = seg_fn(h, list(keys[lo:hi]))
                new_states.update(ns)
        else:
            for i in range(start_at, n):
                if stop_at is not None and i >= stop_at:
                    break
                # fold_in(rng, layer index), matching the segmented
                # path: the random stream is a function of the layer,
                # not of how the walk is segmented
                lrng = (jax.random.fold_in(rng, i)
                        if rng is not None else None)
                h, ns = run_layer(i, h, lrng)
                new_states[f"layer_{i}"] = ns
        if conf.compute_dtype:
            from deeplearning4j_tpu.common.dtypes import cast_floats
            h = cast_floats(h, self._dtype)          # f32 loss/output
            new_states = cast_floats(new_states, self._dtype)
        return h, new_states

    def _recurrent_keys(self):
        return [f"layer_{i}" for i, l in enumerate(self.conf.layers)
                if l.is_recurrent()]

    def _with_zero_rnn_states(self, states, batch: int):
        """states for a fresh sequence: persistent (BN) entries kept,
        recurrent entries zeroed for this batch size."""
        out = dict(states)
        for i, layer in enumerate(self.conf.layers):
            if layer.is_recurrent():
                out[f"layer_{i}"] = layer.zero_state(batch, self._dtype)
        return out

    def _strip_rnn_states(self, states):
        out = dict(states)
        for k in self._recurrent_keys():
            out[k] = {}
        return out

    def _regularization(self, params):
        """Score-side l1/l2 (reference: applied to weights, not biases)."""
        reg = 0.0
        for i, layer in enumerate(self.conf.layers):
            if getattr(layer, "is_frozen", lambda: False)():
                # regularizing frozen weights would un-freeze them:
                # the l1/l2 gradient bypasses forward's stop_gradient
                continue
            l1 = layer.l1 or 0.0
            l2 = layer.l2 or 0.0
            if l1 == 0.0 and l2 == 0.0:
                continue
            for name, p in params.get(f"layer_{i}", {}).items():
                if name not in ("W",):   # weights only, like the reference
                    continue
                if l1:
                    reg = reg + l1 * jnp.sum(jnp.abs(p))
                if l2:
                    reg = reg + 0.5 * l2 * jnp.sum(p * p)
        return reg

    # ------------------------------------------------------------------
    def _build_train_step(self):
        from deeplearning4j_tpu.common.compilecache import \
            enable_persistent_cache
        enable_persistent_cache()    # second process loads, not compiles
        conf = self.conf
        out_layer = self.output_layer_conf
        want_logits = out_layer.wants_logits()
        updaters = [(layer.updater or conf.updater)
                    for layer in conf.layers]

        gn = conf.gradient_normalization
        thr = conf.gradient_normalization_threshold
        dp_mesh, dp_axis = self._dp_mesh, self._dp_axis
        fsdp = self._dp_fsdp and dp_mesh is not None
        dense_tail = self._dp_dense and dp_mesh is not None
        encoded = self._dp_encoded and dp_mesh is not None
        encoding = self._dp_encoding if encoded else None
        tp_specs_all = (dict(self._tp_specs)
                        if dp_mesh is not None and self._tp_specs else {})
        if fsdp:
            from deeplearning4j_tpu.common.environment import Environment
            from deeplearning4j_tpu.parallel.zero import FsdpParamView
            fsdp_specs = dict(self._fsdp_specs)
            fsdp_prefetch = Environment.get().fsdp_prefetch
            layer_order = [f"layer_{i}" for i in range(len(conf.layers))]

        def loss_fn(params, states, x, y, fmask, lmask, rng):
            # fmask: per-timestep features mask (recurrent/pooling hold);
            # lmask: labels mask (loss exclusion) — distinct, as in the
            # reference (featuresMaskArray vs labelsMaskArray)
            if fsdp:
                # lazy view over the 1/N flat shards: each layer's
                # all-gather is emitted at its point of use in the walk
                params = FsdpParamView(params, fsdp_specs, dp_mesh,
                                       dp_axis, order=layer_order,
                                       prefetch=fsdp_prefetch,
                                       tp_specs=tp_specs_all)
            elif tp_specs_all:
                # 2D mode: pin tp leaves to their compute spec; the
                # custom-vjp pin sends the cotangent to the resident
                # spec, so dp grad collectives stay on the data axis
                from deeplearning4j_tpu.parallel.zero import pin_tp_entry
                params = {k: (pin_tp_entry(sub, dp_mesh,
                                           tp_specs_all[k])
                              if k in tp_specs_all and
                              isinstance(sub, dict) else sub)
                          for k, sub in params.items()}
            out, new_states = self._forward(params, states, x,
                                            training=True, rng=rng,
                                            want_logits=True, mask=fmask)
            # attribution scope: loss + regularization are real step
            # work but belong to no layer — name them instead of
            # letting them fall into the _unattributed bucket
            with layerprof.scope("loss"):
                data_loss = out_layer.compute_loss(
                    y, out, from_logits=want_logits, mask=lmask)
                return (data_loss + self._regularization(params),
                        new_states)

        # numerics watchdog (common.diagnostics): when armed, the step
        # also emits the global grad norm — computed in-jit, fused into
        # the backward, so the host check is one extra scalar read.
        # When off it is a free zeros constant and XLA dead-code
        # eliminates the reduction; the step keeps ONE output shape.
        from deeplearning4j_tpu.common.diagnostics import watchdog_enabled
        want_gnorm = watchdog_enabled()
        self._step_gnorm = want_gnorm

        def grad_norm(grads):
            if not want_gnorm:
                return jnp.zeros((), jnp.float32)
            sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
                  for g in jax.tree_util.tree_leaves(grads)]
            return jnp.sqrt(sum(sq)) if sq else jnp.zeros((),
                                                          jnp.float32)

        def update_tail(params, upd_states, grads, iteration):
            """Grads -> (new_params, new_upd). Shared by the fused step
            and the accumulation apply step. With a dp mesh installed
            (and not dense×tp) the updater runs ZeRO-1 sharded
            (parallel.zero; the resolver guarantees
            gradient_normalization NONE there, so skipping it is
            exact). Under fsdp params/grads are already the 1/N flat
            shards and stay that way — no trailing all-gather
            (constraints skipped: the resolver refuses fsdp when any
            layer has them). Tensor-parallel leaves (tp_specs) never
            enter the dp flats: they get their own elementwise tail
            (apply_update_tp) pinned to the model-axis layout. Under
            the encoded rung the same structure swaps in
            apply_update_encoded — flat gradient compressed (with
            error-feedback residual carried in ENCODED_KEY state)
            before the data-axis collective; tp leaves keep their
            uncompressed elementwise tail."""
            new_params, new_upd = {}, {}
            for i, up in enumerate(updaters):
                k = f"layer_{i}"
                g = grads.get(k, {})
                if not g:
                    new_params[k] = params.get(k, {})
                    new_upd[k] = upd_states.get(k, ())
                    continue
                tps = tp_specs_all.get(k)
                if fsdp:
                    from deeplearning4j_tpu.learning.updaters import \
                        FSDP_KEY, TP_KEY
                    from deeplearning4j_tpu.parallel.zero import (
                        apply_update_fsdp, apply_update_tp,
                        merge_tp_state, split_tp_state)
                    st_rest, st_tp = split_tp_state(upd_states[k])
                    new_flat, us = apply_update_fsdp(
                        up, g[FSDP_KEY], params[k][FSDP_KEY],
                        st_rest, iteration, dp_mesh, dp_axis)
                    ent = {FSDP_KEY: new_flat}
                    if tps and TP_KEY in g:
                        new_tp, us_tp = apply_update_tp(
                            up, g[TP_KEY], params[k][TP_KEY], st_tp,
                            iteration, dp_mesh, tps,
                            gather_params=False)
                        ent[TP_KEY] = new_tp
                        us = merge_tp_state(us, us_tp)
                    new_params[k] = ent
                    new_upd[k] = us
                    continue
                if dp_mesh is not None and not dense_tail:
                    import functools as _ft

                    from deeplearning4j_tpu.parallel.zero import (
                        apply_update_encoded, apply_update_sharded,
                        apply_update_tp, merge_tp_state,
                        split_tp_entry, split_tp_state)
                    apply_dp = (_ft.partial(apply_update_encoded,
                                            encoding=encoding)
                                if encoded else apply_update_sharded)
                    if tps:
                        g_rest, g_tp = split_tp_entry(g, tps)
                        p_rest, p_tp = split_tp_entry(params[k], tps)
                        st_rest, st_tp = split_tp_state(upd_states[k])
                        if g_rest:
                            new_rest, us = apply_dp(
                                up, g_rest, p_rest, st_rest,
                                iteration, dp_mesh, dp_axis)
                        else:
                            new_rest, us = p_rest, st_rest
                        new_tp, us_tp = apply_update_tp(
                            up, g_tp, p_tp, st_tp, iteration,
                            dp_mesh, tps, gather_params=True)
                        new_p = {**new_rest, **new_tp}
                        us = merge_tp_state(us, us_tp)
                    else:
                        new_p, us = apply_dp(
                            up, g, params[k], upd_states[k], iteration,
                            dp_mesh, dp_axis)
                else:
                    g = apply_gradient_normalization(gn, thr, g)
                    updates, us = up.apply(g, upd_states[k], iteration)
                    new_p = jax.tree_util.tree_map(
                        lambda p, u: p - u, params[k], updates)
                # post-update projection (reference: constraints are
                # applied after the updater, inside the same step)
                new_params[k] = apply_constraints(conf.layers[i], new_p)
                new_upd[k] = us
            return new_params, new_upd

        def step(params, states, upd_states, x, y, fmask, lmask,
                 iteration, rng):
            (loss, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, states, x, y, fmask,
                                       lmask, rng)
            gnorm = grad_norm(grads)
            # attribution scope: the updater sweep reads/writes every
            # parameter — substantial byte traffic that is not any
            # layer's compute
            with layerprof.scope("optimizer"):
                new_params, new_upd = update_tail(params, upd_states,
                                                  grads, iteration)
            return new_params, new_states, new_upd, loss, gnorm

        def grad_step(params, states, x, y, fmask, lmask, rng):
            # accumulation micro-step: backward only, no update (params
            # NOT donated — the apply step still reads them)
            (loss, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, states, x, y, fmask,
                                       lmask, rng)
            return grads, new_states, loss, grad_norm(grads)

        def apply_step(params, upd_states, grads, scale, iteration):
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            with layerprof.scope("optimizer"):
                new_params, new_upd = update_tail(params, upd_states,
                                                  grads, iteration)
            return new_params, new_upd

        # donate params/states/updater-state buffers: XLA reuses them
        # in place of the reference's workspaces
        self._step_fn = step        # unjitted (multi-step path reuses)
        self._train_step = jax.jit(step, donate_argnums=(0, 1, 2))
        self._grad_step = jax.jit(grad_step, donate_argnums=(1,))
        self._apply_step = jax.jit(apply_step, donate_argnums=(1, 2))
        self._accum_add = jax.jit(
            lambda acc, g: jax.tree_util.tree_map(
                lambda a, b: a + b, acc, g),
            donate_argnums=(0,))

    # ------------------------------------------------------------------
    def set_dp_mesh(self, mesh, axis: str = "data", mode=None, *,
                    model_axis=None, tp_specs=None, encoding=None):
        """Install (or clear, with ``mesh=None``) the (possibly 2D)
        mesh the jitted step tail specializes on (``parallel.zero``).
        ``mode="fsdp"`` selects the ZeRO-3 tail: params convert to the
        1/N flat resident layout here (the model owns both param and
        updater-state conversion under fsdp); ``mode="dense"`` installs
        the mesh WITHOUT the ZeRO-1 tail (dense×tp: the step needs the
        mesh for tensor-parallel pins only); ``mode="encoded"`` selects
        the compressed-collective tail (``encoding=`` takes an
        ``EncodingSpec`` or scheme string; the ENCODED_KEY
        error-feedback state is injected at the next layout sync); for
        the ZeRO-1 tail callers still own converting/placing
        ``updater_states``. ``model_axis``/``tp_specs``
        (``parallel.speclayout``) add the tensor-parallel dimension:
        spec'd leaves pin to the model axis in-step and never enter
        the dp flats. Invalidates compiled steps."""
        mode_s = str(getattr(mode, "value", mode) or "").lower()
        fsdp = mode_s == "fsdp" and mesh is not None
        dense = mode_s == "dense" and mesh is not None
        encoded = mode_s == "encoded" and mesh is not None
        if encoded:
            from deeplearning4j_tpu.parallel.encoding import \
                resolve_encoding
            encoding = resolve_encoding(encoding)
        else:
            encoding = None
        tp_specs = dict(tp_specs or {}) if mesh is not None else {}
        model_axis = model_axis if tp_specs else None
        if mesh is self._dp_mesh and axis == self._dp_axis and \
                fsdp == self._dp_fsdp and dense == self._dp_dense and \
                encoded == self._dp_encoded and \
                encoding == self._dp_encoding and \
                model_axis == self._tp_model_axis and \
                tp_specs == self._tp_specs:
            return self
        self.flush_accumulated()
        self._dp_mesh = mesh
        self._dp_axis = axis
        self._dp_fsdp = fsdp
        self._dp_dense = dense
        self._dp_encoded = encoded
        self._dp_encoding = encoding
        self._tp_model_axis = model_axis
        self._tp_specs = tp_specs
        self._train_step = None
        self._step_fn = None
        self._grad_step = None
        self._apply_step = None
        self._accum_add = None
        if hasattr(self, "_multi_steps"):
            del self._multi_steps
        self._sync_param_layout()
        return self

    def set_accumulation_steps(self, n: int):
        """Apply the updater once every ``n`` fit() micro-batches on the
        mean of their gradients (the reference's GradientsAccumulator):
        effective batch = n x micro-batch with no extra activation HBM."""
        n = max(int(n), 1)
        if n != self._accum_steps:
            self.flush_accumulated()
            self._accum_steps = n
        return self

    def flush_accumulated(self):
        """Apply a partial accumulation window now (epoch end / mode
        change); no-op when nothing is pending."""
        if self._accum_count:
            self._apply_accumulated()
        return self

    def _apply_accumulated(self):
        k = self._accum_count
        scale = jnp.asarray(1.0 / k, jnp.float32)
        self.params, self.updater_states = self._apply_step(
            self.params, self.updater_states, self._accum_grads, scale,
            jnp.asarray(self._updates_applied))
        self._accum_grads = None
        self._accum_count = 0
        self._updates_applied += 1

    def _sync_updater_layout(self):
        """A checkpoint restored from a ZeRO-1 run carries flat sharded
        updater state; on a plain (no-mesh) model — or under the
        dense×tp tail, which consumes dense state — convert it back to
        the dense per-layer layout before stepping (ENCODED_KEY
        error-feedback state is stripped there: the residual belongs
        to the compressed exchange). Under ``mode="encoded"`` the
        inverse sync runs: entries missing their ENCODED_KEY state
        (first fit, or a dense/sharded checkpoint restored into an
        encoded run — on any device count) get it injected and placed."""
        if self._dp_mesh is not None and not self._dp_dense:
            if self._dp_encoded:
                from deeplearning4j_tpu.parallel.zero import (
                    ensure_encoded_states, place_updater_states)
                n = self._dp_mesh.shape[self._dp_axis]
                states = self.updater_states
                new = ensure_encoded_states(
                    self.dense_params() if self._params_are_fsdp()
                    else self.params,
                    states, n, self._dp_encoding,
                    tp_specs=self._tp_specs)
                if any(new[k] is not states.get(k) for k in new):
                    self.updater_states = place_updater_states(
                        self._dp_mesh, new, self._dp_axis,
                        tp_specs=self._tp_specs)
            return
        from deeplearning4j_tpu.learning.updaters import (has_tp,
                                                          is_dp_sharded,
                                                          is_encoded)
        if any(is_dp_sharded(s) or has_tp(s) or is_encoded(s)
               for s in self.updater_states.values()):
            from deeplearning4j_tpu.parallel.zero import (
                states_to_dense, strip_encoded_states)
            self.updater_states = strip_encoded_states(
                states_to_dense(self.params, self.updater_states))

    def _params_are_fsdp(self) -> bool:
        from deeplearning4j_tpu.learning.updaters import is_fsdp
        return any(is_fsdp(p) for p in self.params.values()
                   if isinstance(p, dict))

    def _sync_param_layout(self):
        """Enter/leave the fsdp flat resident param layout
        (parallel.zero). Entering converts updater state to the ZeRO-1
        flat layout too (the fsdp tail consumes it) and places both at
        1/N per replica; leaving densifies params (gather timed into
        ``dl4j_fsdp_gather_seconds``).  Elastic re-mesh: flats resident
        for a DIFFERENT world size (resume onto a new mesh) round-trip
        through the dense layout and re-enter — params via
        ``params_to_dense`` -> ``place_fsdp_params``, updater state via
        its ``DpFlatSpec`` re-ravel inside ``states_to_sharded``."""
        flat = self._params_are_fsdp()
        if self._dp_fsdp and self._dp_mesh is not None:
            from deeplearning4j_tpu.parallel.zero import (
                fsdp_spec_shards, params_to_fsdp, place_fsdp_params,
                place_updater_states, states_to_sharded)
            n = self._dp_mesh.shape[self._dp_axis]
            if flat:
                if fsdp_spec_shards(self._fsdp_specs) == n and \
                        self._tp_layout_matches():
                    # already resident; placement happened on entry
                    return
                # raveled for another world size (or another tp
                # partition): densify and re-enter
                self._densify_params_inplace()
            self.updater_states = states_to_sharded(
                self.params, self.updater_states, n,
                tp_specs=self._tp_specs)
            self.params, self._fsdp_specs = params_to_fsdp(
                self.params, n, tp_specs=self._tp_specs)
            self.params = place_fsdp_params(self._dp_mesh, self.params,
                                            self._dp_axis,
                                            tp_specs=self._tp_specs)
            self.updater_states = place_updater_states(
                self._dp_mesh, self.updater_states, self._dp_axis,
                tp_specs=self._tp_specs)
        elif flat:
            self._densify_params_inplace()

    def _tp_layout_matches(self) -> bool:
        """True when the resident fsdp entries' TP_KEY split matches
        the installed tp specs (an fsdp×tp checkpoint restored onto a
        mesh with a different tp degree must densify and re-enter)."""
        from deeplearning4j_tpu.learning.updaters import TP_KEY, is_fsdp
        want = {k: set(v) for k, v in (self._tp_specs or {}).items()}
        for k, sub in self.params.items():
            if not isinstance(sub, dict) or not is_fsdp(sub):
                continue
            got = set(sub.get(TP_KEY, {}))
            if got != want.get(k, set()):
                return False
        return True

    def _densify_params_inplace(self):
        if self._params_are_fsdp():
            from deeplearning4j_tpu.parallel.zero import (on_2d_mesh,
                                                          params_to_dense)
            self.params = params_to_dense(self.params, self._fsdp_specs)
            # specs kept: a later _sync_param_layout re-entry recomputes
            if any(on_2d_mesh(a)
                   for a in jax.tree_util.tree_leaves(self.params)):
                # leaving a 2D (data, model) residency: the densified
                # leaves still carry the old mesh's shardings, and
                # re-raveling them through XLA SPMD hits the same
                # concatenate-lowering bug worked around in
                # zero.apply_update_sharded — re-enter from host copies
                self.params = jax.device_get(self.params)
                self.updater_states = jax.device_get(self.updater_states)

    def dense_params(self) -> dict:
        """Params in the dense per-layer layout regardless of residency
        (non-mutating; under fsdp this is a full host-side all-gather —
        checkpoint/inference/introspection consumers only)."""
        if not self._params_are_fsdp():
            return self.params
        from deeplearning4j_tpu.parallel.zero import params_to_dense
        return params_to_dense(self.params, self._fsdp_specs)

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, *, n_epochs: int = 1):
        """fit(x, y) | fit(DataSet) | fit(iterator[, n_epochs])."""
        if not self._initialized:
            self.init()
        self._sync_updater_layout()
        self._sync_param_layout()
        if self._train_step is None:
            self._build_train_step()
        if labels is not None:
            for _ in range(n_epochs):
                self._fit_batch(data, labels, None, None)
            return self
        if hasattr(data, "features") and hasattr(data, "labels"):
            for _ in range(n_epochs):
                self._fit_batch(data.features, data.labels,
                                getattr(data, "features_mask", None),
                                getattr(data, "labels_mask", None))
            return self
        # iterator protocol: stage batches device-side ahead of the
        # step loop (no-op when DL4J_TPU_DEVICE_PREFETCH=0 or the
        # stream is not a resettable iterator)
        from deeplearning4j_tpu.datasets.prefetch import \
            maybe_device_prefetch
        data = maybe_device_prefetch(data, dtype=self._dtype)
        for _ in range(n_epochs):
            for lis in self.listeners:
                lis.on_epoch_start(self)
            if hasattr(data, "reset"):
                data.reset()
            for ds in data:
                self._fit_batch(ds.features, ds.labels,
                                getattr(ds, "features_mask", None),
                                getattr(ds, "labels_mask", None))
            # a partial accumulation window does not leak across epochs
            self.flush_accumulated()
            # epochs-completed count advances BEFORE listeners fire:
            # an epoch-end checkpoint then serializes the true count
            # (a resumed job must not retrain a finished epoch)
            self.epoch_count += 1
            for lis in self.listeners:
                lis.on_epoch_end(self)
        return self

    # ------------------------------------------------------------------
    def fit_steps(self, ds, steps: int):
        """Run ``steps`` train iterations on one device-resident batch
        in ONE jit dispatch (lax.fori_loop over the compiled step; the
        Keras steps_per_execution idea — see ComputationGraph.fit_steps).
        Masks unsupported on this fast path; listeners fire once per
        group with the final loss."""
        if not self._initialized:
            self.init()
        self._sync_updater_layout()
        self._sync_param_layout()
        if self._train_step is None:
            self._build_train_step()
        if getattr(ds, "features_mask", None) is not None or \
                getattr(ds, "labels_mask", None) is not None:
            raise ValueError(
                "fit_steps does not support masked DataSets — padded "
                "timesteps would train as real data; use fit()")
        x = _as_jnp(ds.features, self._dtype)
        y = _as_jnp(ds.labels, self._dtype)

        if not hasattr(self, "_multi_steps"):
            self._multi_steps = {}
        if steps not in self._multi_steps:
            step_fn = self._step_fn

            def multi(params, states, upd, x, y, it0, rng):
                def body(i, carry):
                    p, s, u, _, _ = carry
                    r = jax.random.fold_in(rng, i)
                    return step_fn(p, s, u, x, y, None, None, it0 + i, r)

                # loss carry must match step_fn's loss dtype (bf16 nets
                # produce a bf16 loss); grad-norm carry is f32
                zero = jnp.zeros((), self._dtype)
                gz = jnp.zeros((), jnp.float32)
                return jax.lax.fori_loop(0, steps, body,
                                         (params, states, upd, zero, gz))

            self._multi_steps[steps] = jax.jit(multi,
                                               donate_argnums=(0, 1, 2))

        states_in = self._with_zero_rnn_states(self.states,
                                               int(x.shape[0]))
        self._rng, rng = jax.random.split(self._rng)
        from deeplearning4j_tpu.common import diagnostics, telemetry
        with telemetry.step_span("MultiLayerNetwork", steps=steps) as sp:
            self.params, new_states, self.updater_states, loss, gnorm = \
                self._multi_steps[steps](self.params, states_in,
                                         self.updater_states, x, y,
                                         jnp.asarray(
                                             self.iteration_count),
                                         rng)
        self.states = self._strip_rnn_states(new_states)
        self._score = loss
        self.last_batch_size = int(x.shape[0])
        self.iteration_count += steps
        # one record per group: the final step's loss/grad norm stand
        # in for the window (the fori_loop body is opaque to the host)
        diagnostics.after_step(
            self, "MultiLayerNetwork", self.iteration_count - 1, loss,
            sp, grad_norm=gnorm if self._step_gnorm else None,
            params=self.params, steps=steps)
        for lis in self.listeners:
            lis.iteration_done(self, self.iteration_count - 1,
                               self.epoch_count)
        return self

    # ------------------------------------------------------------------
    def pretrain(self, data, *, n_epochs: int = 1):
        """Layerwise unsupervised pretraining (reference:
        MultiLayerNetwork.pretrain(DataSetIterator) — fits every
        pretrainable layer (AutoEncoder/VAE) in stack order on the
        activations of the layers below it)."""
        from deeplearning4j_tpu.nn.pretrain_util import materialize_once
        data = materialize_once(data)
        for i, layer in enumerate(self.conf.layers):
            if getattr(layer, "is_pretrainable", lambda: False)():
                self.pretrain_layer(i, data, n_epochs=n_epochs)
        return self

    def pretrain_layer(self, idx: int, data, *, n_epochs: int = 1):
        """Fit one pretrainable layer (reference: pretrainLayer(int,
        iter)). The layer's ``pretrain_loss`` + its updater compile into
        one jitted step; layers below run in inference mode."""
        if not self._initialized:
            self.init()
        self._sync_updater_layout()
        # pretrain reads/writes per-layer dense params directly; leave
        # the flat layout (a later fit() re-enters it)
        self._densify_params_inplace()
        layer = self.conf.layers[idx]
        if not getattr(layer, "is_pretrainable", lambda: False)():
            raise ValueError(f"layer {idx} is not pretrainable")
        up = layer.updater or self.conf.updater
        key = f"layer_{idx}"
        upd_state = self.updater_states[key]

        if not hasattr(self, "_pretrain_steps"):
            self._pretrain_steps = {}
        if idx not in self._pretrain_steps:
            def step(lp, below_params, states, us, x, iteration, rng):
                r_in, r_loss = jax.random.split(rng)
                h = x
                if idx > 0:
                    h, _ = self._forward(below_params, states, x,
                                         training=False, rng=r_in,
                                         stop_at=idx, want_logits=False)
                # _forward(stop_at=idx) stops before layer idx's own
                # preprocessor; apply it (auto-inserted CnnToFeedForward
                # etc.) so pretrain sees the same input as supervised fit
                if idx in self.conf.input_preprocessors:
                    h = self.conf.input_preprocessors[idx].pre_process(h)
                loss, g = jax.value_and_grad(layer.pretrain_loss)(
                    lp, h, r_loss)
                updates, new_us = up.apply(g, us, iteration)
                new_lp = jax.tree_util.tree_map(lambda p, u: p - u, lp,
                                                updates)
                new_lp = apply_constraints(layer, new_lp)
                return new_lp, new_us, loss

            self._pretrain_steps[idx] = jax.jit(step,
                                                donate_argnums=(0, 3))
        jit_step = self._pretrain_steps[idx]
        below = {f"layer_{j}": self.params[f"layer_{j}"]
                 for j in range(idx)}

        from deeplearning4j_tpu.nn.pretrain_util import (
            feature_batches, materialize_once)
        data = materialize_once(data)

        for _ in range(n_epochs):
            for x in feature_batches(data):
                x = _as_jnp(x, self._dtype)
                self._rng, rng = jax.random.split(self._rng)
                states_in = self._with_zero_rnn_states(self.states,
                                                       int(x.shape[0]))
                self.params[key], upd_state, loss = jit_step(
                    self.params[key], below, states_in, upd_state,
                    x, jnp.asarray(self.iteration_count), rng)
                self._score = loss
                self.iteration_count += 1
        self.updater_states[key] = upd_state
        return self

    def _fit_batch(self, x, y, fmask, lmask):
        x = _as_jnp(x, self._dtype)
        y = _as_jnp(y, self._dtype)
        fmask = _as_jnp(fmask) if fmask is not None else None
        lmask = _as_jnp(lmask) if lmask is not None else None
        if self._retrace_guard is None:
            from deeplearning4j_tpu.common.compilecache import RetraceGuard
            self._retrace_guard = RetraceGuard(
                f"{type(self).__name__} train step")
        self._retrace_guard.record(x, y, fmask, lmask)
        # layer_report() with no batch re-lowers at the last fit shape
        self._layerprof_shapes = ((x.shape, x.dtype), (y.shape, y.dtype))
        if self.conf.backprop_type is BackpropType.TRUNCATED_BPTT and \
                x.ndim == 3:
            return self._fit_tbptt(x, y, fmask, lmask)
        if self._accum_steps > 1:
            return self._fit_batch_accum(x, y, fmask, lmask)
        self._rng, rng = jax.random.split(self._rng)
        states_in = self._with_zero_rnn_states(self.states,
                                               int(x.shape[0]))
        from deeplearning4j_tpu.common import diagnostics, telemetry
        with telemetry.step_span("MultiLayerNetwork") as sp:
            self.params, new_states, self.updater_states, loss, gnorm = \
                self._train_step(self.params, states_in,
                                 self.updater_states, x, y, fmask, lmask,
                                 jnp.asarray(self.iteration_count), rng)
        # standard BPTT: recurrent state resets every minibatch
        # (reference: fit() clears rnn state); BN stats persist
        self.states = self._strip_rnn_states(new_states)
        self._score = loss          # device scalar; float() on read
        self.last_batch_size = int(x.shape[0])
        # grads never leave the fused step, so a trip attributes the
        # first bad leaf in the (poisoned) post-update params
        diagnostics.after_step(
            self, "MultiLayerNetwork", self.iteration_count, loss, sp,
            grad_norm=gnorm if self._step_gnorm else None,
            params=self.params)
        self.iteration_count += 1
        for lis in self.listeners:
            lis.iteration_done(self, self.iteration_count - 1,
                               self.epoch_count)

    def _fit_batch_accum(self, x, y, fmask, lmask):
        """Accumulation micro-step: backward + gradient add only; the
        updater fires once per ``_accum_steps`` window on the mean
        gradient, with the updater iteration = number of updates
        APPLIED (so Adam bias correction sees update indices, not
        micro-batch indices)."""
        self._rng, rng = jax.random.split(self._rng)
        states_in = self._with_zero_rnn_states(self.states,
                                               int(x.shape[0]))
        from deeplearning4j_tpu.common import diagnostics, telemetry
        with telemetry.step_span("MultiLayerNetwork",
                                 accumulating=self._accum_steps) as sp:
            grads, new_states, loss, gnorm = self._grad_step(
                self.params, states_in, x, y, fmask, lmask, rng)
            # watchdog check BEFORE accumulate/apply: the first
            # micro-batch's grads become _accum_grads, whose buffers
            # the apply step donates — after that the scan target is
            # gone
            diagnostics.check_numerics(
                self, "MultiLayerNetwork", self.iteration_count, loss,
                grad_norm=gnorm if self._step_gnorm else None,
                grads=grads)
            self._accum_grads = (grads if self._accum_grads is None
                                 else self._accum_add(self._accum_grads,
                                                      grads))
            self._accum_count += 1
            if self._accum_count >= self._accum_steps:
                self._apply_accumulated()
        self.states = self._strip_rnn_states(new_states)
        self._score = loss          # device scalar; float() on read
        self.last_batch_size = int(x.shape[0])
        diagnostics.record_step(
            self, "MultiLayerNetwork", self.iteration_count, loss, sp,
            grad_norm=gnorm if self._step_gnorm else None)
        self.iteration_count += 1
        for lis in self.listeners:
            lis.iteration_done(self, self.iteration_count - 1,
                               self.epoch_count)

    def _fit_tbptt(self, x, y, fmask, lmask):
        """Truncated BPTT (SURVEY.md section 5.7): the time axis splits
        into tbptt_fwd_length segments; recurrent state carries across
        segments (no gradient flow between step calls = truncation), and
        resets at the batch boundary — reference tBPTT semantics."""
        L = self.conf.tbptt_fwd_length
        T = x.shape[1]

        def seg(m, t0):
            return m[:, t0:t0 + L] if m is not None and m.ndim >= 2 else m

        from deeplearning4j_tpu.common import diagnostics
        states = self._with_zero_rnn_states(self.states, int(x.shape[0]))
        for t0 in range(0, T, L):
            seg_x = x[:, t0:t0 + L]
            seg_y = y[:, t0:t0 + L] if y.ndim >= 3 else y
            self._rng, rng = jax.random.split(self._rng)
            self.params, states, self.updater_states, loss, gnorm = \
                self._train_step(self.params, states,
                                 self.updater_states, seg_x, seg_y,
                                 seg(fmask, t0), seg(lmask, t0),
                                 jnp.asarray(self.iteration_count), rng)
            self._score = loss          # device scalar; float() on read
            diagnostics.after_step(
                self, "MultiLayerNetwork", self.iteration_count, loss,
                None, grad_norm=gnorm if self._step_gnorm else None,
                params=self.params, tbptt_segment=t0 // L)
            self.iteration_count += 1
        self.states = self._strip_rnn_states(states)
        self.last_batch_size = int(x.shape[0])
        for lis in self.listeners:
            lis.iteration_done(self, self.iteration_count - 1,
                               self.epoch_count)

    # -- stateful streaming inference (SURVEY.md section 5.7) -----------
    def rnn_time_step(self, x):
        """Feed one step (or a chunk) of a sequence, carrying hidden
        state across calls (reference: rnnTimeStep)."""
        from deeplearning4j_tpu.nn.conf.layers_recurrent import Bidirectional
        if any(isinstance(l, Bidirectional) for l in self.conf.layers):
            # reference throws too: the backward direction needs future
            # timesteps, which streaming cannot provide
            raise ValueError(
                "rnnTimeStep is not supported on networks with "
                "Bidirectional layers")
        if not self._initialized:
            self.init()
        x = _as_jnp(x, self._dtype)
        single_step = x.ndim == 2
        if single_step:
            x = x[:, None, :]
        if getattr(self, "_rnn_stream_states", None) is None:
            self._rnn_stream_states = self._with_zero_rnn_states(
                self.states, int(x.shape[0]))
            self._rnn_stream_batch = int(x.shape[0])
        elif int(x.shape[0]) != self._rnn_stream_batch:
            raise ValueError(
                f"rnnTimeStep batch size {int(x.shape[0])} != stored "
                f"state batch size {self._rnn_stream_batch}; call "
                f"rnn_clear_previous_state() first")
        out, new_states = self._forward(
            self.dense_params(), self._rnn_stream_states, x,
            training=False, rng=None, want_logits=False)
        # keep persistent (BN) states as-is; update only the rnn carries
        merged = dict(self._rnn_stream_states)
        for k in self._recurrent_keys():
            merged[k] = new_states[k]
        self._rnn_stream_states = merged
        if single_step and out.ndim == 3:
            out = out[:, -1]
        return out

    def rnn_clear_previous_state(self):
        self._rnn_stream_states = None

    def rnn_get_previous_state(self, layer_idx: int):
        if getattr(self, "_rnn_stream_states", None) is None:
            return None
        return self._rnn_stream_states.get(f"layer_{layer_idx}")

    # ------------------------------------------------------------------
    def output(self, x, train: bool = False, mask=None):
        """Inference forward pass (reference: ``output(INDArray)``)."""
        if not self._initialized:
            self.init()
        x = _as_jnp(x, self._dtype)
        mask = _as_jnp(mask) if mask is not None else None
        out, _ = self._forward(self.dense_params(), self.states, x,
                               training=train, rng=None,
                               want_logits=False, mask=mask)
        return out

    def feed_forward(self, x, train: bool = False) -> list:
        """All layer activations (reference: feedForward)."""
        if not self._initialized:
            self.init()
        x = _as_jnp(x, self._dtype)
        params = self.dense_params()
        if self.conf.compute_dtype:
            # same dtype path as fit()/output() — per-layer activations
            # must match what the trained/predicted path computes
            from deeplearning4j_tpu.common.dtypes import cast_floats
            cd = self.conf.compute_dtype
            params = cast_floats(params, cd)
            x = cast_floats(x, cd)
        acts = [x]
        h = x
        rng = None
        for i, layer in enumerate(self.conf.layers):
            if i in self.conf.input_preprocessors:
                h = self.conf.input_preprocessors[i].pre_process(h)
            h, _ = layer.forward(params.get(f"layer_{i}", {}), h,
                                 training=train, rng=rng,
                                 state=self.states.get(f"layer_{i}") or
                                 None)
            acts.append(h)
        return acts

    def predict(self, x) -> np.ndarray:
        """Argmax class predictions (reference: predict)."""
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def score(self, dataset=None) -> float:
        """Latest minibatch score, or score of a given DataSet."""
        if dataset is None:
            return float(self._score)
        x = _as_jnp(dataset.features, self._dtype)
        y = _as_jnp(dataset.labels, self._dtype)
        mask = getattr(dataset, "labels_mask", None)
        mask = _as_jnp(mask) if mask is not None else None
        out_layer = self.output_layer_conf
        want_logits = out_layer.wants_logits()
        params = self.dense_params()
        out, _ = self._forward(params, self.states, x, training=False,
                               rng=None, want_logits=True)
        loss = out_layer.compute_loss(y, out, from_logits=want_logits,
                                      mask=mask)
        return float(loss + self._regularization(params))

    # ------------------------------------------------------------------
    def evaluate(self, iterator):
        """Classification evaluation (reference: evaluate(DataSetIterator))."""
        from deeplearning4j_tpu.evaluation import Evaluation
        ev = Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features,
                              mask=getattr(ds, "features_mask", None))
            ev.eval(ds.labels, out,
                    mask=getattr(ds, "labels_mask", None))
        return ev

    def evaluate_regression(self, iterator):
        from deeplearning4j_tpu.evaluation import RegressionEvaluation
        ev = RegressionEvaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            out = self.output(ds.features)
            ev.eval(ds.labels, out,
                    mask=getattr(ds, "labels_mask", None))
        return ev

    # ------------------------------------------------------------------
    def num_params(self) -> int:
        return int(sum(np.prod(p.shape) for p in
                       jax.tree_util.tree_leaves(self.dense_params())))

    def param_table(self) -> dict:
        """{"0_W": array, ...} — reference paramTable naming."""
        out = {}
        params = self.dense_params()
        for i in range(self.n_layers()):
            for name, p in params.get(f"layer_{i}", {}).items():
                out[f"{i}_{name}"] = p
            for name, s in (self.states.get(f"layer_{i}") or {}).items():
                out[f"{i}_{name}"] = s
        return out

    def get_param(self, key: str):
        i, name = key.split("_", 1)
        return self.dense_params()[f"layer_{i}"][name]

    def set_params_from_table(self, table: dict):
        self._densify_params_inplace()
        for k, v in table.items():
            i, name = k.split("_", 1)
            lk = f"layer_{i}"
            if name in self.params.get(lk, {}):
                if isinstance(v, dict):   # wrapper sub-trees (fwd/bwd)
                    for sub, a in v.items():
                        self.params[lk][name][sub] = jnp.asarray(a)
                else:
                    self.params[lk][name] = jnp.asarray(v)
            elif name in (self.states.get(lk) or {}):
                self.states[lk][name] = jnp.asarray(v)

    def clone(self) -> "MultiLayerNetwork":
        import copy
        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        if self._initialized:
            net.init()
            net.params = jax.tree_util.tree_map(lambda a: a,
                                                self.dense_params())
            net.states = jax.tree_util.tree_map(lambda a: a, self.states)
            net.updater_states = jax.tree_util.tree_map(
                lambda a: a, self.updater_states)
        return net

    def layer_report(self, data=None, labels=None, **roofline_kw):
        """Per-layer flops/bytes/roofline attribution of the compiled
        train step (common.layerprof): lowers the jitted step at the
        given batch (or the last fitted batch's shapes), partitions
        ``cost_analysis()`` by the ``dl4j.layer_<i>`` scopes, and joins
        the kernel-select decisions recorded at trace time.  Also
        published to ``GET /api/layers`` and the ``dl4j_layer_*``
        metrics.  Lowering only — nothing executes, buffers are not
        donated."""
        if not self._initialized:
            self.init()
        self._sync_updater_layout()
        self._sync_param_layout()
        if self._train_step is None:
            self._build_train_step()
        if data is not None and hasattr(data, "features"):
            labels = data.labels
            data = data.features
        if data is None:
            shapes = getattr(self, "_layerprof_shapes", None)
            if shapes is None:
                raise ValueError(
                    "layer_report needs a batch: pass (data, labels) "
                    "or fit at least one batch first")
            (xs, xd), (ys, yd) = shapes
            data = np.zeros(xs, dtype=xd)
            labels = np.zeros(ys, dtype=yd)
        x = _as_jnp(data, self._dtype)
        y = _as_jnp(labels, self._dtype)
        states_in = self._with_zero_rnn_states(self.states,
                                               int(x.shape[0]))
        lowered = self._train_step.lower(
            self.params, states_in, self.updater_states, x, y, None,
            None, jnp.asarray(0), jax.random.PRNGKey(0))
        types = {f"layer_{i}": type(l).__name__
                 for i, l in enumerate(self.conf.layers)}
        return layerprof.attribute_compiled(
            lowered.compile(), model_name=type(self).__name__,
            layer_types=types, **roofline_kw)

    def summary(self) -> str:
        lines = [f"{'idx':<4} {'type':<24} {'nIn->nOut':<14} {'params':<10}"]
        total = 0
        params = self.dense_params()
        for i, layer in enumerate(self.conf.layers):
            n = int(sum(np.prod(p.shape) for p in
                        params.get(f"layer_{i}", {}).values()))
            total += n
            lines.append(f"{i:<4} {type(layer).__name__:<24} "
                         f"{layer.n_in}->{layer.n_out:<10} {n:<10}")
        lines.append(f"Total params: {total}")
        return "\n".join(lines)
