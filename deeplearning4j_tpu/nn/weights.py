"""Weight initialization schemes.

Reference parity: ``org.deeplearning4j.nn.weights.WeightInit`` + the
``IWeightInit`` impls (SURVEY.md D1). fan_in/fan_out conventions follow the
reference (XAVIER = glorot with 2/(fan_in+fan_out) variance, RELU = He).
"""
from __future__ import annotations

import enum
import math

import jax
import jax.numpy as jnp


class WeightInit(enum.Enum):
    ZERO = "zero"
    ONES = "ones"
    CONSTANT = "constant"
    NORMAL = "normal"            # N(0, 1/sqrt(fan_in))
    UNIFORM = "uniform"          # U(-a, a), a = 1/sqrt(fan_in)
    XAVIER = "xavier"            # N(0, 2/(fan_in+fan_out))
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    RELU = "relu"                # He normal: N(0, 2/fan_in)
    RELU_UNIFORM = "relu_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    VAR_SCALING_NORMAL_FAN_AVG = "var_scaling_normal_fan_avg"
    IDENTITY = "identity"

    def init(self, key, shape, fan_in: float, fan_out: float,
             dtype=jnp.float32) -> jax.Array:
        s = tuple(int(x) for x in shape)
        if self is WeightInit.ZERO:
            return jnp.zeros(s, dtype)
        if self is WeightInit.ONES:
            return jnp.ones(s, dtype)
        if self is WeightInit.IDENTITY:
            if len(s) != 2 or s[0] != s[1]:
                raise ValueError("IDENTITY init needs square 2d shape")
            return jnp.eye(s[0], dtype=dtype)
        if self is WeightInit.NORMAL:
            return jax.random.normal(key, s, dtype) / math.sqrt(fan_in)
        if self is WeightInit.UNIFORM:
            a = 1.0 / math.sqrt(fan_in)
            return jax.random.uniform(key, s, dtype, -a, a)
        if self is WeightInit.XAVIER:
            std = math.sqrt(2.0 / (fan_in + fan_out))
            return std * jax.random.normal(key, s, dtype)
        if self is WeightInit.XAVIER_UNIFORM:
            a = math.sqrt(6.0 / (fan_in + fan_out))
            return jax.random.uniform(key, s, dtype, -a, a)
        if self is WeightInit.XAVIER_FAN_IN:
            std = math.sqrt(1.0 / fan_in)
            return std * jax.random.normal(key, s, dtype)
        if self is WeightInit.RELU:
            std = math.sqrt(2.0 / fan_in)
            return std * jax.random.normal(key, s, dtype)
        if self is WeightInit.RELU_UNIFORM:
            a = math.sqrt(6.0 / fan_in)
            return jax.random.uniform(key, s, dtype, -a, a)
        if self is WeightInit.LECUN_NORMAL:
            std = math.sqrt(1.0 / fan_in)
            return std * jax.random.normal(key, s, dtype)
        if self is WeightInit.LECUN_UNIFORM:
            a = math.sqrt(3.0 / fan_in)
            return jax.random.uniform(key, s, dtype, -a, a)
        if self is WeightInit.SIGMOID_UNIFORM:
            a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
            return jax.random.uniform(key, s, dtype, -a, a)
        if self is WeightInit.VAR_SCALING_NORMAL_FAN_AVG:
            std = math.sqrt(2.0 / (fan_in + fan_out))
            return std * jax.random.normal(key, s, dtype)
        raise ValueError(self)
