"""Transfer learning — graph surgery on trained networks.

Reference parity: `org.deeplearning4j.nn.transferlearning.
{TransferLearning, FineTuneConfiguration}` (SURVEY.md D10): take a
trained `MultiLayerNetwork`, freeze a feature-extractor prefix,
remove/replace output layers, append new layers, override the
updater/regularization for the fine-tune phase — keeping the trained
weights of every retained layer.

Freezing is expressed as the `NoOp` updater on the frozen layer
(exactly the reference's FrozenLayer mechanism: gradients are
computed but the update is identity), so the jitted train step needs
no special casing.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import List, Optional

import jax
import numpy as np

from ..learning.updaters import IUpdater, NoOp
from .conf.builders import MultiLayerConfiguration
from .multilayer import MultiLayerNetwork


@dataclass
class FineTuneConfiguration:
    """Overrides applied to the whole net for the fine-tune phase
    (reference: FineTuneConfiguration.Builder subset)."""
    updater: Optional[IUpdater] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    seed: Optional[int] = None

    def apply_to(self, conf: MultiLayerConfiguration):
        if self.updater is not None:
            conf.updater = self.updater
            for layer in conf.layers:
                if layer.updater is not None and \
                        not isinstance(layer.updater, NoOp):
                    layer.updater = None   # net-level updater wins
        if self.l1 is not None:
            conf.l1 = self.l1
        if self.l2 is not None:
            conf.l2 = self.l2
        if self.seed is not None:
            conf.seed = self.seed


class TransferLearning:
    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            if not net._initialized:
                raise ValueError("source network must be initialized")
            self._net = net
            self._conf = copy.deepcopy(net.conf)
            self._keep = list(range(len(self._conf.layers)))
            self._appended: List = []
            self._freeze_until = -1
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._nout_replaced = {}

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers 0..layer_idx inclusive (reference
            semantics)."""
            self._freeze_until = layer_idx
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            if n > len(self._keep):
                raise ValueError("removing more layers than exist")
            self._keep = self._keep[:len(self._keep) - n]
            return self

        def n_out_replace(self, layer_idx: int, n_out: int):
            """Replace layer_idx's output width (+ reinit it and fix
            the downstream layer's n_in) keeping its type/config."""
            self._nout_replaced[layer_idx] = n_out
            return self

        def add_layer(self, layer):
            self._appended.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            old_layers = self._conf.layers
            layers = [old_layers[i] for i in self._keep] \
                + list(self._appended)
            conf = copy.deepcopy(self._conf)
            conf.layers = layers
            conf.input_preprocessors = {
                i: p for i, p in conf.input_preprocessors.items()
                if i < len(layers)}

            reinit = set()   # new-net indices whose params re-randomize
            for idx, n_out in self._nout_replaced.items():
                layers[idx].n_out = n_out
                reinit.add(idx)
                if idx + 1 < len(layers):
                    layers[idx + 1].n_in = None   # re-inferred
                    reinit.add(idx + 1)
            for i in range(len(self._appended)):
                reinit.add(len(self._keep) + i)
            if self._appended and self._keep:
                # appended layers infer n_in from the retained stack
                pass

            if self._fine_tune is not None:
                self._fine_tune.apply_to(conf)
            for i in range(min(self._freeze_until + 1, len(layers))):
                layers[i].updater = NoOp()
                layers[i].frozen = True

            new = MultiLayerNetwork(conf)
            new.init()
            # copy trained params for retained, non-reinit layers
            for new_i, old_i in enumerate(self._keep):
                if new_i in reinit:
                    continue
                old_p = self._net.params.get(f"layer_{old_i}", {})
                new.params[f"layer_{new_i}"] = jax.tree_util.tree_map(
                    lambda a: a, old_p)
            return new
