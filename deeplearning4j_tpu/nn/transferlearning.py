"""Transfer learning — graph surgery on trained networks.

Reference parity: `org.deeplearning4j.nn.transferlearning.
{TransferLearning, FineTuneConfiguration}` (SURVEY.md D10): take a
trained `MultiLayerNetwork`, freeze a feature-extractor prefix,
remove/replace output layers, append new layers, override the
updater/regularization for the fine-tune phase — keeping the trained
weights of every retained layer.

Freezing is expressed as the `NoOp` updater on the frozen layer
(exactly the reference's FrozenLayer mechanism: gradients are
computed but the update is identity), so the jitted train step needs
no special casing.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import List, Optional

import jax
import numpy as np

from ..learning.updaters import IUpdater, NoOp
from .conf.builders import MultiLayerConfiguration
from .multilayer import MultiLayerNetwork


@dataclass
class FineTuneConfiguration:
    """Overrides applied to the whole net for the fine-tune phase
    (reference: FineTuneConfiguration.Builder subset)."""
    updater: Optional[IUpdater] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    seed: Optional[int] = None

    def apply_to(self, conf: MultiLayerConfiguration):
        self._apply(conf, conf.layers)

    def apply_to_graph(self, conf):
        self._apply(conf, [v.content for v in conf.vertices.values()
                           if v.is_layer])

    def _apply(self, conf, layers):
        if self.updater is not None:
            conf.updater = self.updater
            for layer in layers:
                if layer.updater is not None and \
                        not isinstance(layer.updater, NoOp):
                    layer.updater = None   # net-level updater wins
        if self.l1 is not None:
            conf.l1 = self.l1
        if self.l2 is not None:
            conf.l2 = self.l2
        if self.seed is not None:
            conf.seed = self.seed


class TransferLearning:
    class GraphBuilder:
        """Transfer learning for ComputationGraph (reference:
        TransferLearning.GraphBuilder): freeze a feature-extractor
        subgraph, remove vertices, append new layers/vertices, keep
        trained weights of retained vertices."""

        def __init__(self, net):
            if not net._initialized:
                raise ValueError("source graph must be initialized")
            self._net = net
            self._conf = copy.deepcopy(net.conf)
            self._removed = set()
            self._added = []          # (name, content, inputs) tuples
            self._freeze_until: Optional[str] = None
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._outputs: Optional[List[str]] = None

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, vertex_name: str):
            """Freeze ``vertex_name`` and every ancestor vertex."""
            self._freeze_until = vertex_name
            return self

        def remove_vertex_and_connections(self, name: str):
            """Drop a vertex and everything downstream of it."""
            conf = self._conf
            dead = {name}
            changed = True
            while changed:
                changed = False
                for v in conf.vertices.values():
                    if v.name not in dead and \
                            any(i in dead for i in v.inputs):
                        dead.add(v.name)
                        changed = True
            self._removed |= dead
            return self

        def add_layer(self, name: str, layer, *inputs: str):
            # layer-vs-vertex is derived from the content type
            # (VertexDef.is_layer); one append path serves both
            self._added.append((name, layer, list(inputs)))
            return self

        add_vertex = add_layer

        def set_outputs(self, *names: str):
            self._outputs = list(names)
            return self

        def build(self):
            from .graph import ComputationGraph
            from .conf.graph_conf import VertexDef
            conf = self._conf
            for name in self._removed:
                conf.vertices.pop(name, None)
            conf.network_outputs = [o for o in conf.network_outputs
                                    if o not in self._removed]
            for name, content, inputs in self._added:
                conf.vertices[name] = VertexDef(name, content, inputs)
            if self._outputs is not None:
                conf.network_outputs = list(self._outputs)

            if self._fine_tune is not None:
                self._fine_tune.apply_to_graph(conf)

            frozen = set()
            if self._freeze_until is not None:
                stack = [self._freeze_until]
                while stack:
                    n = stack.pop()
                    if n in frozen or n in conf.network_inputs:
                        continue
                    frozen.add(n)
                    v = conf.vertices.get(n)
                    if v is not None:
                        stack.extend(v.inputs)
                for n in frozen:
                    v = conf.vertices.get(n)
                    if v is not None and v.is_layer:
                        v.content.updater = NoOp()
                        v.content.frozen = True

            # shapes of new layers re-resolve from retained stack
            if hasattr(conf, "_resolved_types"):
                delattr(conf, "_resolved_types")
            new = ComputationGraph(conf)   # ctor topo-sorts conf
            new.init()
            added_names = {a[0] for a in self._added}
            for name in conf.vertices:
                if name in added_names:
                    continue
                old_p = self._net.params.get(name)
                if old_p:
                    new.params[name] = jax.tree_util.tree_map(
                        lambda a: a, old_p)
                old_s = self._net.states.get(name)
                if old_s:
                    new.states[name] = jax.tree_util.tree_map(
                        lambda a: a, old_s)
            return new

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            if not net._initialized:
                raise ValueError("source network must be initialized")
            self._net = net
            self._conf = copy.deepcopy(net.conf)
            self._keep = list(range(len(self._conf.layers)))
            self._appended: List = []
            self._freeze_until = -1
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._nout_replaced = {}

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_idx: int):
            """Freeze layers 0..layer_idx inclusive (reference
            semantics)."""
            self._freeze_until = layer_idx
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int):
            if n > len(self._keep):
                raise ValueError("removing more layers than exist")
            self._keep = self._keep[:len(self._keep) - n]
            return self

        def n_out_replace(self, layer_idx: int, n_out: int):
            """Replace layer_idx's output width (+ reinit it and fix
            the downstream layer's n_in) keeping its type/config."""
            self._nout_replaced[layer_idx] = n_out
            return self

        def add_layer(self, layer):
            self._appended.append(layer)
            return self

        def build(self) -> MultiLayerNetwork:
            old_layers = self._conf.layers
            layers = [old_layers[i] for i in self._keep] \
                + list(self._appended)
            conf = copy.deepcopy(self._conf)
            conf.layers = layers
            conf.input_preprocessors = {
                i: p for i, p in conf.input_preprocessors.items()
                if i < len(layers)}

            reinit = set()   # new-net indices whose params re-randomize
            for idx, n_out in self._nout_replaced.items():
                layers[idx].n_out = n_out
                reinit.add(idx)
                if idx + 1 < len(layers):
                    layers[idx + 1].n_in = None   # re-inferred
                    reinit.add(idx + 1)
            for i in range(len(self._appended)):
                reinit.add(len(self._keep) + i)
            if self._appended and self._keep:
                # appended layers infer n_in from the retained stack
                pass

            if self._fine_tune is not None:
                self._fine_tune.apply_to(conf)
            for i in range(min(self._freeze_until + 1, len(layers))):
                layers[i].updater = NoOp()
                layers[i].frozen = True

            new = MultiLayerNetwork(conf)
            new.init()
            # copy trained params for retained, non-reinit layers
            for new_i, old_i in enumerate(self._keep):
                if new_i in reinit:
                    continue
                old_p = self._net.params.get(f"layer_{old_i}", {})
                new.params[f"layer_{new_i}"] = jax.tree_util.tree_map(
                    lambda a: a, old_p)
            return new
