"""Gradient normalization / clipping.

Reference parity: ``org.deeplearning4j.nn.conf.GradientNormalization``
applied by ``BaseLayer.backpropGradient``/updater path (SURVEY.md D6).
Pure functions over one layer's gradient dict, applied inside the jitted
step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.builders import GradientNormalization


def _global_l2(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(l * l) for l in leaves) + 1e-12)


def apply_gradient_normalization(kind: GradientNormalization,
                                 threshold: float, layer_grads: dict):
    """layer_grads: one layer's param-name -> grad dict."""
    if kind is GradientNormalization.NONE or not layer_grads:
        return layer_grads
    if kind is GradientNormalization.RENORMALIZE_L2_PER_LAYER:
        n = _global_l2(layer_grads)
        return jax.tree_util.tree_map(lambda g: g / n, layer_grads)
    if kind is GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE:
        return {k: v / jnp.sqrt(jnp.sum(v * v) + 1e-12)
                for k, v in layer_grads.items()}
    if kind is GradientNormalization.CLIP_ELEMENT_WISE_ABSOLUTE_VALUE:
        t = threshold
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, -t, t),
                                      layer_grads)
    if kind is GradientNormalization.CLIP_L2_PER_LAYER:
        n = _global_l2(layer_grads)
        scale = jnp.minimum(1.0, threshold / n)
        return jax.tree_util.tree_map(lambda g: g * scale, layer_grads)
    if kind is GradientNormalization.CLIP_L2_PER_PARAM_TYPE:
        out = {}
        for k, v in layer_grads.items():
            n = jnp.sqrt(jnp.sum(v * v) + 1e-12)
            out[k] = v * jnp.minimum(1.0, threshold / n)
        return out
    raise ValueError(kind)
