"""ComputationGraph: DAG model compiled to one jitted step.

Reference parity: ``org.deeplearning4j.nn.graph.ComputationGraph``
(SURVEY.md D3, call stack 3.2): topo-ordered vertex execution,
multi-input/multi-output, same fit/output/score/evaluate surface as
MultiLayerNetwork. The reference's reverse-topo epsilon accumulation
(fan-out vertices sum incoming gradients) is what reverse-mode autodiff
does by construction — ``jax.value_and_grad`` over the whole DAG replaces
the hand-written backprop orchestration.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common import layerprof
from deeplearning4j_tpu.common.dtypes import to_jnp_dtype
from deeplearning4j_tpu.nn.conf.graph_conf import \
    ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.constraints import apply_constraints
from deeplearning4j_tpu.nn.conf.layers import BaseOutputLayer
from deeplearning4j_tpu.nn.gradient import apply_gradient_normalization
from deeplearning4j_tpu.nn.multilayer import _as_jnp
from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger("deeplearning4j_tpu")


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params: dict = {}
        self.states: dict = {}
        self.updater_states: dict = {}
        self.listeners: List[TrainingListener] = []
        self.iteration_count = 0
        self.epoch_count = 0
        self.last_batch_size = 0
        self._score = float("nan")
        self._rng = jax.random.PRNGKey(conf.seed)
        self._train_step = None
        self._step_gnorm = False    # step emits a real grad norm
        self._initialized = False
        self._dtype = to_jnp_dtype(conf.dtype)
        self._topo = conf.topo_order()
        self._retrace_guard = None
        # ZeRO-1 sharded update (parallel.zero): when a dp mesh is
        # installed the step tail runs the updater on 1/N param shards
        self._dp_mesh = None
        self._dp_axis = "data"
        # full FSDP / ZeRO-3 (parallel.zero): params live as 1/N flat
        # shards ({FSDP_KEY: {dtype: flat}} per vertex), gathered
        # per-vertex just-in-time in the forward; _fsdp_specs keeps the
        # per-vertex DpFlatSpec needed to densify
        self._dp_fsdp = False
        self._fsdp_specs = {}
        # dense update tail WITH a mesh installed (dense x tp 2D mode:
        # the step needs the mesh for tp pins but must not run ZeRO-1)
        self._dp_dense = False
        # encoded update exchange (parallel.encoding): the ZeRO-1 tail
        # with the flat gradient compressed before the data-axis
        # collective; _dp_encoding holds the static EncodingSpec
        self._dp_encoded = False
        self._dp_encoding = None
        # tensor parallelism (parallel.speclayout): per-vertex
        # {name: TpLeafSpec} for model-axis sharded leaves
        self._tp_model_axis = None
        self._tp_specs = {}
        # gradient accumulation (reference: GradientsAccumulator)
        self._accum_steps = 1
        self._accum_grads = None
        self._accum_count = 0
        self._updates_applied = 0

    # ------------------------------------------------------------------
    def init(self) -> "ComputationGraph":
        if self._initialized:
            return self
        conf = self.conf
        conf.resolve_shapes()
        types = getattr(conf, "_resolved_types", {})
        key = jax.random.PRNGKey(conf.seed)
        for name in self._topo:
            v = conf.vertices[name]
            if not v.is_layer:
                self.params[name] = {}
                self.states[name] = {}
                continue
            in_type = types.get(v.inputs[0]) if types else None
            if v.preprocessor is not None and in_type is not None:
                in_type = v.preprocessor.get_output_type(in_type)
            key, sub = jax.random.split(key)
            self.params[name] = v.content.init_params(
                sub, in_type, self._dtype) if v.content.has_params() else {}
            self.states[name] = v.content.init_state(
                in_type, self._dtype) if v.content.has_state() else {}
        for name in self._topo:
            v = conf.vertices[name]
            up = (v.content.updater if v.is_layer and v.content.updater
                  else conf.updater)
            self.updater_states[name] = up.init_state(self.params[name])
        self._initialized = True
        return self

    # ------------------------------------------------------------------
    def set_listeners(self, *listeners: TrainingListener):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners: TrainingListener):
        self.listeners.extend(listeners)
        return self

    def output_layer_confs(self) -> Dict[str, BaseOutputLayer]:
        out = {}
        for name in self.conf.network_outputs:
            layer = self.conf.vertices[name].content
            if isinstance(layer, BaseOutputLayer):
                out[name] = layer
        return out

    # ------------------------------------------------------------------
    def _forward(self, params, states, inputs: Sequence, *,
                 training: bool, rng, want_logits: bool, fmask=None,
                 upto: Optional[str] = None, start_acts=None,
                 topo_slice=None):
        """Topo walk. inputs: list matching conf.network_inputs order.
        ``fmask`` is the per-timestep features mask (first input's), passed
        to mask-aware layers — multi-input graphs with per-input masks can
        attach masks via PreprocessorVertex if they diverge.
        ``upto``: walk only the ancestor subgraph of this vertex
        (inclusive) — the pretrain path, where downstream vertices must
        not even be traced (their params are held out of the step).
        ``topo_slice``: ``(lo, hi)`` — walk only ``self._topo[lo:hi]``,
        the pipeline-stage slice (parallel/pipeline.py), with
        ``start_acts`` seeding the activations handed over from earlier
        stages; per-vertex RNG stays folded on the FULL-topo layer
        position, so a sliced walk reproduces the whole-graph stream.
        Returns ({vertex: activation} for outputs, new_states)."""
        conf = self.conf
        if conf.compute_dtype:
            # mixed precision: bfloat16 math, float32 master params —
            # the entry cast's transpose gives float32 gradients.
            # States (BN running stats) stay f32: bf16 ulp would
            # swallow their (1-decay)*delta updates.
            from deeplearning4j_tpu.common.dtypes import cast_floats
            cd = conf.compute_dtype
            # an FsdpParamView casts per-vertex post-gather, keeping
            # the just-in-time gather schedule
            params = (params.cast(cd) if hasattr(params, "cast")
                      else cast_floats(params, cd))
            inputs = [cast_floats(x, cd) for x in inputs]
            if start_acts is not None:
                start_acts = cast_floats(start_acts, cd)
        def run_vertex(name, acts, lrng):
            """Execute one vertex against the live activation dict;
            returns (activation, layer_state).  The layer-attribution
            scope (common.layerprof) tags every op the vertex traces —
            forward AND its autodiff transpose — with
            ``dl4j.<vertex name>``; both the remat-segmented and the
            plain walk funnel through here."""
            with layerprof.scope(name):
                return _run_vertex(name, acts, lrng)

        def _run_vertex(name, acts, lrng):
            v = conf.vertices[name]
            xs = [acts[i] for i in v.inputs]
            if not v.is_layer:
                return v.content.forward(xs, training=training), {}
            h = xs[0]
            if v.preprocessor is not None:
                h = v.preprocessor.pre_process(h)
            layer = v.content
            lp = params.get(name, {})
            if training and layer.weight_noise is not None and \
                    lrng is not None and lp:
                # reference: conf.weightnoise — params perturbed
                # per forward; gradients flow to the clean params
                lrng, wn_rng = jax.random.split(lrng)
                lp = layer.weight_noise.apply(lp, wn_rng)
            ls = states.get(name, {})
            kw = {}
            if fmask is not None and layer.accepts_mask():
                kw["mask"] = fmask
            if want_logits and name in conf.network_outputs and \
                    isinstance(layer, BaseOutputLayer) and \
                    layer.wants_logits():
                h, ns = layer.forward_logits(
                    lp, h, training=training,
                    rng=lrng, state=ls or None)
            else:
                h, ns = layer.forward(
                    lp, h, training=training,
                    rng=lrng, state=ls or None, **kw)
            return h, ns if ns is not None else {}

        if training and conf.remat_segments > 1 and \
                len(self._topo) > 1 and \
                start_acts is None and topo_slice is None:
            acts, new_states = self._forward_segmented(run_vertex, rng,
                                                       inputs)
        else:
            topo = self._topo
            if topo_slice is not None:
                topo = topo[topo_slice[0]:topo_slice[1]]
            if upto is not None:
                need = {upto}
                for n in reversed(self._topo):
                    if n in need:
                        need.update(conf.vertices[n].inputs)
                topo = [n for n in topo if n in need]
            acts = dict(zip(conf.network_inputs, inputs))
            if start_acts is not None:
                acts.update(start_acts)
            new_states = {}
            # fold_in by layer position IN THE FULL TOPO — same
            # derivation as _forward_segmented, so neither toggling
            # remat_segments nor an upto-restricted walk changes the
            # dropout/weight-noise stream
            layer_pos = {n: i for i, n in enumerate(
                n for n in self._topo if conf.vertices[n].is_layer)}
            for name in topo:
                lrng = None
                if rng is not None and conf.vertices[name].is_layer:
                    lrng = jax.random.fold_in(rng, layer_pos[name])
                h, ns = run_vertex(name, acts, lrng)
                acts[name] = h
                new_states[name] = ns
        if self.conf.compute_dtype:
            from deeplearning4j_tpu.common.dtypes import cast_floats
            for out in self.conf.network_outputs:
                if out in acts:          # absent under a partial walk
                    acts[out] = cast_floats(acts[out], self._dtype)
            new_states = cast_floats(new_states, self._dtype)
        return acts, new_states

    def _forward_segmented(self, run_vertex, rng, inputs):
        """Training forward in ``conf.remat_segments`` contiguous
        ``jax.checkpoint`` segments of the topo walk: only the
        activations LIVE at a segment boundary are stored for the
        backward pass; everything inside a segment is recomputed
        (sqrt(N) checkpointing — trades recompute FLOPs for HBM
        activation traffic, usually a win on bandwidth-bound TPUs).
        Per-vertex RNG is ``fold_in(rng, layer position)`` — the same
        derivation as the plain walk, so the random stream is invariant
        to segmentation (and to remat on/off)."""
        from deeplearning4j_tpu.common.remat import segment_plan
        conf = self.conf
        topo = self._topo
        plan = segment_plan(len(topo), conf.remat_segments)

        layer_names = [n for n in topo if conf.vertices[n].is_layer]
        if rng is not None and layer_names:
            rng_for = {n: jax.random.fold_in(rng, i)
                       for i, n in enumerate(layer_names)}
        else:
            rng_for = {}

        # liveness: an activation must cross a segment boundary iff a
        # later vertex consumes it or it is a network output
        consumers: Dict[str, list] = {}
        for name in topo:
            for src in conf.vertices[name].inputs:
                consumers.setdefault(src, []).append(name)
        pos = {n: i for i, n in enumerate(topo)}

        def needed_after(idx_end):
            keep = set(conf.network_outputs)
            for src, cons in consumers.items():
                if any(pos[c] >= idx_end for c in cons):
                    keep.add(src)
            return keep

        live: Dict[str, jnp.ndarray] = dict(zip(conf.network_inputs,
                                                inputs))
        new_states: dict = {}
        for lo, hi, wrap in plan:
            seg = topo[lo:hi]
            produced = set(seg)
            refs = {src for n in seg
                    for src in conf.vertices[n].inputs}
            seg_in = sorted(refs - produced)
            keep = needed_after(hi)
            seg_out = sorted(produced & keep)
            seg_rngs = {n: rng_for[n] for n in seg if n in rng_for}

            def seg_fn(in_acts, seg_rngs, seg=seg, seg_out=seg_out):
                acts = dict(in_acts)
                ns = {}
                for name in seg:
                    h, s = run_vertex(name, acts,
                                      seg_rngs.get(name))
                    acts[name] = h
                    ns[name] = s
                return {k: acts[k] for k in seg_out}, ns

            if wrap:
                # the LAST segment (wrap=False) holds the loss head;
                # checkpointing it buys nothing
                seg_fn = jax.checkpoint(seg_fn)
            outs, ns = seg_fn({k: live[k] for k in seg_in}, seg_rngs)
            live.update(outs)
            new_states.update(ns)
            # prune dead activations so they do not stay resident
            # (reuses this segment's liveness set from above)
            live = {k: v for k, v in live.items() if k in keep}
        return live, new_states

    # -- recurrent state lifecycle (mirrors MultiLayerNetwork) ----------
    def _recurrent_names(self):
        return [n for n in self._topo
                if self.conf.vertices[n].is_layer and
                self.conf.vertices[n].content.is_recurrent()]

    def _with_zero_rnn_states(self, states, batch: int):
        out = dict(states)
        for n in self._recurrent_names():
            out[n] = self.conf.vertices[n].content.zero_state(
                batch, self._dtype)
        return out

    def _strip_rnn_states(self, states):
        out = dict(states)
        for n in self._recurrent_names():
            out[n] = {}
        return out

    def _regularization(self, params):
        reg = 0.0
        for name in self._topo:
            v = self.conf.vertices[name]
            if not v.is_layer:
                continue
            l1 = v.content.l1 or 0.0
            l2 = v.content.l2 or 0.0
            if l1 == 0.0 and l2 == 0.0:
                continue
            W = params.get(name, {}).get("W")
            if W is None:
                continue
            if l1:
                reg = reg + l1 * jnp.sum(jnp.abs(W))
            if l2:
                reg = reg + 0.5 * l2 * jnp.sum(W * W)
        return reg

    # ------------------------------------------------------------------
    def _build_train_step(self):
        from deeplearning4j_tpu.common.compilecache import \
            enable_persistent_cache
        enable_persistent_cache()    # second process loads, not compiles
        conf = self.conf
        out_confs = self.output_layer_confs()
        updaters = {name: (conf.vertices[name].content.updater
                           if conf.vertices[name].is_layer and
                           conf.vertices[name].content.updater
                           else conf.updater)
                    for name in self._topo}

        gn = conf.gradient_normalization
        thr = conf.gradient_normalization_threshold
        dp_mesh, dp_axis = self._dp_mesh, self._dp_axis
        fsdp = self._dp_fsdp and dp_mesh is not None
        dense_tail = self._dp_dense and dp_mesh is not None
        encoded = self._dp_encoded and dp_mesh is not None
        encoding = self._dp_encoding if encoded else None
        tp_specs_all = (dict(self._tp_specs)
                        if dp_mesh is not None and self._tp_specs else {})
        if fsdp:
            from deeplearning4j_tpu.common.environment import Environment
            from deeplearning4j_tpu.parallel.zero import FsdpParamView
            fsdp_specs = dict(self._fsdp_specs)
            fsdp_prefetch = Environment.get().fsdp_prefetch
            vertex_order = list(self._topo)

        def loss_fn(params, states, inputs, labels, fmask, lmasks, rng):
            if fsdp:
                # lazy view over the 1/N flat shards: each vertex's
                # all-gather is emitted at its point of use in the walk
                params = FsdpParamView(params, fsdp_specs, dp_mesh,
                                       dp_axis, order=vertex_order,
                                       prefetch=fsdp_prefetch,
                                       tp_specs=tp_specs_all)
            elif tp_specs_all:
                # 2D mode: pin tp leaves to their compute spec; the
                # custom-vjp pin sends the cotangent to the resident
                # spec, so dp grad collectives stay on the data axis
                from deeplearning4j_tpu.parallel.zero import pin_tp_entry
                params = {k: (pin_tp_entry(sub, dp_mesh,
                                           tp_specs_all[k])
                              if k in tp_specs_all and
                              isinstance(sub, dict) else sub)
                          for k, sub in params.items()}
            acts, new_states = self._forward(params, states, inputs,
                                             training=True, rng=rng,
                                             want_logits=True,
                                             fmask=fmask)
            # attribution scope: loss + regularization are real step
            # work but belong to no vertex — name them instead of
            # letting them fall into the _unattributed bucket
            with layerprof.scope("loss"):
                loss = self._regularization(params)
                for i, out_name in enumerate(conf.network_outputs):
                    layer = out_confs.get(out_name)
                    if layer is None:
                        continue
                    loss = loss + layer.compute_loss(
                        labels[i], acts[out_name],
                        from_logits=layer.wants_logits(),
                        mask=lmasks[i] if lmasks is not None else None)
                return loss, new_states

        # numerics watchdog: when armed the step also emits the global
        # grad norm in-jit; when off it is a free zeros constant (see
        # MultiLayerNetwork._build_train_step)
        from deeplearning4j_tpu.common.diagnostics import watchdog_enabled
        want_gnorm = watchdog_enabled()
        self._step_gnorm = want_gnorm

        def grad_norm(grads):
            if not want_gnorm:
                return jnp.zeros((), jnp.float32)
            sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
                  for g in jax.tree_util.tree_leaves(grads)]
            return jnp.sqrt(sum(sq)) if sq else jnp.zeros((),
                                                          jnp.float32)

        def update_tail(params, upd_states, grads, iteration):
            """Grads -> (new_params, new_upd); shared by the fused step
            and the accumulation apply step. With a dp mesh the updater
            runs ZeRO-1 sharded (parallel.zero; the resolver guarantees
            gradient_normalization NONE there, so skipping it is exact)."""
            new_params, new_upd = {}, {}
            for name in self._topo:
                g = grads.get(name, {})
                if not g:
                    new_params[name] = params.get(name, {})
                    new_upd[name] = upd_states.get(name, ())
                    continue
                tps = tp_specs_all.get(name)
                if fsdp:
                    # ZeRO-3 tail: params/grads already the 1/N flat
                    # shards and stay that way — no trailing all-gather
                    # (constraints skipped: the resolver refuses fsdp
                    # when any layer has them). TP leaves get their own
                    # elementwise tail pinned to the model-axis layout.
                    from deeplearning4j_tpu.learning.updaters import \
                        FSDP_KEY, TP_KEY
                    from deeplearning4j_tpu.parallel.zero import (
                        apply_update_fsdp, apply_update_tp,
                        merge_tp_state, split_tp_state)
                    st_rest, st_tp = split_tp_state(upd_states[name])
                    new_flat, us = apply_update_fsdp(
                        updaters[name], g[FSDP_KEY],
                        params[name][FSDP_KEY], st_rest,
                        iteration, dp_mesh, dp_axis)
                    ent = {FSDP_KEY: new_flat}
                    if tps and TP_KEY in g:
                        new_tp, us_tp = apply_update_tp(
                            updaters[name], g[TP_KEY],
                            params[name][TP_KEY], st_tp, iteration,
                            dp_mesh, tps, gather_params=False)
                        ent[TP_KEY] = new_tp
                        us = merge_tp_state(us, us_tp)
                    new_params[name] = ent
                    new_upd[name] = us
                    continue
                if dp_mesh is not None and not dense_tail:
                    import functools as _ft

                    from deeplearning4j_tpu.parallel.zero import (
                        apply_update_encoded, apply_update_sharded,
                        apply_update_tp, merge_tp_state,
                        split_tp_entry, split_tp_state)
                    apply_dp = (_ft.partial(apply_update_encoded,
                                            encoding=encoding)
                                if encoded else apply_update_sharded)
                    if tps:
                        g_rest, g_tp = split_tp_entry(g, tps)
                        p_rest, p_tp = split_tp_entry(params[name], tps)
                        st_rest, st_tp = split_tp_state(
                            upd_states[name])
                        if g_rest:
                            new_rest, us = apply_dp(
                                updaters[name], g_rest, p_rest,
                                st_rest, iteration, dp_mesh, dp_axis)
                        else:
                            new_rest, us = p_rest, st_rest
                        new_tp, us_tp = apply_update_tp(
                            updaters[name], g_tp, p_tp, st_tp,
                            iteration, dp_mesh, tps,
                            gather_params=True)
                        new_p = {**new_rest, **new_tp}
                        us = merge_tp_state(us, us_tp)
                    else:
                        new_p, us = apply_dp(
                            updaters[name], g, params[name],
                            upd_states[name], iteration, dp_mesh,
                            dp_axis)
                else:
                    g = apply_gradient_normalization(gn, thr, g)
                    updates, us = updaters[name].apply(
                        g, upd_states[name], iteration)
                    new_p = jax.tree_util.tree_map(
                        lambda p, u: p - u, params[name], updates)
                v = conf.vertices[name]
                if v.is_layer:
                    new_p = apply_constraints(v.content, new_p)
                new_params[name] = new_p
                new_upd[name] = us
            return new_params, new_upd

        def step(params, states, upd_states, inputs, labels, fmask,
                 lmasks, iteration, rng):
            (loss, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, states, inputs, labels,
                                       fmask, lmasks, rng)
            gnorm = grad_norm(grads)
            # attribution scope: the updater sweep reads/writes every
            # parameter — substantial byte traffic that is not any
            # vertex's compute
            with layerprof.scope("optimizer"):
                new_params, new_upd = update_tail(params, upd_states,
                                                  grads, iteration)
            return new_params, new_states, new_upd, loss, gnorm

        def grad_step(params, states, inputs, labels, fmask, lmasks,
                      rng):
            # accumulation micro-step: backward only, no update (params
            # NOT donated — the apply step still reads them)
            (loss, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, states, inputs, labels,
                                       fmask, lmasks, rng)
            return grads, new_states, loss, grad_norm(grads)

        def apply_step(params, upd_states, grads, scale, iteration):
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            with layerprof.scope("optimizer"):
                new_params, new_upd = update_tail(params, upd_states,
                                                  grads, iteration)
            return new_params, new_upd

        self._step_fn = step         # unjitted (multi-step path reuses)
        self._train_step = jax.jit(step, donate_argnums=(0, 1, 2))
        self._grad_step = jax.jit(grad_step, donate_argnums=(1,))
        self._apply_step = jax.jit(apply_step, donate_argnums=(1, 2))
        self._accum_add = jax.jit(
            lambda acc, g: jax.tree_util.tree_map(
                lambda a, b: a + b, acc, g),
            donate_argnums=(0,))

    # ------------------------------------------------------------------
    def set_dp_mesh(self, mesh, axis: str = "data", mode=None, *,
                    model_axis=None, tp_specs=None, encoding=None):
        """Install (or clear, with ``mesh=None``) the (possibly 2D)
        mesh the jitted step tail specializes on (``parallel.zero``).
        ``mode="fsdp"`` selects the ZeRO-3 tail: params convert to the
        1/N flat resident layout here (the model owns both param and
        updater-state conversion under fsdp); ``mode="dense"`` installs
        the mesh WITHOUT the ZeRO-1 tail (dense×tp); ``mode="encoded"``
        selects the compressed-collective tail (``encoding=`` takes an
        ``EncodingSpec`` or scheme string); for the ZeRO-1 tail callers
        still own converting/placing ``updater_states``.
        ``model_axis``/``tp_specs`` (``parallel.speclayout``) add the
        tensor-parallel dimension. Invalidates compiled steps."""
        mode_s = str(getattr(mode, "value", mode) or "").lower()
        fsdp = mode_s == "fsdp" and mesh is not None
        dense = mode_s == "dense" and mesh is not None
        encoded = mode_s == "encoded" and mesh is not None
        if encoded:
            from deeplearning4j_tpu.parallel.encoding import \
                resolve_encoding
            encoding = resolve_encoding(encoding)
        else:
            encoding = None
        tp_specs = dict(tp_specs or {}) if mesh is not None else {}
        model_axis = model_axis if tp_specs else None
        if mesh is self._dp_mesh and axis == self._dp_axis and \
                fsdp == self._dp_fsdp and dense == self._dp_dense and \
                encoded == self._dp_encoded and \
                encoding == self._dp_encoding and \
                model_axis == self._tp_model_axis and \
                tp_specs == self._tp_specs:
            return self
        self.flush_accumulated()
        self._dp_mesh = mesh
        self._dp_axis = axis
        self._dp_fsdp = fsdp
        self._dp_dense = dense
        self._dp_encoded = encoded
        self._dp_encoding = encoding
        self._tp_model_axis = model_axis
        self._tp_specs = tp_specs
        self._train_step = None
        self._step_fn = None
        self._grad_step = None
        self._apply_step = None
        self._accum_add = None
        if hasattr(self, "_multi_steps"):
            del self._multi_steps
        self._sync_param_layout()
        return self

    def set_accumulation_steps(self, n: int):
        """Apply the updater once every ``n`` fit() micro-batches on the
        mean of their gradients (the reference's GradientsAccumulator):
        effective batch = n x micro-batch with no extra activation HBM."""
        n = max(int(n), 1)
        if n != self._accum_steps:
            self.flush_accumulated()
            self._accum_steps = n
        return self

    def flush_accumulated(self):
        """Apply a partial accumulation window now (epoch end / mode
        change); no-op when nothing is pending."""
        if self._accum_count:
            self._apply_accumulated()
        return self

    def _apply_accumulated(self):
        k = self._accum_count
        scale = jnp.asarray(1.0 / k, jnp.float32)
        self.params, self.updater_states = self._apply_step(
            self.params, self.updater_states, self._accum_grads, scale,
            jnp.asarray(self._updates_applied))
        self._accum_grads = None
        self._accum_count = 0
        self._updates_applied += 1

    def _sync_updater_layout(self):
        """A checkpoint restored from a ZeRO-1 run carries flat sharded
        updater state; on a plain (no-mesh) model — or under the
        dense×tp tail, which consumes dense state — convert it back to
        the dense per-vertex layout before stepping (ENCODED_KEY
        error-feedback state is stripped there: the residual belongs
        to the compressed exchange). Under ``mode="encoded"`` the
        inverse sync runs: entries missing their ENCODED_KEY state
        (first fit, or a dense/sharded checkpoint restored into an
        encoded run — on any device count) get it injected and placed."""
        if self._dp_mesh is not None and not self._dp_dense:
            if self._dp_encoded:
                from deeplearning4j_tpu.parallel.zero import (
                    ensure_encoded_states, place_updater_states)
                n = self._dp_mesh.shape[self._dp_axis]
                states = self.updater_states
                new = ensure_encoded_states(
                    self.dense_params() if self._params_are_fsdp()
                    else self.params,
                    states, n, self._dp_encoding,
                    tp_specs=self._tp_specs)
                if any(new[k] is not states.get(k) for k in new):
                    self.updater_states = place_updater_states(
                        self._dp_mesh, new, self._dp_axis,
                        tp_specs=self._tp_specs)
            return
        from deeplearning4j_tpu.learning.updaters import (has_tp,
                                                          is_dp_sharded,
                                                          is_encoded)
        if any(is_dp_sharded(s) or has_tp(s) or is_encoded(s)
               for s in self.updater_states.values()):
            from deeplearning4j_tpu.parallel.zero import (
                states_to_dense, strip_encoded_states)
            self.updater_states = strip_encoded_states(
                states_to_dense(self.params, self.updater_states))

    def _params_are_fsdp(self) -> bool:
        from deeplearning4j_tpu.learning.updaters import is_fsdp
        return any(is_fsdp(p) for p in self.params.values()
                   if isinstance(p, dict))

    def _sync_param_layout(self):
        """Enter/leave the fsdp flat resident param layout
        (parallel.zero). Entering converts updater state to the ZeRO-1
        flat layout too (the fsdp tail consumes it) and places both at
        1/N per replica; leaving densifies params (gather timed into
        ``dl4j_fsdp_gather_seconds``).  Elastic re-mesh: flats resident
        for a DIFFERENT world size (resume onto a new mesh) round-trip
        through the dense layout and re-enter — params via
        ``params_to_dense`` -> ``place_fsdp_params``, updater state via
        its ``DpFlatSpec`` re-ravel inside ``states_to_sharded``."""
        flat = self._params_are_fsdp()
        if self._dp_fsdp and self._dp_mesh is not None:
            from deeplearning4j_tpu.parallel.zero import (
                fsdp_spec_shards, params_to_fsdp, place_fsdp_params,
                place_updater_states, states_to_sharded)
            n = self._dp_mesh.shape[self._dp_axis]
            if flat:
                if fsdp_spec_shards(self._fsdp_specs) == n and \
                        self._tp_layout_matches():
                    # already resident; placement happened on entry
                    return
                # raveled for another world size (or another tp
                # partition): densify and re-enter
                self._densify_params_inplace()
            self.updater_states = states_to_sharded(
                self.params, self.updater_states, n,
                tp_specs=self._tp_specs)
            self.params, self._fsdp_specs = params_to_fsdp(
                self.params, n, tp_specs=self._tp_specs)
            self.params = place_fsdp_params(self._dp_mesh, self.params,
                                            self._dp_axis,
                                            tp_specs=self._tp_specs)
            self.updater_states = place_updater_states(
                self._dp_mesh, self.updater_states, self._dp_axis,
                tp_specs=self._tp_specs)
        elif flat:
            self._densify_params_inplace()

    def _tp_layout_matches(self) -> bool:
        """True when the resident fsdp entries' TP_KEY split matches
        the installed tp specs (an fsdp×tp checkpoint restored onto a
        mesh with a different tp degree must densify and re-enter)."""
        from deeplearning4j_tpu.learning.updaters import TP_KEY, is_fsdp
        want = {k: set(v) for k, v in (self._tp_specs or {}).items()}
        for k, sub in self.params.items():
            if not isinstance(sub, dict) or not is_fsdp(sub):
                continue
            got = set(sub.get(TP_KEY, {}))
            if got != want.get(k, set()):
                return False
        return True

    def _densify_params_inplace(self):
        if self._params_are_fsdp():
            from deeplearning4j_tpu.parallel.zero import (on_2d_mesh,
                                                          params_to_dense)
            self.params = params_to_dense(self.params, self._fsdp_specs)
            # specs kept: a later _sync_param_layout re-entry recomputes
            if any(on_2d_mesh(a)
                   for a in jax.tree_util.tree_leaves(self.params)):
                # leaving a 2D (data, model) residency: the densified
                # leaves still carry the old mesh's shardings, and
                # re-raveling them through XLA SPMD hits the same
                # concatenate-lowering bug worked around in
                # zero.apply_update_sharded — re-enter from host copies
                self.params = jax.device_get(self.params)
                self.updater_states = jax.device_get(self.updater_states)

    def dense_params(self) -> dict:
        """Params in the dense per-vertex layout regardless of residency
        (non-mutating; under fsdp this is a full host-side all-gather —
        checkpoint/inference/introspection consumers only)."""
        if not self._params_are_fsdp():
            return self.params
        from deeplearning4j_tpu.parallel.zero import params_to_dense
        return params_to_dense(self.params, self._fsdp_specs)

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, *, n_epochs: int = 1):
        """fit(x, y) | fit(DataSet/MultiDataSet) | fit(iterator)."""
        if not self._initialized:
            self.init()
        self._sync_updater_layout()
        self._sync_param_layout()
        if self._train_step is None:
            self._build_train_step()
        if labels is not None:
            for _ in range(n_epochs):
                self._fit_batch(
                    [data] if not isinstance(data, (list, tuple))
                    else list(data),
                    [labels] if not isinstance(labels, (list, tuple))
                    else list(labels), None, None)
            return self
        if hasattr(data, "features") and hasattr(data, "labels"):
            for _ in range(n_epochs):
                self._fit_dataset(data)
            return self
        # stage batches device-side ahead of the step loop (no-op when
        # DL4J_TPU_DEVICE_PREFETCH=0 or not a resettable iterator)
        from deeplearning4j_tpu.datasets.prefetch import \
            maybe_device_prefetch
        data = maybe_device_prefetch(data, dtype=self._dtype)
        for _ in range(n_epochs):
            for lis in self.listeners:
                lis.on_epoch_start(self)
            if hasattr(data, "reset"):
                data.reset()
            for ds in data:
                self._fit_dataset(ds)
            # a partial accumulation window does not leak across epochs
            self.flush_accumulated()
            # epochs-completed advances BEFORE listeners (see
            # MultiLayerNetwork.fit: checkpoint-resume correctness)
            self.epoch_count += 1
            for lis in self.listeners:
                lis.on_epoch_end(self)
        return self

    def pretrain(self, data, *, n_epochs: int = 1):
        """Greedy layerwise unsupervised pretraining (reference:
        ComputationGraph.pretrain(DataSetIterator) — SURVEY.md D3):
        every pretrainable vertex (AutoEncoder/VAE) is fit in topo
        order on the activations of the subgraph feeding it, with the
        rest of the graph held fixed."""
        from deeplearning4j_tpu.nn.pretrain_util import materialize_once
        data = materialize_once(data)
        for name in self._topo:
            v = self.conf.vertices[name]
            if v.is_layer and getattr(v.content, "is_pretrainable",
                                      lambda: False)():
                self.pretrain_vertex(name, data, n_epochs=n_epochs)
        return self

    def pretrain_vertex(self, name: str, data, *, n_epochs: int = 1):
        """Fit one pretrainable vertex (reference:
        ComputationGraph.pretrainLayer(String, iter)). The vertex's
        ``pretrain_loss`` + its updater compile into ONE jitted step;
        upstream vertices run in inference mode, and XLA dead-code
        eliminates everything downstream of the vertex's input (the
        walk is traced whole, only ``acts[src]`` is consumed)."""
        if not self._initialized:
            self.init()
        self._sync_updater_layout()
        # pretrain reads/writes per-vertex dense params directly; leave
        # the flat layout (a later fit() re-enters it)
        self._densify_params_inplace()
        v = self.conf.vertices[name]
        layer = v.content if v.is_layer else None
        if layer is None or not getattr(layer, "is_pretrainable",
                                        lambda: False)():
            raise ValueError(f"vertex {name!r} is not pretrainable")
        up = layer.updater or self.conf.updater
        upd_state = self.updater_states[name]

        if not hasattr(self, "_pretrain_steps"):
            self._pretrain_steps = {}
        if name not in self._pretrain_steps:
            src = v.inputs[0]

            def step(lp, frozen_params, states, us, inputs, iteration,
                     rng):
                acts, _ = self._forward(frozen_params, states, inputs,
                                        training=False, rng=None,
                                        want_logits=False, upto=src)
                h = acts[src]
                if v.preprocessor is not None:
                    h = v.preprocessor.pre_process(h)
                loss, g = jax.value_and_grad(layer.pretrain_loss)(
                    lp, h, rng)
                updates, new_us = up.apply(g, us, iteration)
                new_lp = jax.tree_util.tree_map(
                    lambda p, u: p - u, lp, updates)
                new_lp = apply_constraints(layer, new_lp)
                return new_lp, new_us, loss

            self._pretrain_steps[name] = jax.jit(step,
                                                 donate_argnums=(0, 3))
        jit_step = self._pretrain_steps[name]

        from deeplearning4j_tpu.nn.pretrain_util import (
            feature_batches, materialize_once)
        data = materialize_once(data)

        for _ in range(n_epochs):
            for inputs in feature_batches(data, as_list=True):
                inputs = [_as_jnp(x, self._dtype) for x in inputs]
                rng = self._next_rng()
                states_in = self._with_zero_rnn_states(
                    self.states, int(inputs[0].shape[0]))
                frozen = {k: p for k, p in self.params.items()
                          if k != name}
                self.params[name], upd_state, loss = jit_step(
                    self.params[name], frozen, states_in, upd_state,
                    inputs, jnp.asarray(self.iteration_count), rng)
                self._score = loss
                self.iteration_count += 1
        self.updater_states[name] = upd_state
        return self

    def _next_rng(self):
        """Pooled rng keys: one eager threefry split per 64 iterations
        instead of one per step (the eager split showed up as ~3ms of
        host time per step in the ResNet-50 profile)."""
        pool = getattr(self, "_rng_pool", None)
        if not pool:
            keys = jax.random.split(self._rng, 65)
            self._rng = keys[0]
            self._rng_pool = list(keys[1:])
            pool = self._rng_pool
        return pool.pop()

    # ------------------------------------------------------------------
    def fit_steps(self, ds, steps: int):
        """Run ``steps`` train iterations on one device-resident batch
        in ONE jit dispatch (lax.fori_loop over the compiled step — the
        Keras ``steps_per_execution`` idea). Removes the per-step host
        dispatch gap entirely; BN stats/updater state/iteration advance
        exactly as ``steps`` calls of fit() would. Listeners fire once
        per group with the final loss. Masks are not supported on this
        fast path — use fit() for masked data."""
        if not self._initialized:
            self.init()
        self._sync_updater_layout()
        self._sync_param_layout()
        if self._train_step is None:
            self._build_train_step()
        if getattr(ds, "features_mask", None) is not None or \
                getattr(ds, "labels_mask", None) is not None:
            raise ValueError(
                "fit_steps does not support masked DataSets — padded "
                "timesteps would train as real data; use fit()")
        feats = ds.features if isinstance(ds.features, list) \
            else [ds.features]
        labs = ds.labels if isinstance(ds.labels, list) else [ds.labels]
        inputs = [_as_jnp(x, self._dtype) for x in feats]
        labels = [_as_jnp(y, self._dtype) for y in labs]

        if not hasattr(self, "_multi_steps"):
            self._multi_steps = {}
        if steps not in self._multi_steps:
            step_fn = self._step_fn

            def multi(params, states, upd, inputs, labels, it0, rng):
                def body(i, carry):
                    p, s, u, _, _ = carry
                    r = jax.random.fold_in(rng, i)
                    return step_fn(p, s, u, inputs, labels, None, None,
                                   it0 + i, r)

                # loss carry must match step_fn's loss dtype (bf16 nets
                # produce a bf16 loss); grad-norm carry is f32
                zero = jnp.zeros((), self._dtype)
                gz = jnp.zeros((), jnp.float32)
                return jax.lax.fori_loop(
                    0, steps, body,
                    (params, states, upd, zero, gz))

            self._multi_steps[steps] = jax.jit(multi,
                                               donate_argnums=(0, 1, 2))

        states_in = self._with_zero_rnn_states(self.states,
                                               int(inputs[0].shape[0]))
        rng = self._next_rng()
        from deeplearning4j_tpu.common import diagnostics, telemetry
        with telemetry.step_span("ComputationGraph", steps=steps) as sp:
            self.params, new_states, self.updater_states, loss, gnorm = \
                self._multi_steps[steps](self.params, states_in,
                                         self.updater_states, inputs,
                                         labels,
                                         jnp.asarray(
                                             self.iteration_count),
                                         rng)
        self.states = self._strip_rnn_states(new_states)
        self._score = loss
        self.last_batch_size = int(inputs[0].shape[0])
        self.iteration_count += steps
        # one record per group: the final step's loss/grad norm stand
        # in for the window (the fori_loop body is opaque to the host)
        diagnostics.after_step(
            self, "ComputationGraph", self.iteration_count - 1, loss,
            sp, grad_norm=gnorm if self._step_gnorm else None,
            params=self.params, steps=steps)
        for lis in self.listeners:
            lis.iteration_done(self, self.iteration_count - 1,
                               self.epoch_count)
        return self

    def _fit_dataset(self, ds):
        feats = ds.features if isinstance(ds.features, list) \
            else [ds.features]
        labs = ds.labels if isinstance(ds.labels, list) else [ds.labels]
        self._fit_batch(feats, labs, self._ds_fmask(ds),
                        self._ds_lmasks(ds))

    def _fit_batch(self, inputs: list, labels: list, fmask, lmasks):
        inputs = [_as_jnp(x, self._dtype) for x in inputs]
        labels = [_as_jnp(y, self._dtype) for y in labels]
        fmask = _as_jnp(fmask) if fmask is not None else None
        if lmasks is not None:
            lmasks = [(_as_jnp(m) if m is not None else None)
                      for m in lmasks]
        if self._retrace_guard is None:
            from deeplearning4j_tpu.common.compilecache import RetraceGuard
            self._retrace_guard = RetraceGuard(
                f"{type(self).__name__} train step")
        self._retrace_guard.record(inputs, labels, fmask, lmasks)
        # layer_report() with no batch re-lowers at the last fit shape
        self._layerprof_shapes = (
            [(x.shape, x.dtype) for x in inputs],
            [(y.shape, y.dtype) for y in labels])
        from deeplearning4j_tpu.nn.conf.builders import BackpropType
        if self.conf.backprop_type is BackpropType.TRUNCATED_BPTT and \
                inputs[0].ndim == 3:
            return self._fit_tbptt(inputs, labels, fmask, lmasks)
        if self._accum_steps > 1:
            return self._fit_batch_accum(inputs, labels, fmask, lmasks)
        rng = self._next_rng()
        states_in = self._with_zero_rnn_states(self.states,
                                               int(inputs[0].shape[0]))
        from deeplearning4j_tpu.common import diagnostics, telemetry
        with telemetry.step_span("ComputationGraph") as sp:
            self.params, new_states, self.updater_states, loss, gnorm = \
                self._train_step(self.params, states_in,
                                 self.updater_states, inputs, labels,
                                 fmask, lmasks,
                                 jnp.asarray(self.iteration_count), rng)
        self.states = self._strip_rnn_states(new_states)
        self._score = loss          # device scalar; float() on read
        self.last_batch_size = int(inputs[0].shape[0])
        # grads never leave the fused step; a trip attributes the first
        # bad leaf in the (poisoned) post-update params
        diagnostics.after_step(
            self, "ComputationGraph", self.iteration_count, loss, sp,
            grad_norm=gnorm if self._step_gnorm else None,
            params=self.params)
        self.iteration_count += 1
        for lis in self.listeners:
            lis.iteration_done(self, self.iteration_count - 1,
                               self.epoch_count)

    def _fit_batch_accum(self, inputs: list, labels: list, fmask,
                         lmasks):
        """Accumulation micro-step: backward + gradient add only; the
        updater fires once per ``_accum_steps`` window on the mean
        gradient with updater iteration = number of updates APPLIED
        (Adam bias correction must see update indices)."""
        rng = self._next_rng()
        states_in = self._with_zero_rnn_states(self.states,
                                               int(inputs[0].shape[0]))
        from deeplearning4j_tpu.common import diagnostics, telemetry
        with telemetry.step_span("ComputationGraph",
                                 accumulating=self._accum_steps) as sp:
            grads, new_states, loss, gnorm = self._grad_step(
                self.params, states_in, inputs, labels, fmask, lmasks,
                rng)
            # watchdog check BEFORE accumulate/apply: the apply step
            # donates the accumulated-grad buffers this micro-batch's
            # grads may alias
            diagnostics.check_numerics(
                self, "ComputationGraph", self.iteration_count, loss,
                grad_norm=gnorm if self._step_gnorm else None,
                grads=grads)
            self._accum_grads = (grads if self._accum_grads is None
                                 else self._accum_add(self._accum_grads,
                                                      grads))
            self._accum_count += 1
            if self._accum_count >= self._accum_steps:
                self._apply_accumulated()
        self.states = self._strip_rnn_states(new_states)
        self._score = loss          # device scalar; float() on read
        self.last_batch_size = int(inputs[0].shape[0])
        diagnostics.record_step(
            self, "ComputationGraph", self.iteration_count, loss, sp,
            grad_norm=gnorm if self._step_gnorm else None)
        self.iteration_count += 1
        for lis in self.listeners:
            lis.iteration_done(self, self.iteration_count - 1,
                               self.epoch_count)

    def _fit_tbptt(self, inputs: list, labels: list, fmask, lmasks):
        """tBPTT segmentation over the time axis (SURVEY.md section 5.7);
        same carry/truncation semantics as MultiLayerNetwork._fit_tbptt."""
        L = self.conf.tbptt_fwd_length
        T = inputs[0].shape[1]
        states = self._with_zero_rnn_states(self.states,
                                            int(inputs[0].shape[0]))
        for t0 in range(0, T, L):
            seg_in = [x[:, t0:t0 + L] if x.ndim >= 3 else x
                      for x in inputs]
            seg_lab = [y[:, t0:t0 + L] if y.ndim >= 3 else y
                       for y in labels]
            seg_f = fmask[:, t0:t0 + L] if fmask is not None and \
                fmask.ndim >= 2 else fmask
            seg_l = None
            if lmasks is not None:
                seg_l = [m[:, t0:t0 + L] if m is not None and
                         m.ndim >= 2 else m for m in lmasks]
            self._rng, rng = jax.random.split(self._rng)
            self.params, states, self.updater_states, loss, gnorm = \
                self._train_step(self.params, states,
                                 self.updater_states, seg_in, seg_lab,
                                 seg_f, seg_l,
                                 jnp.asarray(self.iteration_count), rng)
            self._score = loss          # device scalar; float() on read
            from deeplearning4j_tpu.common import diagnostics
            diagnostics.after_step(
                self, "ComputationGraph", self.iteration_count, loss,
                None, grad_norm=gnorm if self._step_gnorm else None,
                params=self.params, tbptt_segment=t0 // L)
            self.iteration_count += 1
        self.states = self._strip_rnn_states(states)
        self.last_batch_size = int(inputs[0].shape[0])
        for lis in self.listeners:
            lis.iteration_done(self, self.iteration_count - 1,
                               self.epoch_count)

    # ------------------------------------------------------------------
    def output(self, *inputs, train: bool = False, mask=None):
        """Returns list of output activations (single array if one
        output) — reference: ComputationGraph.output(INDArray...)."""
        if not self._initialized:
            self.init()
        xs = [_as_jnp(x, self._dtype) for x in inputs]
        mask = _as_jnp(mask) if mask is not None else None
        acts, _ = self._forward(self.dense_params(), self.states, xs,
                                training=train, rng=None,
                                want_logits=False, fmask=mask)
        outs = [acts[n] for n in self.conf.network_outputs]
        return outs[0] if len(outs) == 1 else outs

    def outputs(self, *inputs, train: bool = False, mask=None) -> list:
        """Always-a-list variant (reference: ComputationGraph.output
        returns INDArray[] regardless of output count)."""
        out = self.output(*inputs, train=train, mask=mask)
        return out if isinstance(out, list) else [out]

    def predict(self, *inputs) -> np.ndarray:
        out = self.output(*inputs)
        if isinstance(out, list):
            out = out[0]
        return np.asarray(jnp.argmax(out, axis=-1))

    # -- stateful streaming inference (SURVEY.md section 5.7;
    #    reference: ComputationGraph.rnnTimeStep) -----------------------
    def rnn_time_step(self, *inputs):
        """Feed one step (2D inputs) or a chunk (3D inputs) of a
        sequence through the DAG, carrying every recurrent vertex's
        hidden state across calls (reference: rnnTimeStep).  2D
        inputs get 2D outputs (the last timestep); 3D chunks return
        full per-step activations."""
        from deeplearning4j_tpu.nn.conf.layers_recurrent import (
            Bidirectional)
        for n in self._topo:
            v = self.conf.vertices[n]
            if v.is_layer and isinstance(v.content, Bidirectional):
                # reference throws too: the backward direction needs
                # future timesteps, which streaming cannot provide
                raise ValueError(
                    "rnnTimeStep is not supported on graphs with "
                    "Bidirectional layers")
        if not self._initialized:
            self.init()
        xs = [_as_jnp(x, self._dtype) for x in inputs]
        # only RECURRENT inputs get the step-dim treatment: a graph
        # can also carry genuinely feed-forward inputs (e.g. static
        # metadata merged after LastTimeStep) that must pass through
        # 2D, exactly as output() passes them
        from deeplearning4j_tpu.nn.conf.inputs import InputTypeRecurrent
        rec = [isinstance(t, InputTypeRecurrent)
               for t in self.conf.input_types] or [True] * len(xs)
        if len(rec) != len(xs):
            raise ValueError(
                f"rnnTimeStep got {len(xs)} inputs for "
                f"{len(rec)} declared network inputs")
        single_step = all(x.ndim == 2 for x, r in zip(xs, rec) if r)
        xs = [x[:, None, :] if r and x.ndim == 2 else x
              for x, r in zip(xs, rec)]
        batch = int(xs[0].shape[0])
        if getattr(self, "_rnn_stream_states", None) is None:
            self._rnn_stream_states = self._with_zero_rnn_states(
                self.states, batch)
            self._rnn_stream_batch = batch
        elif batch != self._rnn_stream_batch:
            raise ValueError(
                f"rnnTimeStep batch size {batch} != stored state "
                f"batch size {self._rnn_stream_batch}; call "
                f"rnn_clear_previous_state() first")
        acts, new_states = self._forward(
            self.dense_params(), self._rnn_stream_states, xs,
            training=False, rng=None, want_logits=False)
        # keep persistent (BN) states as-is; update only rnn carries
        merged = dict(self._rnn_stream_states)
        for k in self._recurrent_names():
            merged[k] = new_states[k]
        self._rnn_stream_states = merged
        outs = [acts[n] for n in self.conf.network_outputs]
        if single_step:
            outs = [o[:, -1] if o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self):
        self._rnn_stream_states = None

    def rnn_get_previous_state(self, vertex_name: str):
        """Stored streaming state of one recurrent vertex, by name
        (reference: rnnGetPreviousState(String))."""
        if getattr(self, "_rnn_stream_states", None) is None:
            return None
        return self._rnn_stream_states.get(vertex_name)

    def rnn_set_previous_state(self, vertex_name: str, state: dict):
        """Overwrite one vertex's streaming state (reference:
        rnnSetPreviousState).  Works on a fresh network too: the
        batch size is inferred from the provided state arrays."""
        if not self._initialized:
            self.init()
        if vertex_name not in self._recurrent_names():
            raise ValueError(
                f"'{vertex_name}' is not a recurrent vertex "
                f"(recurrent: {self._recurrent_names()})")
        leaves = jax.tree_util.tree_leaves(state)
        if not leaves:
            raise ValueError("cannot infer batch size from an "
                             "empty state dict")
        batch = int(leaves[0].shape[0])
        if getattr(self, "_rnn_stream_states", None) is None:
            self._rnn_stream_states = self._with_zero_rnn_states(
                self.states, batch)
            self._rnn_stream_batch = batch
        elif batch != self._rnn_stream_batch:
            raise ValueError(
                f"rnnSetPreviousState batch size {batch} != stored "
                f"state batch size {self._rnn_stream_batch}; call "
                f"rnn_clear_previous_state() first")
        self._rnn_stream_states = dict(self._rnn_stream_states)
        self._rnn_stream_states[vertex_name] = state

    @staticmethod
    def _ds_fmask(ds):
        """First features mask, honoring both the MultiDataSet plural
        (features_masks) and DataSet singular (features_mask) attrs —
        same lookup order as _fit_dataset."""
        ms = getattr(ds, "features_masks", None)
        if ms:
            return ms[0]
        return getattr(ds, "features_mask", None)

    @staticmethod
    def _ds_lmasks(ds):
        ms = getattr(ds, "labels_masks", None)
        if ms is not None:
            return ms
        lm = getattr(ds, "labels_mask", None)
        return [lm] if lm is not None else None

    def score(self, dataset=None) -> float:
        if dataset is None:
            return float(self._score)
        feats = dataset.features if isinstance(dataset.features, list) \
            else [dataset.features]
        labs = dataset.labels if isinstance(dataset.labels, list) \
            else [dataset.labels]
        xs = [_as_jnp(x, self._dtype) for x in feats]
        ys = [_as_jnp(y, self._dtype) for y in labs]
        lmasks = self._ds_lmasks(dataset)
        fmask = self._ds_fmask(dataset)
        params = self.dense_params()
        acts, _ = self._forward(
            params, self.states, xs, training=False, rng=None,
            want_logits=True,
            fmask=_as_jnp(fmask) if fmask is not None else None)
        loss = self._regularization(params)
        out_confs = self.output_layer_confs()
        for i, out_name in enumerate(self.conf.network_outputs):
            layer = out_confs.get(out_name)
            if layer is None:
                continue
            loss = loss + layer.compute_loss(
                ys[i], acts[out_name], from_logits=layer.wants_logits(),
                mask=lmasks[i] if lmasks is not None else None)
        return float(loss)

    def evaluate(self, iterator):
        from deeplearning4j_tpu.evaluation import Evaluation
        ev = Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            feats = ds.features if isinstance(ds.features, list) \
                else [ds.features]
            out = self.output(*feats, mask=self._ds_fmask(ds))
            if isinstance(out, list):
                out = out[0]
            lmasks = self._ds_lmasks(ds)
            ev.eval(ds.labels if not isinstance(ds.labels, list)
                    else ds.labels[0], out,
                    mask=lmasks[0] if lmasks else None)
        return ev

    # ------------------------------------------------------------------
    def num_params(self) -> int:
        return int(sum(np.prod(p.shape) for p in
                       jax.tree_util.tree_leaves(self.dense_params())))

    def param_table(self) -> dict:
        out = {}
        params = self.dense_params()
        for name in self._topo:
            for pname, p in params.get(name, {}).items():
                out[f"{name}_{pname}"] = p
        return out

    def layer_report(self, data=None, labels=None, **roofline_kw):
        """Per-vertex flops/bytes/roofline attribution of the compiled
        train step (common.layerprof): lowers the jitted step at the
        given batch (or the last fitted batch's shapes), partitions
        ``cost_analysis()`` by the ``dl4j.<vertex>`` scopes, and joins
        the kernel-select decisions recorded at trace time.  Also
        published to ``GET /api/layers`` and the ``dl4j_layer_*``
        metrics.  Lowering only — nothing executes, buffers are not
        donated."""
        if not self._initialized:
            self.init()
        self._sync_updater_layout()
        self._sync_param_layout()
        if self._train_step is None:
            self._build_train_step()
        if data is not None and hasattr(data, "features"):
            labels = data.labels
            data = data.features
        if data is None:
            shapes = getattr(self, "_layerprof_shapes", None)
            if shapes is None:
                raise ValueError(
                    "layer_report needs a batch: pass (data, labels) "
                    "or fit at least one batch first")
            xs, ys = shapes
            data = [np.zeros(s, dtype=d) for s, d in xs]
            labels = [np.zeros(s, dtype=d) for s, d in ys]
        if not isinstance(data, list):
            data = [data]
        if not isinstance(labels, list):
            labels = [labels]
        inputs = [_as_jnp(x, self._dtype) for x in data]
        labs = [_as_jnp(y, self._dtype) for y in labels]
        states_in = self._with_zero_rnn_states(
            self.states, int(inputs[0].shape[0]))
        lowered = self._train_step.lower(
            self.params, states_in, self.updater_states, inputs, labs,
            None, None, jnp.asarray(0), jax.random.PRNGKey(0))
        types = {layerprof.sanitize(n):
                 type(self.conf.vertices[n].content).__name__
                 for n in self._topo}
        return layerprof.attribute_compiled(
            lowered.compile(), model_name=type(self).__name__,
            layer_types=types, **roofline_kw)

    def summary(self) -> str:
        lines = [f"{'vertex':<28} {'type':<22} {'inputs':<28} {'params':<10}"]
        total = 0
        params = self.dense_params()
        for name in self._topo:
            v = self.conf.vertices[name]
            n = int(sum(np.prod(p.shape)
                        for p in params.get(name, {}).values()))
            total += n
            lines.append(f"{name:<28} {type(v.content).__name__:<22} "
                         f"{','.join(v.inputs):<28} {n:<10}")
        lines.append(f"Total params: {total}")
        return "\n".join(lines)
