"""Object-detection output layer (SURVEY.md D4:
`org.deeplearning4j.nn.conf.layers.objdetect.Yolo2OutputLayer`).

YOLOv2 loss head. TPU-first data layout (NHWC end-to-end):

- predictions: [b, H, W, A*(5+C)] from the final conv — per anchor
  (tx, ty, tw, th, to) + C class scores;
- labels: [b, H, W, 4+C] — per grid cell: (cx, cy, w, h) of the
  object centered in that cell, in *cell units* (cx, cy in [0,1]
  relative to the cell; w, h in grid units), then a one-hot class.
  A cell with no object is all zeros. (The reference uses
  [mb, 4+C, H, W] NCHW; the content is the same.)

Loss (Redmon & Farhadi, YOLO9000 §2): the anchor with best IoU
against the ground-truth box is responsible — coordinate MSE +
objectness-vs-IoU MSE + class cross-entropy on it; other anchors pay
lambda_noobj * sigmoid(to)^2.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (InputType,
                                               InputTypeConvolutional)
from deeplearning4j_tpu.nn.conf.layers import (BaseOutputLayer,
                                               register_layer)


@register_layer
@dataclass
class Yolo2OutputLayer(BaseOutputLayer):
    """reference: objdetect.Yolo2OutputLayer.Builder()
    .boundingBoxPriors(anchors).lambdaCoord(5).lambdaNoObj(0.5)."""

    anchors: Tuple[Tuple[float, float], ...] = ((1.0, 1.0),
                                               (2.0, 2.0))
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def get_output_type(self, input_type):
        return input_type

    def wants_logits(self) -> bool:
        return False

    def forward(self, params, x, *, training, rng=None, state=None):
        return x, state

    # -- loss ---------------------------------------------------------
    def _decode(self, preds):
        """[b,H,W,A*(5+C)] -> xy [.,A,2] wh [.,A,2] obj [.,A] cls."""
        b, h, w, _ = preds.shape
        a = len(self.anchors)
        p = preds.reshape(b, h, w, a, -1)
        xy = jax.nn.sigmoid(p[..., 0:2])           # within-cell offset
        anchors = jnp.asarray(self.anchors)        # [A, 2] grid units
        wh = jnp.exp(jnp.clip(p[..., 2:4], -8, 8)) * anchors
        obj = p[..., 4]
        cls = p[..., 5:]
        return xy, wh, obj, cls

    @staticmethod
    def _iou(wh_a, wh_b, xy_a, xy_b):
        """IoU of boxes sharing a coordinate frame (grid units)."""
        lt = jnp.maximum(xy_a - wh_a / 2, xy_b - wh_b / 2)
        rb = jnp.minimum(xy_a + wh_a / 2, xy_b + wh_b / 2)
        inter = jnp.prod(jnp.clip(rb - lt, 0), -1)
        ua = jnp.prod(wh_a, -1) + jnp.prod(wh_b, -1) - inter
        return inter / jnp.maximum(ua, 1e-9)

    def compute_loss(self, labels, preds, *, from_logits=False,
                     mask=None, average=True):
        xy, wh, obj, cls = self._decode(preds)       # [b,h,w,A,*]
        gt_xy = labels[..., None, 0:2]               # [b,h,w,1,2]
        gt_wh = labels[..., None, 2:4]
        gt_cls = labels[..., 4:]                     # [b,h,w,C]
        has_obj = (jnp.sum(labels[..., 2:4], -1) > 0)  # [b,h,w]

        iou = self._iou(wh, jnp.broadcast_to(gt_wh, wh.shape),
                        xy, jnp.broadcast_to(gt_xy, xy.shape))
        resp = jax.nn.one_hot(jnp.argmax(iou, -1),
                              iou.shape[-1])         # [b,h,w,A]
        resp = resp * has_obj[..., None]

        coord = jnp.sum(resp[..., None] *
                        (jnp.square(xy - gt_xy)
                         + jnp.square(jnp.sqrt(wh)
                                      - jnp.sqrt(jnp.maximum(
                                          gt_wh, 1e-9)))), (-2, -1))
        obj_s = jax.nn.sigmoid(obj)
        obj_loss = jnp.sum(resp * jnp.square(
            obj_s - jax.lax.stop_gradient(iou)), -1)
        noobj_loss = jnp.sum((1 - resp) * jnp.square(obj_s), -1)
        logp = jax.nn.log_softmax(cls, -1)
        cls_loss = -jnp.sum(resp * jnp.sum(
            gt_cls[..., None, :] * logp, -1), -1)

        per_cell = (self.lambda_coord * coord + obj_loss
                    + self.lambda_no_obj * noobj_loss + cls_loss)
        loss = jnp.sum(per_cell, (1, 2))             # per example
        return jnp.mean(loss) if average else loss
