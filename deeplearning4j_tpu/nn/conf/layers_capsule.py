"""Capsule network layers (Sabour et al. dynamic routing).

Reference parity: ``org.deeplearning4j.nn.conf.layers.{PrimaryCapsules,
CapsuleLayer,CapsuleStrengthLayer}`` (SameDiff-defined layers in the
reference). Capsule tensors ride the recurrent input-type convention the
reference also uses: [b, n_capsules, capsule_dim] == recurrent(size=dim,
timesteps=n_caps).

TPU-first: routing iterations are a static Python unroll (fixed count →
XLA sees straight-line code and fuses the softmax/agreement chain); the
prediction tensor einsum maps to one large MXU contraction.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (InputType,
                                               InputTypeConvolutional,
                                               InputTypeRecurrent)
from deeplearning4j_tpu.nn.conf.layers import Layer, _pair, register_layer
from deeplearning4j_tpu.nn.weights import WeightInit


def _squash(s, axis=-1, eps=1e-8):
    """v = ||s||^2/(1+||s||^2) * s/||s|| — the capsule nonlinearity."""
    n2 = jnp.sum(s * s, axis=axis, keepdims=True)
    return (n2 / (1.0 + n2)) * s / jnp.sqrt(n2 + eps)


@register_layer
@dataclass
class PrimaryCapsules(Layer):
    """Conv -> capsule reshape -> squash (reference: PrimaryCapsules).
    ``capsules`` * ``capsule_dimensions`` output channels."""

    capsule_dimensions: int = 8
    channels: int = 32                      # capsule groups
    kernel_size: Tuple[int, int] = (9, 9)
    stride: Tuple[int, int] = (2, 2)
    has_bias: bool = True

    def __post_init__(self):
        super().__post_init__()
        self.kernel_size = _pair(self.kernel_size)
        self.stride = _pair(self.stride)

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeConvolutional) and \
                (override or not self.n_in):
            self.n_in = input_type.channels

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = self.kernel_size
        c_out = self.channels * self.capsule_dimensions
        wi = self.weight_init or WeightInit.XAVIER
        p = {"W": wi.init(key, (kh, kw, self.n_in, c_out),
                          kh * kw * self.n_in, kh * kw * c_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((c_out,), dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        z = jax.lax.conv_general_dilated(
            x, params["W"], window_strides=self.stride, padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"]
        b, h, w, _ = z.shape
        caps = z.reshape(b, h * w * self.channels,
                         self.capsule_dimensions)
        return _squash(caps), state

    def _out_hw(self, input_type):
        kh, kw = self.kernel_size
        sh, sw = self.stride
        return ((input_type.height - kh) // sh + 1,
                (input_type.width - kw) // sw + 1)

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional)
        oh, ow = self._out_hw(input_type)
        return InputType.recurrent(self.capsule_dimensions,
                                   oh * ow * self.channels)


@register_layer
@dataclass
class CapsuleLayer(Layer):
    """Fully-connected capsules with dynamic routing (reference:
    CapsuleLayer; ``capsules`` output capsules of ``capsule_dimensions``
    dims, ``routings`` iterations)."""

    capsules: int = 10
    capsule_dimensions: int = 16
    routings: int = 3
    #: detach u_hat in the routing-logit updates (Sabour et al.'s
    #: u_hat_stopped trick). Default False = fully differentiable,
    #: matching the reference's SameDiff routing loop, which contains
    #: no gradient-stop op — and making analytic gradients equal the
    #: numeric check.
    stop_routing_gradients: bool = False

    def set_n_in(self, input_type, override):
        assert isinstance(input_type, InputTypeRecurrent)
        self._in_caps = input_type.timesteps
        self._in_dim = input_type.size

    def init_params(self, key, input_type, dtype=jnp.float32):
        self.set_n_in(input_type, override=False)
        wi = self.weight_init or WeightInit.XAVIER
        # [in_caps, out_caps, out_dim, in_dim] prediction transforms
        fan_in = self._in_dim
        fan_out = self.capsule_dimensions
        return {"W": wi.init(key, (self._in_caps, self.capsules,
                                   self.capsule_dimensions, self._in_dim),
                             fan_in, fan_out, dtype)}

    def forward(self, params, x, *, training, rng=None, state=None):
        # x: [b, in_caps, in_dim]; u_hat: [b, in_caps, out_caps, out_dim]
        u_hat = jnp.einsum("bid,iokd->biok", x, params["W"])
        # routing logits b_ij: [b, in_caps, out_caps]
        logits = jnp.zeros(u_hat.shape[:3], u_hat.dtype)
        v = None
        u_route = (jax.lax.stop_gradient(u_hat)
                   if self.stop_routing_gradients else u_hat)
        for it in range(self.routings):
            c = jax.nn.softmax(logits, axis=2)
            s = jnp.einsum("bio,biok->bok", c, u_hat)
            v = _squash(s)
            if it < self.routings - 1:
                logits = logits + jnp.einsum("biok,bok->bio", u_route, v)
        return v, state

    def get_output_type(self, input_type):
        return InputType.recurrent(self.capsule_dimensions, self.capsules)


@register_layer
@dataclass
class CapsuleStrengthLayer(Layer):
    """Capsule norm: [b, caps, dim] -> [b, caps] class-probability
    lengths (reference: CapsuleStrengthLayer)."""

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        return jnp.sqrt(jnp.sum(x * x, axis=-1) + 1e-12), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeRecurrent)
        return InputType.feed_forward(input_type.timesteps)
