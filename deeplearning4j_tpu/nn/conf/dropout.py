"""Dropout variants + weight noise (SURVEY.md D1/D4 regularization).

Reference parity: ``org.deeplearning4j.nn.conf.dropout.{Dropout,
GaussianDropout,GaussianNoise,AlphaDropout,SpatialDropout}`` (the
IDropout hierarchy — a layer's ``dropout`` can be any of these, not
just a retain probability) and ``conf.weightnoise.{WeightNoise,
DropConnect}`` (noise applied to the *parameters* each forward pass).

All are pure functions of (x, rng): stateless, jit-friendly, applied
inside the compiled step.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


class IDropout:
    """Base activation-noise interface (reference: conf.dropout
    IDropout)."""

    def apply(self, x, rng):
        raise NotImplementedError

    # -- serde ----------------------------------------------------------
    def to_map(self) -> dict:
        d = {"@class": type(self).__name__}
        d.update(self.__dict__)
        return d

    @staticmethod
    def from_map(d: dict) -> "IDropout":
        d = dict(d)
        return _REGISTRY[d.pop("@class")](**d)


@dataclass
class Dropout(IDropout):
    """Inverted dropout; ``p`` is the RETAIN probability (the
    reference's convention)."""

    p: float = 0.5

    def apply(self, x, rng):
        keep = jax.random.bernoulli(rng, self.p, x.shape)
        return jnp.where(keep, x / self.p, 0.0)


@dataclass
class GaussianDropout(IDropout):
    """Multiplicative gaussian noise N(1, rate/(1-rate)) (reference:
    GaussianDropout)."""

    rate: float = 0.1

    def apply(self, x, rng):
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        return x * (1.0 + stddev *
                    jax.random.normal(rng, x.shape, x.dtype))


@dataclass
class GaussianNoise(IDropout):
    """Additive gaussian noise N(0, stddev) (reference: GaussianNoise)."""

    stddev: float = 0.1

    def apply(self, x, rng):
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)


@dataclass
class AlphaDropout(IDropout):
    """SELU-preserving dropout (reference: AlphaDropout; Klambauer et
    al.): dropped units take the value alpha', and an affine correction
    keeps mean/variance at (0, 1). ``p`` is the retain probability."""

    p: float = 0.95

    # fixed-point constants of SELU
    _ALPHA = 1.6732632423543772
    _SCALE = 1.0507009873554805

    def apply(self, x, rng):
        ap = -self._ALPHA * self._SCALE
        keep = jax.random.bernoulli(rng, self.p, x.shape)
        a = (self.p + ap * ap * self.p * (1 - self.p)) ** -0.5
        b = -a * ap * (1 - self.p)
        return a * jnp.where(keep, x, ap) + b


@dataclass
class SpatialDropout(IDropout):
    """Drop whole feature maps/channels (reference: SpatialDropout):
    one keep/drop decision per trailing-channel per example. ``p`` is
    the retain probability."""

    p: float = 0.5

    def apply(self, x, rng):
        shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        keep = jax.random.bernoulli(rng, self.p, shape)
        return jnp.where(keep, x / self.p, 0.0)


@dataclass
class WeightNoise:
    """Parameter noise applied each training forward pass (reference:
    conf.weightnoise.WeightNoise with a gaussian distribution, or
    DropConnect via ``is_dropconnect``). ``additive`` gaussian N(0,
    stddev) or multiplicative N(1, stddev); DropConnect zeroes weights
    with probability 1-p instead."""

    stddev: float = 0.05
    additive: bool = True
    apply_to_bias: bool = False
    is_dropconnect: bool = False
    p: float = 0.5              # DropConnect retain probability

    def apply(self, params: dict, rng) -> dict:
        out = {}
        for name, w in params.items():
            if not self.apply_to_bias and name in ("b", "gamma", "beta"):
                out[name] = w
                continue
            rng, sub = jax.random.split(rng)
            if isinstance(w, dict):        # wrapper sub-trees
                out[name] = self.apply(w, sub)
            elif self.is_dropconnect:
                keep = jax.random.bernoulli(sub, self.p, w.shape)
                out[name] = jnp.where(keep, w / self.p, 0.0)
            elif self.additive:
                out[name] = w + self.stddev * jax.random.normal(
                    sub, w.shape, w.dtype)
            else:
                out[name] = w * (1.0 + self.stddev * jax.random.normal(
                    sub, w.shape, w.dtype))
        return out

    def to_map(self) -> dict:
        d = {"@class": type(self).__name__}
        d.update(self.__dict__)
        return d

    @staticmethod
    def from_map(d: dict) -> "WeightNoise":
        d = dict(d)
        d.pop("@class", None)
        return WeightNoise(**d)


_REGISTRY = {c.__name__: c for c in
             (Dropout, GaussianDropout, GaussianNoise, AlphaDropout,
              SpatialDropout)}
