"""Misc parameterised layers (SURVEY.md D4 long tail).

Reference parity: ``org.deeplearning4j.nn.conf.layers.{PReLULayer,
LocallyConnected1D,LocallyConnected2D,LocalResponseNormalization,
misc.ElementWiseMultiplicationLayer,RnnLossLayer}``.

LocallyConnected* in the reference are SameDiff-defined layers
(unshared-weight convolutions); here they lower to
``conv_general_dilated_patches`` + a per-position einsum — one XLA dot
that still lands on the MXU.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.nn.conf.inputs import (
    InputType, InputTypeConvolutional, InputTypeRecurrent)
from deeplearning4j_tpu.nn.conf.layers import (
    BaseOutputLayer, ConvolutionMode, Layer, _pair, register_layer)
from deeplearning4j_tpu.nn.weights import WeightInit


@register_layer
@dataclass
class PReLULayer(Layer):
    """Parametric ReLU: y = max(x, 0) + alpha * min(x, 0) with learned
    per-feature alpha (reference: PReLULayer; ``shared_axes`` collapses
    alpha over those axes, e.g. (1, 2) shares across H, W)."""

    alpha_init: float = 0.0
    shared_axes: Optional[Tuple[int, ...]] = None

    def set_n_in(self, input_type, override):
        self._input_shape = input_type.shape(batch=1)[1:]

    def init_params(self, key, input_type, dtype=jnp.float32):
        shape = list(input_type.shape(batch=1)[1:])
        if self.shared_axes:
            for ax in self.shared_axes:
                shape[ax - 1] = 1
        return {"alpha": jnp.full(tuple(shape), self.alpha_init, dtype)}

    def forward(self, params, x, *, training, rng=None, state=None):
        a = params["alpha"]
        return jnp.maximum(x, 0) + a * jnp.minimum(x, 0), state

    def get_output_type(self, input_type):
        return input_type


@register_layer
@dataclass
class ElementWiseMultiplicationLayer(Layer):
    """y = activation(x ∘ w + b) — learned per-feature scale/shift
    (reference: misc.ElementWiseMultiplicationLayer; n_in == n_out)."""

    def __post_init__(self):
        super().__post_init__()
        if self.n_in and not self.n_out:
            self.n_out = self.n_in

    def set_n_in(self, input_type, override):
        if override or not self.n_in:
            self.n_in = self.n_out = input_type.arrays_per_example() \
                if not hasattr(input_type, "size") else input_type.size

    def init_params(self, key, input_type, dtype=jnp.float32):
        return {"W": jnp.ones((self.n_in,), dtype),
                "b": jnp.full((self.n_in,), self.bias_init, dtype)}

    def forward(self, params, x, *, training, rng=None, state=None):
        return self.activation(x * params["W"] + params["b"]), state

    def get_output_type(self, input_type):
        return input_type


@register_layer
@dataclass
class LocalResponseNormalization(Layer):
    """Cross-channel LRN (reference: conf.layers.
    LocalResponseNormalization, AlexNet-era): y = x / (k + alpha*sum)^beta
    over ``n`` adjacent channels."""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        half = self.n // 2
        sq = x * x
        # sum over a window of n channels via reduce_window on last axis
        win = [1] * (x.ndim - 1) + [self.n]
        s = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, tuple(win), (1,) * x.ndim,
            [(0, 0)] * (x.ndim - 1) + [(half, self.n - 1 - half)])
        return x / (self.k + self.alpha * s) ** self.beta, state

    def get_output_type(self, input_type):
        return input_type


@register_layer
@dataclass
class LocallyConnected2D(Layer):
    """Unshared-weight 2D convolution (reference: LocallyConnected2D, a
    SameDiff layer): every output position has its own kernel."""

    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    def __post_init__(self):
        super().__post_init__()
        self.kernel_size = _pair(self.kernel_size)
        self.stride = _pair(self.stride)
        self.padding = _pair(self.padding)

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeConvolutional) and \
                (override or not self.n_in):
            self.n_in = input_type.channels
        self._in_hw = (input_type.height, input_type.width)

    def _out_hw(self):
        h, w = self._in_hw
        kh, kw = self.kernel_size
        sh, sw = self.stride
        if self.convolution_mode is ConvolutionMode.SAME:
            return -(-h // sh), -(-w // sw)
        ph, pw = self.padding
        return (h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1

    def init_params(self, key, input_type, dtype=jnp.float32):
        self.set_n_in(input_type, override=False)
        oh, ow = self._out_hw()
        kh, kw = self.kernel_size
        fan = kh * kw * self.n_in
        wi = self.weight_init or WeightInit.XAVIER
        p = {"W": wi.init(key, (oh, ow, fan, self.n_out), fan,
                          kh * kw * self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((oh, ow, self.n_out), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        kh, kw = self.kernel_size
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            ph, pw = self.padding
            pad = [(ph, ph), (pw, pw)]
        # patches: [b, oh, ow, c*kh*kw]
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), self.stride, pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # conv_general_dilated_patches yields channel-major patch order
        # [c, kh, kw]; W was laid out to match (fan = kh*kw*c re-ordered
        # consistently at init since both sides are learned).
        z = jnp.einsum("bhwf,hwfo->bhwo", patches, params["W"])
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional)
        self._in_hw = (input_type.height, input_type.width)
        oh, ow = self._out_hw()
        return InputType.convolutional(oh, ow, self.n_out)


@register_layer
@dataclass
class LocallyConnected1D(Layer):
    """Unshared-weight temporal convolution on [b, t, f] (reference:
    LocallyConnected1D)."""

    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    def __post_init__(self):
        super().__post_init__()
        for f in ("kernel_size", "stride", "padding"):
            v = getattr(self, f)
            setattr(self, f, int(v[0] if isinstance(v, (tuple, list))
                                 else v))

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeRecurrent) and \
                (override or not self.n_in):
            self.n_in = input_type.size
        self._in_t = input_type.timesteps

    def _out_t(self):
        t, k, s = self._in_t, self.kernel_size, self.stride
        if self.convolution_mode is ConvolutionMode.SAME:
            return -(-t // s)
        return (t + 2 * self.padding - k) // s + 1

    def init_params(self, key, input_type, dtype=jnp.float32):
        self.set_n_in(input_type, override=False)
        ot = self._out_t()
        fan = self.kernel_size * self.n_in
        wi = self.weight_init or WeightInit.XAVIER
        p = {"W": wi.init(key, (ot, fan, self.n_out), fan,
                          self.kernel_size * self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((ot, self.n_out), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            pad = [(self.padding, self.padding)]
        patches = jax.lax.conv_general_dilated_patches(
            x, (self.kernel_size,), (self.stride,), pad,
            dimension_numbers=("NWC", "WIO", "NWC"))
        z = jnp.einsum("btf,tfo->bto", patches, params["W"])
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeRecurrent)
        self._in_t = input_type.timesteps
        return InputType.recurrent(self.n_out, self._out_t())


@register_layer
@dataclass
class RnnLossLayer(BaseOutputLayer):
    """Per-timestep loss-only head on [b, t, f] — no params (reference:
    RnnLossLayer; the per-timestep twin of LossLayer)."""

    activation: Activation = Activation.IDENTITY

    def has_params(self) -> bool:
        return False

    def accepts_mask(self) -> bool:
        return True

    def init_params(self, key, input_type, dtype=jnp.float32):
        return {}

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeRecurrent):
            self.n_in = self.n_out = input_type.size

    def get_output_type(self, input_type):
        return input_type

    def wants_logits(self) -> bool:
        return False

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        return self.activation(x), state

    def forward_logits(self, params, x, *, training, rng=None, state=None,
                       mask=None):
        return x, state


@register_layer
@dataclass
class LayerNormalization(Layer):
    """Layer normalization over the trailing (feature/channel) axis
    with learned per-feature gain/bias (reference: the Keras
    ``LayerNormalization`` import target; the reference's SameDiff
    ``standardize`` + gain/bias composition).  Works on [b, f],
    [b, t, f] and [b, h, w, c] — the normalized axis is always the
    last, which is the TPU lane dimension."""

    eps: float = 1e-3               # keras default epsilon
    scale: bool = True              # learn gamma
    center: bool = True             # learn beta

    def set_n_in(self, input_type, override):
        # trailing-axis feature count for every layout
        nf = getattr(input_type, "channels", None)
        if nf is None:
            nf = input_type.size
        if override or not self.n_in:
            self.n_in = nf
        self.n_out = self.n_in

    def has_params(self) -> bool:
        return self.scale or self.center

    def init_params(self, key, input_type, dtype=jnp.float32):
        p = {}
        if self.scale:
            p["gamma"] = jnp.ones((self.n_in,), dtype)
        if self.center:
            p["beta"] = jnp.zeros((self.n_in,), dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        x = self._maybe_dropout(x, training, rng)
        acc = jnp.promote_types(x.dtype, jnp.float32)
        xf = x.astype(acc)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + self.eps)
        if self.scale:
            y = y * params["gamma"].astype(acc)
        if self.center:
            y = y + params["beta"].astype(acc)
        return self.activation(y.astype(x.dtype)), state

    def get_output_type(self, input_type):
        return input_type


@register_layer
@dataclass
class UnitNormLayer(Layer):
    """L2-normalize the trailing axis (the Keras ``UnitNormalization``
    import target; layer form of L2NormalizeVertex)."""

    eps: float = 1e-12

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
        return x / jnp.maximum(n, self.eps), state

    def get_output_type(self, input_type):
        return input_type


@register_layer
@dataclass
class GroupNormalization(Layer):
    """Group normalization (Wu & He 2018; the Keras
    ``GroupNormalization`` import target): channels split into
    ``groups``, normalized over (group, spatial) with per-channel
    gain/bias.  ``groups=-1`` is instance norm (one group per
    channel); ``groups=1`` is layer norm over all channels+spatial."""

    groups: int = 32
    eps: float = 1e-3
    scale: bool = True
    center: bool = True

    def set_n_in(self, input_type, override):
        nf = getattr(input_type, "channels", None)
        if nf is None:
            nf = input_type.size
        if override or not self.n_in:
            self.n_in = nf
        self.n_out = self.n_in

    def has_params(self) -> bool:
        return self.scale or self.center

    def init_params(self, key, input_type, dtype=jnp.float32):
        p = {}
        if self.scale:
            p["gamma"] = jnp.ones((self.n_in,), dtype)
        if self.center:
            p["beta"] = jnp.zeros((self.n_in,), dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        x = self._maybe_dropout(x, training, rng)
        c = x.shape[-1]
        g = c if self.groups == -1 else self.groups
        if c % g:
            raise ValueError(f"channels {c} not divisible by "
                             f"groups {g}")
        acc = jnp.promote_types(x.dtype, jnp.float32)
        xf = x.astype(acc).reshape(x.shape[:-1] + (g, c // g))
        # normalize over (spatial..., channels-in-group) per example
        axes = tuple(range(1, x.ndim - 1)) + (x.ndim,)
        mu = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        y = ((xf - mu) * jax.lax.rsqrt(var + self.eps)) \
            .reshape(x.shape)
        if self.scale:
            y = y * params["gamma"].astype(acc)
        if self.center:
            y = y + params["beta"].astype(acc)
        return self.activation(y.astype(x.dtype)), state

    def get_output_type(self, input_type):
        return input_type


@register_layer
@dataclass
class ScaleOffsetLayer(Layer):
    """y = x * scale + offset (the Keras ``Rescaling`` import target;
    e.g. 1/255 pixel normalization baked into exported models).
    ``scale``/``offset`` may be scalars or broadcastable lists
    (per-channel normalization)."""

    scale: object = 1.0
    offset: object = 0.0

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def _coef(self, v, x):
        # floats stay WEAKLY typed (python scalar / f32 list): integer
        # pixel inputs promote to float instead of collapsing to
        # jnp.asarray(1/255, uint8) == 0
        if isinstance(v, (int, float)):
            return v
        return jnp.asarray(v, jnp.float32)

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        return x * self._coef(self.scale, x) \
            + self._coef(self.offset, x), state

    def get_output_type(self, input_type):
        return input_type


@register_layer
@dataclass
class ResizingLayer(Layer):
    """Spatial resize on [b, h, w, c] (the Keras ``Resizing`` import
    target)."""

    height: int = 224
    width: int = 224
    interpolation: str = "bilinear"

    def __post_init__(self):
        super().__post_init__()
        if self.interpolation not in ("bilinear", "nearest"):
            raise ValueError(
                f"ResizingLayer interpolation="
                f"'{self.interpolation}' unsupported "
                f"(bilinear|nearest)")

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        method = ("nearest" if self.interpolation == "nearest"
                  else "bilinear")
        # antialias=False matches tf.image.resize's default (keras
        # Resizing semantics); jax antialiases minification by default
        return jax.image.resize(
            x, (x.shape[0], self.height, self.width, x.shape[3]),
            method, antialias=False), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional)
        return InputType.convolutional(self.height, self.width,
                                       input_type.channels)
