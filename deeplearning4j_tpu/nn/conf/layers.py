"""Layer configurations + their functional runtime math.

Reference parity: ``org.deeplearning4j.nn.conf.layers.*`` (config classes,
SURVEY.md D1) and ``org.deeplearning4j.nn.layers.**`` (runtime twins, D4).
The reference splits config from runtime layer objects; here each config
dataclass *is* the runtime: it exposes pure functions

    init_params(key, input_type)            -> param dict
    init_state(input_type)                  -> state dict (e.g. BN stats)
    forward(params, x, training, rng, state) -> (y, new_state)
    get_output_type(input_type)             -> InputType

so the network compiles every layer into one jitted step (SURVEY.md §7:
"the layer-config API compiles into a single jitted train step"). There is
no helper seam (D5): cuDNN/oneDNN helpers are replaced by XLA lowerings —
``lax.conv_general_dilated`` / ``lax.reduce_window`` hit the TPU MXU/VPU
directly (BASELINE.json north star: "cuDNN helpers lower to XLA ops").

Layout: conv activations are NHWC, kernels HWIO (XLA:TPU native);
recurrent activations are [batch, time, features]. The reference's NCHW /
[b, f, t] layouts exist only at import boundaries.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning.updaters import IUpdater
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.inputs import (
    InputType, InputTypeConvolutional, InputTypeFeedForward,
    InputTypeRecurrent)
from deeplearning4j_tpu.nn.weights import WeightInit


class PoolingType(enum.Enum):
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


class ConvolutionMode(enum.Enum):
    """Reference: Strict/Truncate/Same. Truncate == XLA VALID."""
    STRICT = "strict"
    TRUNCATE = "truncate"
    SAME = "same"


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


class _Builder:
    """Fluent builder shim for reference-style ``Layer.Builder()`` chains."""

    def __init__(self, cls, *args, **kwargs):
        self._cls = cls
        self._kw = dict(kwargs)
        if args:  # positional kernel size etc. handled per-class
            self._kw.update(cls._builder_positional(*args))

    def __getattr__(self, name):
        def setter(*v):
            self._kw[name] = v[0] if len(v) == 1 else tuple(v)
            return self
        return setter

    def build(self):
        return self._cls(**self._kw)


@dataclass
class Layer:
    """Base layer config. Fields mirror BaseLayer/FeedForwardLayer."""

    n_in: int = 0
    n_out: int = 0
    activation: Activation = Activation.IDENTITY
    weight_init: Optional[WeightInit] = None      # None -> net default
    bias_init: float = 0.0
    updater: Optional[IUpdater] = None            # None -> net default
    l1: Optional[float] = None
    l2: Optional[float] = None
    #: float retain probability OR an IDropout variant (conf.dropout)
    dropout: object = None
    #: optional WeightNoise/DropConnect applied to params in training
    weight_noise: object = None
    #: post-update projections (lists of LayerConstraint); None -> net
    #: default. Reference: o.d.nn.conf.constraint + builder
    #: constrainWeights/constrainBias/constrainAllParameters
    constrain_weights: object = None
    constrain_bias: object = None
    constrain_all: object = None
    #: exact param-name scoping: {"W": [c...], "RW": [c...]} — the
    #: Keras import surface (kernel_constraint vs recurrent_constraint
    #: are per-param, like the reference's BaseConstraint param sets)
    constrain_params: object = None
    name: Optional[str] = None

    def __post_init__(self):
        # accept strings for enum-typed fields (reference: DL4J builders
        # take Activation.RELU; the string spelling is a convenience)
        for f in ("activation", "gate_activation"):
            v = getattr(self, f, None)
            if isinstance(v, str):
                setattr(self, f, Activation.from_name(v))
        if isinstance(self.weight_init, str):
            self.weight_init = WeightInit[self.weight_init.upper()]
        lf = getattr(self, "loss_function", None)
        if isinstance(lf, str):
            self.loss_function = LossFunction[lf.upper()]

    # -- builder parity --------------------------------------------------
    @classmethod
    def Builder(cls, *args, **kwargs) -> _Builder:  # noqa: N802
        return _Builder(cls, *args, **kwargs)

    @staticmethod
    def _builder_positional(*args) -> dict:
        return {}

    # -- runtime protocol ------------------------------------------------
    def has_params(self) -> bool:
        return True

    def has_state(self) -> bool:
        return False

    def is_recurrent(self) -> bool:
        """True for layers with transient per-sequence state (h/c)."""
        return False

    def accepts_mask(self) -> bool:
        """True if forward() takes a per-timestep mask kwarg."""
        return self.is_recurrent()

    def zero_state(self, batch: int, dtype=jnp.float32) -> dict:
        return {}

    def is_pretrain_param(self, name: str) -> bool:
        return False

    def init_params(self, key, input_type: InputType, dtype=jnp.float32):
        return {}

    def init_state(self, input_type: InputType, dtype=jnp.float32):
        return {}

    def forward(self, params, x, *, training: bool, rng=None, state=None):
        raise NotImplementedError

    def get_output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def set_n_in(self, input_type: InputType, override: bool):
        """Shape inference hook (reference: FeedForwardLayer.setNIn)."""
        if isinstance(input_type, InputTypeFeedForward) and \
                (override or not self.n_in):
            self.n_in = input_type.size

    # -- input dropout (reference applies dropout to layer *input*) ------
    def _maybe_dropout(self, x, training: bool, rng):
        if self.dropout is None or not training or rng is None:
            return x
        from deeplearning4j_tpu.nn.conf.dropout import IDropout
        if isinstance(self.dropout, IDropout):   # reference: IDropout
            return self.dropout.apply(x, rng)
        p = float(self.dropout)
        keep = jax.random.bernoulli(rng, p, x.shape)
        return jnp.where(keep, x / p, 0.0)

    # -- serde -----------------------------------------------------------
    def to_map(self) -> dict:
        from deeplearning4j_tpu.nn.conf.dropout import IDropout, \
            WeightNoise
        from deeplearning4j_tpu.nn.conf.constraints import \
            constraints_to_map
        d = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            if isinstance(v, enum.Enum):
                v = v.name
            elif isinstance(v, (IUpdater, IDropout, WeightNoise)):
                v = v.to_map()
            elif isinstance(v, LossFunction):
                v = v.name
            elif k in ("constrain_weights", "constrain_bias",
                       "constrain_all"):
                v = constraints_to_map(v)
            elif k == "constrain_params" and v is not None:
                v = {pk: constraints_to_map(pv) for pk, pv in v.items()}
            d[k] = v
        return d

    @staticmethod
    def from_map(d: dict) -> "Layer":
        d = dict(d)
        cls = LAYER_REGISTRY[d.pop("@class")]
        # enum-name strings for activation/weight_init/loss_function are
        # coerced by Layer.__post_init__; only non-Layer-field enums here
        for k, v in list(d.items()):
            if k == "updater" and isinstance(v, dict):
                d[k] = IUpdater.from_map(v)
            elif k == "dropout" and isinstance(v, dict):
                from deeplearning4j_tpu.nn.conf.dropout import IDropout
                d[k] = IDropout.from_map(v)
            elif k == "weight_noise" and isinstance(v, dict):
                from deeplearning4j_tpu.nn.conf.dropout import \
                    WeightNoise
                d[k] = WeightNoise.from_map(v)
            elif k in ("constrain_weights", "constrain_bias",
                       "constrain_all") and isinstance(v, list):
                from deeplearning4j_tpu.nn.conf.constraints import \
                    constraints_from_map
                d[k] = constraints_from_map(v)
            elif k == "constrain_params" and isinstance(v, dict):
                from deeplearning4j_tpu.nn.conf.constraints import \
                    constraints_from_map
                d[k] = {pk: constraints_from_map(pv)
                        for pk, pv in v.items()}
            elif k in ("pooling_type",) and isinstance(v, str):
                d[k] = PoolingType[v]
            elif k in ("convolution_mode",) and isinstance(v, str):
                d[k] = ConvolutionMode[v]
            elif isinstance(v, list):
                d[k] = tuple(v)
        return cls(**d)


# ---------------------------------------------------------------------------
@dataclass
class DenseLayer(Layer):
    """Fully connected layer (reference: conf.layers.DenseLayer /
    runtime layers.feedforward.dense.DenseLayer)."""

    has_bias: bool = True
    activation: Activation = Activation.SIGMOID

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        k1, _ = jax.random.split(key)
        p = {"W": wi.init(k1, (self.n_in, self.n_out),
                          self.n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


@dataclass
class ConvolutionLayer(Layer):
    """2D convolution (reference: conf.layers.ConvolutionLayer; runtime
    convolution.ConvolutionLayer with CudnnConvolutionHelper — here the
    lowering is ``lax.conv_general_dilated`` straight onto the MXU)."""

    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    @staticmethod
    def _builder_positional(*args) -> dict:
        # reference: ConvolutionLayer.Builder(kh, kw)
        if len(args) == 1:
            return {"kernel_size": _pair(args[0])}
        return {"kernel_size": (int(args[0]), int(args[1]))}

    def __post_init__(self):
        super().__post_init__()
        self.kernel_size = _pair(self.kernel_size)
        self.stride = _pair(self.stride)
        self.padding = _pair(self.padding)
        self.dilation = _pair(self.dilation)

    def _pad_cfg(self):
        if self.convolution_mode is ConvolutionMode.SAME:
            return "SAME"
        ph, pw = self.padding
        return [(ph, ph), (pw, pw)]

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = self.kernel_size
        c_in = self.n_in
        fan_in = kh * kw * c_in
        fan_out = kh * kw * self.n_out
        wi = self.weight_init or WeightInit.XAVIER
        k1, _ = jax.random.split(key)
        # HWIO kernel layout (XLA native)
        p = {"W": wi.init(k1, (kh, kw, c_in, self.n_out),
                          fan_in, fan_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        # conv + bias/activation epilogue through the shared fused
        # entry point (ops/conv_pallas.py): when the conv_epilogue
        # kernel-select ladder admits the site the epilogue runs
        # inside Pallas output tiles; otherwise this IS the dense
        # lax.conv_general_dilated lowering the layer always used
        from deeplearning4j_tpu.ops.conv_pallas import conv_forward
        z = conv_forward(
            x, params["W"],
            window_strides=self.stride,
            padding=self._pad_cfg(),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            bias=params["b"] if self.has_bias else None,
            activation=self.activation)
        return z, state

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeConvolutional) and \
                (override or not self.n_in):
            self.n_in = input_type.channels

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional), input_type
        h, w = input_type.height, input_type.width
        kh, kw = self.kernel_size
        sh, sw = self.stride
        dh, dw = self.dilation
        ekh, ekw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
        if self.convolution_mode is ConvolutionMode.SAME:
            oh = -(-h // sh)
            ow = -(-w // sw)
        else:
            ph, pw = self.padding
            oh = (h + 2 * ph - ekh) // sh + 1
            ow = (w + 2 * pw - ekw) // sw + 1
        return InputType.convolutional(oh, ow, self.n_out)


@dataclass
class SubsamplingLayer(Layer):
    """Pooling (reference: conf.layers.SubsamplingLayer; cuDNN/oneDNN
    helpers replaced by ``lax.reduce_window``)."""

    pooling_type: PoolingType = PoolingType.MAX
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    pnorm: int = 2

    @staticmethod
    def _builder_positional(*args) -> dict:
        out = {}
        rest = list(args)
        if rest and isinstance(rest[0], PoolingType):
            out["pooling_type"] = rest.pop(0)
        if rest:
            out["kernel_size"] = _pair(rest.pop(0))
        if rest:
            out["stride"] = _pair(rest.pop(0))
        return out

    def __post_init__(self):
        super().__post_init__()
        self.kernel_size = _pair(self.kernel_size)
        self.stride = _pair(self.stride)
        self.padding = _pair(self.padding)

    def has_params(self) -> bool:
        return False

    def forward(self, params, x, *, training, rng=None, state=None):
        kh, kw = self.kernel_size
        sh, sw = self.stride
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            ph, pw = self.padding
            pad = [(0, 0), (ph, ph), (pw, pw), (0, 0)]
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        if self.pooling_type is PoolingType.MAX:
            z = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims,
                                      strides, pad)
        elif self.pooling_type is PoolingType.SUM:
            z = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                      pad)
        elif self.pooling_type is PoolingType.AVG:
            s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                      pad)
            n = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                      dims, strides, pad)
            z = s / n
        else:  # PNORM
            p = float(self.pnorm)
            s = jax.lax.reduce_window(jnp.abs(x) ** p, 0.0, jax.lax.add,
                                      dims, strides, pad)
            z = s ** (1.0 / p)
        return z, state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional), input_type
        h, w = input_type.height, input_type.width
        kh, kw = self.kernel_size
        sh, sw = self.stride
        if self.convolution_mode is ConvolutionMode.SAME:
            oh, ow = -(-h // sh), -(-w // sw)
        else:
            ph, pw = self.padding
            oh = (h + 2 * ph - kh) // sh + 1
            ow = (w + 2 * pw - kw) // sw + 1
        return InputType.convolutional(oh, ow, input_type.channels)

    def set_n_in(self, input_type, override):
        pass


@dataclass
class BatchNormalization(Layer):
    """Batch norm (reference: conf.layers.BatchNormalization with
    CudnnBatchNormalizationHelper — here plain XLA ops that fuse into the
    surrounding conv; running stats are functional state carried by the
    network, replacing the reference's mutable arrays)."""

    decay: float = 0.9
    eps: float = 1e-5
    gamma_init: float = 1.0
    beta_init: float = 0.0
    lock_gamma_beta: bool = False

    def has_state(self) -> bool:
        return True

    def _nf(self, input_type):
        if isinstance(input_type, InputTypeConvolutional):
            return input_type.channels
        return input_type.size

    def init_params(self, key, input_type, dtype=jnp.float32):
        nf = self._nf(input_type)
        return {"gamma": jnp.full((nf,), self.gamma_init, dtype),
                "beta": jnp.full((nf,), self.beta_init, dtype)}

    def init_state(self, input_type, dtype=jnp.float32):
        nf = self._nf(input_type)
        return {"mean": jnp.zeros((nf,), dtype),
                "var": jnp.ones((nf,), dtype)}

    def forward(self, params, x, *, training, rng=None, state=None):
        if training:
            # shared forward math (one-pass E[x]/E[x^2] for bf16 — one
            # fused HBM read, the dominant ResNet-50 cost per
            # benchmarks/profile_resnet.py — two-pass for f32; see
            # ops/bn_pallas.py:bn_forward_math). With
            # DL4J_TPU_FUSED_BN_BWD the SAME forward runs under a
            # custom_vjp whose backward is the hand Pallas kernel
            # pair (measured slower than XLA's autodiff on ResNet-50;
            # kept as the tuning seam — BENCH_notes_r03.md), and the
            # bn_fwd ladder (DL4J_TPU_FUSED_CONV family) additionally
            # routes its statistics + normalize through the one-pass
            # Pallas kernels in ops/conv_pallas.py. Without the fused
            # backward, maybe_fused_bn_train runs the same kernels
            # with the relu/identity activation streamed into the
            # normalize epilogue.
            from deeplearning4j_tpu.ops.bn_pallas import (
                bn_forward_math, bn_train_normalize,
                fused_bn_bwd_enabled)
            from deeplearning4j_tpu.ops.conv_pallas import (
                maybe_fused_bn_train)
            act_done = False
            if fused_bn_bwd_enabled():
                out, mean, var = bn_train_normalize(
                    x, params["gamma"], params["beta"], self.eps)
            else:
                fused = maybe_fused_bn_train(
                    x, params["gamma"], params["beta"], self.eps,
                    self.activation)
                if fused is not None:
                    out, mean, var = fused
                    act_done = True
                else:
                    out, mean, var, _ = bn_forward_math(
                        x, params["gamma"], params["beta"], self.eps)
            d = self.decay
            new_state = {"mean": d * state["mean"] + (1 - d) * mean,
                         "var": d * state["var"] + (1 - d) * var}
            return (out if act_done else self.activation(out),
                    new_state)
        acc = jnp.promote_types(x.dtype, jnp.float32)
        mean = state["mean"].astype(acc)
        var = state["var"].astype(acc)
        # x * scale + bias with per-channel scale/bias: one fused
        # multiply-add over the tensor instead of subtract/divide chains
        scale = params["gamma"].astype(var.dtype) / jnp.sqrt(var + self.eps)
        bias = params["beta"].astype(var.dtype) - mean * scale
        from deeplearning4j_tpu.ops.conv_pallas import (
            maybe_bn_inference_epilogue)
        out = maybe_bn_inference_epilogue(x, scale, bias,
                                          self.activation)
        if out is not None:         # scale/shift/act in ONE pass
            return out, state
        out = x * scale.astype(x.dtype) + bias.astype(x.dtype)
        return self.activation(out), state

    def get_output_type(self, input_type):
        return input_type

    def set_n_in(self, input_type, override):
        self.n_in = self.n_out = self._nf(input_type)


@dataclass
class ActivationLayer(Layer):
    def has_params(self) -> bool:
        return False

    def forward(self, params, x, *, training, rng=None, state=None):
        return self.activation(x), state

    def get_output_type(self, input_type):
        return input_type

    def set_n_in(self, input_type, override):
        pass


@dataclass
class DropoutLayer(Layer):
    """Standalone dropout layer; ``dropout`` is the retain probability,
    matching the reference's convention."""

    dropout: float = 0.5

    def has_params(self) -> bool:
        return False

    def forward(self, params, x, *, training, rng=None, state=None):
        return self._maybe_dropout(x, training, rng), state

    def get_output_type(self, input_type):
        return input_type

    def set_n_in(self, input_type, override):
        pass


@dataclass
class EmbeddingLayer(Layer):
    """Index -> vector lookup (reference: conf.layers.EmbeddingLayer).
    Input: int [batch] or [batch, 1]."""

    has_bias: bool = False

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        p = {"W": wi.init(key, (self.n_in, self.n_out),
                          self.n_in, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        z = params["W"][idx]
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def set_n_in(self, input_type, override):
        pass  # n_in is vocabulary size; never inferred from input width


@dataclass
class GlobalPoolingLayer(Layer):
    """Global pooling over spatial or time dims (reference:
    conf.layers.GlobalPoolingLayer). Supports masked time averaging."""

    pooling_type: PoolingType = PoolingType.MAX

    def has_params(self) -> bool:
        return False

    def accepts_mask(self) -> bool:
        return True

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        if x.ndim == 5:          # NDHWC -> pool D,H,W
            axes = (1, 2, 3)
        elif x.ndim == 4:        # NHWC -> pool H,W
            axes = (1, 2)
        elif x.ndim == 3:        # [b, t, f] -> pool t
            axes = (1,)
        else:
            return x, state
        if mask is not None and x.ndim in (3, 5):
            # time mask over [b, t, f] or [b, t, h, w, c] (masked
            # ConvLSTM sequences): padded steps drop out of the pool
            m = mask.reshape(mask.shape[:2] + (1,) * (x.ndim - 2))
            spatial = 1
            for d in x.shape[2:-1]:
                spatial *= d
            if self.pooling_type is PoolingType.MAX:
                z = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=axes)
            elif self.pooling_type is PoolingType.SUM:
                z = jnp.sum(x * m, axis=axes)
            elif self.pooling_type is PoolingType.AVG:
                denom = jnp.maximum(jnp.sum(mask, axis=1),
                                    1.0)[:, None] * spatial
                z = jnp.sum(x * m, axis=axes) / denom
            else:                # PNORM over unmasked timesteps
                p = float(self.pnorm) if hasattr(self, "pnorm") else 2.0
                z = jnp.sum(jnp.abs(x * m) ** p, axis=axes) ** (1.0 / p)
            return z, state
        if self.pooling_type is PoolingType.MAX:
            z = jnp.max(x, axis=axes)
        elif self.pooling_type is PoolingType.SUM:
            z = jnp.sum(x, axis=axes)
        elif self.pooling_type is PoolingType.AVG:
            z = jnp.mean(x, axis=axes)
        else:
            p = float(self.pnorm) if hasattr(self, "pnorm") else 2.0
            z = jnp.sum(jnp.abs(x) ** p, axis=axes) ** (1.0 / p)
        return z, state

    def get_output_type(self, input_type):
        from deeplearning4j_tpu.nn.conf.inputs import \
            InputTypeConvolutional3D
        if isinstance(input_type, (InputTypeConvolutional,
                                   InputTypeConvolutional3D)):
            return InputType.feed_forward(input_type.channels)
        if isinstance(input_type, InputTypeRecurrent):
            return InputType.feed_forward(input_type.size)
        return input_type

    def set_n_in(self, input_type, override):
        pass


# ---------------------------------------------------------------------------
@dataclass
class BaseOutputLayer(DenseLayer):
    """Common: dense projection + loss head."""

    loss_function: LossFunction = LossFunction.MCXENT
    activation: Activation = Activation.SOFTMAX

    @staticmethod
    def _builder_positional(*args) -> dict:
        return {"loss_function": args[0]} if args else {}

    def compute_loss(self, labels, preds_or_logits, *, from_logits: bool,
                     mask=None, average=True):
        lf = self.loss_function
        if from_logits and lf.supports_logits():
            return lf.score_from_logits(labels, preds_or_logits, mask=mask,
                                        average=average)
        return lf.score(labels, preds_or_logits, mask=mask, average=average)

    def wants_logits(self) -> bool:
        """Fuse final softmax/sigmoid into the loss (TPU-first: avoids the
        reference's prob-space clip+log; same trick its MCXENT+softmax
        fusion performs)."""
        return (self.loss_function.supports_logits() and
                self.activation in (Activation.SOFTMAX, Activation.SIGMOID))

    def forward_logits(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        return z, state


@dataclass
class OutputLayer(BaseOutputLayer):
    """Reference: conf.layers.OutputLayer."""


@dataclass
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep output head (reference: conf.layers.RnnOutputLayer).
    Input [b, t, f] -> output [b, t, n_out]."""

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeRecurrent) and \
                (override or not self.n_in):
            self.n_in = input_type.size

    def get_output_type(self, input_type):
        t = input_type.timesteps if isinstance(input_type,
                                               InputTypeRecurrent) else -1
        return InputType.recurrent(self.n_out, t)


@dataclass
class LossLayer(BaseOutputLayer):
    """Loss-only head, no params (reference: conf.layers.LossLayer)."""

    def has_params(self) -> bool:
        return False

    def init_params(self, key, input_type, dtype=jnp.float32):
        return {}

    def forward(self, params, x, *, training, rng=None, state=None):
        return self.activation(x), state

    def forward_logits(self, params, x, *, training, rng=None, state=None):
        return x, state

    def get_output_type(self, input_type):
        return input_type

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeFeedForward):
            self.n_in = self.n_out = input_type.size


@dataclass
class CnnLossLayer(BaseOutputLayer):
    """Per-pixel loss head on [b, h, w, c] activations — no params,
    no flattening (reference: conf.layers.CnnLossLayer; used by
    segmentation nets like UNet)."""

    activation: Activation = Activation.IDENTITY

    def has_params(self) -> bool:
        return False

    def init_params(self, key, input_type, dtype=jnp.float32):
        return {}

    def set_n_in(self, input_type, override):
        pass

    def get_output_type(self, input_type):
        return input_type

    def wants_logits(self) -> bool:
        return False

    def forward(self, params, x, *, training, rng=None, state=None):
        return self.activation(x), state

    def forward_logits(self, params, x, *, training, rng=None,
                       state=None):
        return x, state


LAYER_REGISTRY: dict = {c.__name__: c for c in
                        (DenseLayer, ConvolutionLayer, SubsamplingLayer,
                         BatchNormalization, ActivationLayer, DropoutLayer,
                         EmbeddingLayer, GlobalPoolingLayer, OutputLayer,
                         RnnOutputLayer, LossLayer, CnnLossLayer)}


def register_layer(cls):
    """Register a layer class for JSON round-trip (zoo/custom layers)."""
    LAYER_REGISTRY[cls.__name__] = cls
    return cls
