from deeplearning4j_tpu.nn.conf.builders import (  # noqa: F401
    NeuralNetConfiguration, MultiLayerConfiguration, ListBuilder,
    GradientNormalization, BackpropType, WorkspaceMode)
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers_attention  # noqa: F401
from deeplearning4j_tpu.nn.conf import preprocessors  # noqa: F401
