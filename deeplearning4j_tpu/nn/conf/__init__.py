from deeplearning4j_tpu.nn.conf.builders import (  # noqa: F401
    NeuralNetConfiguration, MultiLayerConfiguration, ListBuilder,
    GradientNormalization, BackpropType, WorkspaceMode)
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers_attention  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers_shape  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers_conv_1d3d  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers_misc  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers_vae  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers_output_extra  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers_capsule  # noqa: F401
from deeplearning4j_tpu.nn.conf import preprocessors  # noqa: F401
from deeplearning4j_tpu.nn.conf.dropout import (  # noqa: F401
    AlphaDropout, Dropout, GaussianDropout, GaussianNoise, IDropout,
    SpatialDropout, WeightNoise)
