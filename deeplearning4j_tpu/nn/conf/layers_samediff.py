"""Custom layers defined via the SameDiff graph builder.

Reference parity: ``org.deeplearning4j.nn.conf.layers.samediff.
{SameDiffLayer,SameDiffOutputLayer,SameDiffVertex,SDLayerParams}`` —
the reference's escape hatch for user-defined layers: subclass, declare
parameter shapes, and describe the forward pass as a SameDiff graph;
the layer then participates in a MultiLayerNetwork/ComputationGraph
like any built-in layer.

TPU-first: the user's graph is traced ONCE into the layer's private
SameDiff and compiled into the surrounding network's single jitted train
step via ``SameDiff._build_fn`` — there is no per-layer session or
op-by-op dispatch; the custom subgraph fuses with its neighbours in XLA.

Usage:

    class MyLayer(SameDiffLayer):
        def define_parameters(self):
            return {"W": (self.n_in, self.n_out)}
        def define_layer(self, sd, layer_input, params):
            return sd.nn.relu(layer_input.mmul(params["W"]))
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (BaseOutputLayer, Layer,
                                               register_layer)
from deeplearning4j_tpu.nn.conf.graph_vertices import GraphVertex
from deeplearning4j_tpu.nn.weights import WeightInit


def _build_layer_fn(define, n_inputs: int, param_shapes: Dict[str, tuple],
                    training: bool):
    """Trace a define_layer-style callable into a fresh SameDiff and
    return a pure fn(param_vals, input_arrays, rng) -> output array."""
    from deeplearning4j_tpu.autodiff.samediff import SameDiff
    sd = SameDiff()
    inputs = [sd.placeholder(f"input{i}" if n_inputs > 1 else "input",
                             shape=None) for i in range(n_inputs)]
    pvars = {n: sd.var(n, shape=shape)
             for n, shape in param_shapes.items()}
    out = define(sd, inputs[0] if n_inputs == 1 else inputs, pvars)
    fn, var_names = sd._build_fn(
        (out.name,), tuple(v.name for v in inputs), training)

    def pure(param_vals, input_arrays, rng):
        ph = {v.name: a for v, a in zip(inputs, input_arrays)}
        return fn({n: param_vals[n] for n in var_names
                   if n in param_vals}, ph, rng)[0]

    return pure


@register_layer
@dataclass
class SameDiffLayer(Layer):
    """Base class for user-defined SameDiff layers (reference:
    samediff.SameDiffLayer). Subclass and override
    ``define_parameters`` + ``define_layer`` (and optionally
    ``initialize_parameters`` / ``get_output_type``)."""

    # -- user hooks ------------------------------------------------------
    def define_parameters(self) -> Dict[str, tuple]:
        """name -> shape of every trainable parameter."""
        return {}

    def initialize_parameters(self, key, shapes: Dict[str, tuple],
                              dtype) -> Dict[str, jnp.ndarray]:
        """Default: weight_init (XAVIER) for >=2-d params, zeros for
        biases (reference: SDLayerParams weight/bias split)."""
        wi = self.weight_init or WeightInit.XAVIER
        out = {}
        for n, shape in shapes.items():
            key, sub = jax.random.split(key)
            if len(shape) >= 2:
                out[n] = wi.init(sub, tuple(shape), shape[0], shape[-1],
                                 dtype)
            else:
                out[n] = jnp.zeros(shape, dtype)
        return out

    def define_layer(self, sd, layer_input, params):
        raise NotImplementedError

    # -- layer protocol --------------------------------------------------
    def init_params(self, key, input_type, dtype=jnp.float32):
        return self.initialize_parameters(key, self.define_parameters(),
                                          dtype)

    def _fn(self, training: bool):
        cache = getattr(self, "_fn_cache", None)
        if cache is None:
            cache = self._fn_cache = {}
        if training not in cache:
            cache[training] = _build_layer_fn(
                self.define_layer, 1, self.define_parameters(), training)
        return cache[training]

    def forward(self, params, x, *, training, rng=None, state=None):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return self._fn(training)(params, [x], rng), state

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def to_map(self) -> dict:
        d = super().to_map()
        d.pop("_fn_cache", None)
        return d


@register_layer
@dataclass
class SameDiffOutputLayer(BaseOutputLayer):
    """User-defined output layer (reference: samediff.
    SameDiffOutputLayer): ``define_layer`` produces the activations;
    ``define_loss`` is inherited from the configured loss function
    applied to those activations (the common reference pattern)."""

    def define_parameters(self) -> Dict[str, tuple]:
        return {}

    def initialize_parameters(self, key, shapes, dtype):
        return SameDiffLayer.initialize_parameters(self, key, shapes,
                                                   dtype)

    def define_layer(self, sd, layer_input, params):
        raise NotImplementedError

    def init_params(self, key, input_type, dtype=jnp.float32):
        return self.initialize_parameters(key, self.define_parameters(),
                                          dtype)

    _fn = SameDiffLayer._fn

    def wants_logits(self) -> bool:
        return False

    def forward(self, params, x, *, training, rng=None, state=None):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        z = self._fn(training)(params, [x], rng)
        return self.activation(z), state

    def forward_logits(self, params, x, *, training, rng=None,
                       state=None):
        if rng is None:
            rng = jax.random.PRNGKey(0)
        return self._fn(training)(params, [x], rng), state

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def to_map(self) -> dict:
        d = super().to_map()
        d.pop("_fn_cache", None)
        return d


class SameDiffVertex(GraphVertex):
    """User-defined multi-input vertex for ComputationGraph (reference:
    samediff.SameDiffVertex). Subclass and override ``define_vertex(sd,
    inputs)`` (parameter-free — trainable custom vertices belong in a
    SameDiffLayer) and ``get_output_type``."""

    def define_vertex(self, sd, inputs):
        raise NotImplementedError

    def _fn(self, n_inputs: int, training: bool):
        cache = getattr(self, "_fn_cache", None)
        if cache is None:
            cache = self._fn_cache = {}
        key = (n_inputs, training)
        if key not in cache:
            cache[key] = _build_layer_fn(
                lambda sd, ins, params: self.define_vertex(
                    sd, ins if isinstance(ins, list) else [ins]),
                n_inputs, {}, training)
        return cache[key]

    def forward(self, inputs, *, training=False):
        return self._fn(len(inputs), training)(
            {}, list(inputs), jax.random.PRNGKey(0))

    def get_output_type(self, input_types):
        return input_types[0]
