"""Attention layers.

Reference parity (SURVEY.md D4 "attention"):
``org.deeplearning4j.nn.conf.layers.SelfAttentionLayer``,
``LearnedSelfAttentionLayer``, ``RecurrentAttentionLayer`` — in the
reference these are SameDiff-backed layers built on the nd4j
``multi_head_dot_product_attention`` op. Here each is a config dataclass
whose forward lowers to one fused einsum/softmax/einsum chain that XLA
maps onto the MXU; no per-head loop, heads are a tensor dimension.

Activations are [batch, time, features]. Masks are [batch, time] key
masks: masked timesteps neither attend nor get attended to (scores set
to -inf before softmax), matching the reference's masked attention.

These layers route through ``ops.attention.dot_product_attention``,
which on TPU auto-selects the Pallas flash-attention backend
(``ops.attention_pallas``) at long sequence lengths or when the dense
[batch, heads, t_q, t_k] scores tensor would not fit comfortably in
free HBM; ``DL4J_TPU_FLASH_ATTENTION=1/0`` forces/kills it. Bias'd
projections keep the dense path (flash takes no additive bias).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.nn.conf.inputs import (InputType,
                                               InputTypeRecurrent)
from deeplearning4j_tpu.nn.conf.layers import Layer, register_layer
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.ops.attention import (  # noqa: F401
    dot_product_attention, multi_head_attention)


@dataclass
class BaseAttentionLayer(Layer):
    """Shared config: n_heads * head_size projection width."""

    n_heads: int = 1
    head_size: int = 0          # 0 -> n_out // n_heads
    #: learn projection biases (the Keras MultiHeadAttention
    #: ``use_bias=True`` form; the reference layer has none)
    has_bias: bool = False

    def _head_size(self) -> int:
        return self.head_size or max(self.n_out // self.n_heads, 1)

    def accepts_mask(self) -> bool:
        return True

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeRecurrent) and \
                (override or not self.n_in):
            self.n_in = input_type.size
            if not self.n_out:
                self.n_out = self.n_in

    def _proj_params(self, key, q_dim, kv_dim, dtype):
        wi = self.weight_init or WeightInit.XAVIER
        hs = self._head_size() * self.n_heads
        k1, k2, k3, k4 = jax.random.split(key, 4)
        p = {
            "Wq": wi.init(k1, (q_dim, hs), q_dim, hs, dtype),
            "Wk": wi.init(k2, (kv_dim, hs), kv_dim, hs, dtype),
            "Wv": wi.init(k3, (kv_dim, hs), kv_dim, hs, dtype),
            "Wo": wi.init(k4, (hs, self.n_out), hs, self.n_out, dtype),
        }
        if self.has_bias:
            p.update({"bq": jnp.zeros((hs,), dtype),
                      "bk": jnp.zeros((hs,), dtype),
                      "bv": jnp.zeros((hs,), dtype),
                      "bo": jnp.zeros((self.n_out,), dtype)})
        return p


@register_layer
@dataclass
class SelfAttentionLayer(BaseAttentionLayer):
    """Self-attention over the input sequence (reference:
    conf.layers.SelfAttentionLayer). ``project_input=False`` requires
    a single head and applies unprojected dot-product attention."""

    project_input: bool = True

    def init_params(self, key, input_type, dtype=jnp.float32):
        if not self.project_input:
            if self.n_heads != 1:
                raise ValueError(
                    "SelfAttentionLayer(project_input=False) requires "
                    f"n_heads=1, got {self.n_heads} (reference rejects "
                    "projectInput=false with nHeads!=1)")
            return {}
        return self._proj_params(key, self.n_in, self.n_in, dtype)

    def has_params(self) -> bool:
        return self.project_input

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        x = self._maybe_dropout(x, training, rng)
        if not self.project_input:
            m = mask[:, None, :] if mask is not None else None
            y = dot_product_attention(x, x, x, m)
        else:
            y = multi_head_attention(params, x, x, self.n_heads,
                                     key_mask=mask)
        if mask is not None:
            y = y * mask[:, :, None]
        return self.activation(y), state

    def get_output_type(self, input_type):
        t = input_type.timesteps if isinstance(input_type,
                                               InputTypeRecurrent) else -1
        n = self.n_out if self.project_input else self.n_in
        return InputType.recurrent(n, t)


@register_layer
@dataclass
class LearnedSelfAttentionLayer(BaseAttentionLayer):
    """Attention with ``n_queries`` learned query vectors (reference:
    conf.layers.LearnedSelfAttentionLayer). Output has a fixed
    ``n_queries`` timesteps regardless of input length — the
    reference's sequence-summarisation head."""

    n_queries: int = 1

    def init_params(self, key, input_type, dtype=jnp.float32):
        kq, kp = jax.random.split(key)
        wi = self.weight_init or WeightInit.XAVIER
        p = self._proj_params(kp, self.n_in, self.n_in, dtype)
        p["Q"] = wi.init(kq, (self.n_queries, self.n_in),
                         self.n_in, self.n_in, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        x = self._maybe_dropout(x, training, rng)
        b = x.shape[0]
        q = jnp.broadcast_to(params["Q"],
                             (b,) + params["Q"].shape)
        y = multi_head_attention(params, q, x, self.n_heads,
                                 key_mask=mask)
        return self.activation(y), state

    def get_output_type(self, input_type):
        return InputType.recurrent(self.n_out, self.n_queries)


@register_layer
@dataclass
class RecurrentAttentionLayer(BaseAttentionLayer):
    """Recurrent cell whose per-timestep input is augmented with an
    attention readout over the full sequence, queried by the previous
    hidden state (reference: conf.layers.RecurrentAttentionLayer):

        a_t = MHA(q = h_{t-1}, kv = x)
        h_t = act(x_t W + h_{t-1} R + a_t + b)

    The attention readout is recomputed each step inside one
    ``lax.scan``; XLA hoists the shared K/V projections out of the
    loop, so per-step cost is one [b,1,d]x[b,t,d] attention."""

    activation: Activation = Activation.TANH
    has_bias: bool = True

    def is_recurrent(self) -> bool:
        return True

    def zero_state(self, batch: int, dtype=jnp.float32) -> dict:
        return {"h": jnp.zeros((batch, self.n_out), dtype)}

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        k1, k2, k3 = jax.random.split(key, 3)
        p = self._proj_params(k3, self.n_out, self.n_in, dtype)
        p["W"] = wi.init(k1, (self.n_in, self.n_out), self.n_in,
                         self.n_out, dtype)
        p["R"] = wi.init(k2, (self.n_out, self.n_out), self.n_out,
                         self.n_out, dtype)
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        x = self._maybe_dropout(x, training, rng)
        b, t, _ = x.shape
        if not state:
            state = self.zero_state(b, x.dtype)
        act = self.activation.fn()
        xw = x @ params["W"]                       # hoisted input proj
        if "b" in params:
            xw = xw + params["b"]

        def step(h, inp):
            xw_t, m_t = inp
            a = multi_head_attention(params, h[:, None, :], x,
                                     self.n_heads, key_mask=mask)[:, 0]
            h_new = act(xw_t + h @ params["R"] + a)
            if m_t is not None:
                h_new = jnp.where(m_t[:, None] > 0, h_new, h)
            return h_new, h_new

        if mask is not None:
            h_last, ys = jax.lax.scan(step, state["h"],
                                      (xw.swapaxes(0, 1),
                                       mask.swapaxes(0, 1)))
        else:
            h_last, ys = jax.lax.scan(
                lambda h, xt: step(h, (xt, None)), state["h"],
                xw.swapaxes(0, 1))
        return ys.swapaxes(0, 1), {"h": h_last}

    def get_output_type(self, input_type):
        t = input_type.timesteps if isinstance(input_type,
                                               InputTypeRecurrent) else -1
        return InputType.recurrent(self.n_out, t)
