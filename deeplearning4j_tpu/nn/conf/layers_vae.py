"""Unsupervised/pretrainable layers: AutoEncoder + VariationalAutoencoder.

Reference parity: ``org.deeplearning4j.nn.conf.layers.AutoEncoder``
(denoising autoencoder with tied decode weights, corruption level) and
``conf.layers.variational.VariationalAutoencoder`` (+ runtime
``nn.layers.variational.VariationalAutoencoder``: encoder/decoder MLPs,
reparameterised q(z|x), Gaussian/Bernoulli reconstruction distributions,
``reconstructionProbability`` / ``reconstructionError`` scoring,
``generateAtMeanGivenZ``), SURVEY.md D4 "VAE".

TPU-first: the pretrain objective is a pure function
``pretrain_loss(params, x, rng)``; MultiLayerNetwork.pretrain_layer jits
value_and_grad over it — layerwise pretraining compiles to one XLA
program per layer exactly like supervised fit. Sampling uses jax threefry
keys (no stateful RNG).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer, register_layer
from deeplearning4j_tpu.nn.weights import WeightInit


@register_layer
@dataclass
class AutoEncoder(Layer):
    """Denoising autoencoder (reference: conf.layers.AutoEncoder).
    Encode: h = act(xW + b). Decode (tied): x' = act(hWᵀ + vb).
    ``corruption_level`` zeroes that fraction of inputs during pretrain
    (masking noise); ``sparsity`` is an L1 penalty on h."""

    corruption_level: float = 0.3
    sparsity: float = 0.0

    def is_pretrainable(self) -> bool:
        return True

    def is_pretrain_param(self, name: str) -> bool:
        return name == "vb"   # decoder bias only used during pretraining

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        k1, _ = jax.random.split(key)
        return {"W": wi.init(k1, (self.n_in, self.n_out), self.n_in,
                             self.n_out, dtype),
                "b": jnp.full((self.n_out,), self.bias_init, dtype),
                "vb": jnp.zeros((self.n_in,), dtype)}

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        return self.activation(x @ params["W"] + params["b"]), state

    def pretrain_loss(self, params, x, rng):
        """Reconstruction MSE after masking-noise corruption."""
        k_corrupt, _ = jax.random.split(rng)
        if self.corruption_level > 0:
            keep = jax.random.bernoulli(k_corrupt,
                                        1.0 - self.corruption_level,
                                        x.shape)
            x_in = jnp.where(keep, x, 0.0)
        else:
            x_in = x
        h = self.activation(x_in @ params["W"] + params["b"])
        recon = self.activation(h @ params["W"].T + params["vb"])
        loss = jnp.mean(jnp.sum((recon - x) ** 2, axis=-1))
        if self.sparsity > 0:
            loss = loss + self.sparsity * jnp.mean(jnp.abs(h))
        return loss

    def reconstruct(self, params, x):
        h = self.activation(x @ params["W"] + params["b"])
        return self.activation(h @ params["W"].T + params["vb"])

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


@register_layer
@dataclass
class VariationalAutoencoder(Layer):
    """VAE layer (reference: conf.layers.variational.
    VariationalAutoencoder). ``n_out`` is the latent size; in a
    supervised stack the layer's output is the mean of q(z|x) — matching
    the reference's activate(). Pretraining maximises the ELBO with the
    reparameterisation trick."""

    encoder_layer_sizes: Tuple[int, ...] = (128,)
    decoder_layer_sizes: Tuple[int, ...] = (128,)
    reconstruction_distribution: str = "gaussian"  # or "bernoulli"
    pzx_activation: Activation = Activation.IDENTITY
    num_samples: int = 1

    def __post_init__(self):
        super().__post_init__()
        if isinstance(self.pzx_activation, str):
            self.pzx_activation = Activation.from_name(self.pzx_activation)
        self.encoder_layer_sizes = tuple(self.encoder_layer_sizes)
        self.decoder_layer_sizes = tuple(self.decoder_layer_sizes)

    def is_pretrainable(self) -> bool:
        return True

    def is_pretrain_param(self, name: str) -> bool:
        return name.startswith("d") or name.startswith("px")

    # -- params ----------------------------------------------------------
    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        p = {}
        sizes = (self.n_in,) + self.encoder_layer_sizes
        keys = jax.random.split(key, len(sizes) + len(
            self.decoder_layer_sizes) + 4)
        ki = 0
        for i in range(len(self.encoder_layer_sizes)):
            p[f"e{i}W"] = wi.init(keys[ki], (sizes[i], sizes[i + 1]),
                                  sizes[i], sizes[i + 1], dtype)
            p[f"e{i}b"] = jnp.zeros((sizes[i + 1],), dtype)
            ki += 1
        enc_top = sizes[-1]
        p["mW"] = wi.init(keys[ki], (enc_top, self.n_out), enc_top,
                          self.n_out, dtype); ki += 1
        p["mb"] = jnp.zeros((self.n_out,), dtype)
        p["lW"] = wi.init(keys[ki], (enc_top, self.n_out), enc_top,
                          self.n_out, dtype); ki += 1
        p["lb"] = jnp.zeros((self.n_out,), dtype)
        dsizes = (self.n_out,) + self.decoder_layer_sizes
        for i in range(len(self.decoder_layer_sizes)):
            p[f"d{i}W"] = wi.init(keys[ki], (dsizes[i], dsizes[i + 1]),
                                  dsizes[i], dsizes[i + 1], dtype)
            p[f"d{i}b"] = jnp.zeros((dsizes[i + 1],), dtype)
            ki += 1
        dec_top = dsizes[-1]
        out_w = self.n_in * (2 if self.reconstruction_distribution ==
                             "gaussian" else 1)
        p["pxW"] = wi.init(keys[ki], (dec_top, out_w), dec_top, out_w,
                           dtype)
        p["pxb"] = jnp.zeros((out_w,), dtype)
        return p

    # -- encoder/decoder -------------------------------------------------
    def _encode(self, params, x):
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = self.activation(h @ params[f"e{i}W"] + params[f"e{i}b"])
        mean = self.pzx_activation(h @ params["mW"] + params["mb"])
        log_var = h @ params["lW"] + params["lb"]
        return mean, log_var

    def _decode(self, params, z):
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = self.activation(h @ params[f"d{i}W"] + params[f"d{i}b"])
        return h @ params["pxW"] + params["pxb"]

    def _recon_nll(self, stats, x):
        """Negative log p(x|z) per example, summed over features."""
        if self.reconstruction_distribution == "bernoulli":
            logits = stats
            nll = jnp.maximum(logits, 0) - logits * x + \
                jnp.log1p(jnp.exp(-jnp.abs(logits)))
            return jnp.sum(nll, axis=-1)
        mean, log_var = jnp.split(stats, 2, axis=-1)
        log_var = jnp.clip(log_var, -10.0, 10.0)
        nll = 0.5 * (jnp.log(2 * jnp.pi) + log_var +
                     (x - mean) ** 2 / jnp.exp(log_var))
        return jnp.sum(nll, axis=-1)

    # -- layer protocol --------------------------------------------------
    def forward(self, params, x, *, training, rng=None, state=None):
        mean, _ = self._encode(params, x)
        return mean, state

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    # -- pretraining (ELBO) ----------------------------------------------
    def pretrain_loss(self, params, x, rng):
        mean, log_var = self._encode(params, x)
        log_var = jnp.clip(log_var, -10.0, 10.0)
        kl = 0.5 * jnp.sum(jnp.exp(log_var) + mean ** 2 - 1.0 - log_var,
                           axis=-1)
        nll = 0.0
        keys = jax.random.split(rng, self.num_samples)
        for k in keys:
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            nll = nll + self._recon_nll(self._decode(params, z), x)
        nll = nll / self.num_samples
        return jnp.mean(nll + kl)

    # -- reference scoring API -------------------------------------------
    def reconstruction_log_probability(self, params, x, rng,
                                       num_samples: int = 16):
        """log p(x) importance-sampled estimate (reference:
        reconstructionLogProbability); returns [batch]."""
        mean, log_var = self._encode(params, x)
        log_var = jnp.clip(log_var, -10.0, 10.0)
        std = jnp.exp(0.5 * log_var)
        lps = []
        for k in jax.random.split(rng, num_samples):
            eps = jax.random.normal(k, mean.shape, mean.dtype)
            z = mean + std * eps
            log_px_z = -self._recon_nll(self._decode(params, z), x)
            log_pz = -0.5 * jnp.sum(z ** 2 + jnp.log(2 * jnp.pi), -1)
            log_qz = -0.5 * jnp.sum(eps ** 2 + jnp.log(2 * jnp.pi) +
                                    log_var, -1)
            lps.append(log_px_z + log_pz - log_qz)
        stacked = jnp.stack(lps)  # [S, batch]
        return jax.scipy.special.logsumexp(stacked, axis=0) - \
            jnp.log(float(num_samples))

    def reconstruction_error(self, params, x):
        """Deterministic reconstruction error at the mean of q(z|x)
        (reference: reconstructionError)."""
        mean, _ = self._encode(params, x)
        stats = self._decode(params, mean)
        if self.reconstruction_distribution == "bernoulli":
            recon = jax.nn.sigmoid(stats)
        else:
            recon, _ = jnp.split(stats, 2, axis=-1)
        return jnp.sum((recon - x) ** 2, axis=-1)

    def generate_at_mean_given_z(self, params, z):
        """Decoder mean for latent z (reference: generateAtMeanGivenZ)."""
        stats = self._decode(params, z)
        if self.reconstruction_distribution == "bernoulli":
            return jax.nn.sigmoid(stats)
        mean, _ = jnp.split(stats, 2, axis=-1)
        return mean
