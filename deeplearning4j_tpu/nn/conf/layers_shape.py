"""Shape-manipulation + wrapper layers (SURVEY.md D4 long tail).

Reference parity: ``org.deeplearning4j.nn.conf.layers.convolutional.
{Cropping1D,Cropping2D,Cropping3D}``, ``conf.layers.{ZeroPadding1DLayer,
ZeroPaddingLayer,ZeroPadding3DLayer,SpaceToDepthLayer,DepthToSpaceLayer,
Upsampling1D,Upsampling3D,RepeatVector}``, ``conf.layers.util.
{MaskLayer,MaskZeroLayer}``, ``conf.layers.misc.{FrozenLayer,
FrozenLayerWithBackprop}``, ``conf.layers.recurrent.TimeDistributed``.

All are parameter-free rearrangements (XLA fuses them into neighbouring
ops — they cost nothing on TPU) except the wrappers, which delegate to an
underlying layer. Conv layouts are NHWC / NDHWC (TPU-native); the
reference's NCHW/NCDHW exists only at import boundaries.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (
    InputType, InputTypeConvolutional, InputTypeConvolutional3D,
    InputTypeFeedForward, InputTypeRecurrent)
from deeplearning4j_tpu.nn.conf.layers import (Layer, _pair, register_layer)


# ---------------------------------------------------------------------------
# Cropping
# ---------------------------------------------------------------------------
@register_layer
@dataclass
class Cropping1D(Layer):
    """Crop timesteps off a [b, t, f] sequence (reference: Cropping1D)."""

    cropping: Tuple[int, int] = (0, 0)

    @staticmethod
    def _builder_positional(*args) -> dict:
        return {"cropping": _pair(args if len(args) > 1 else args[0])}

    def __post_init__(self):
        super().__post_init__()
        self.cropping = _pair(self.cropping)

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        a, b = self.cropping
        t = x.shape[1]
        return x[:, a:t - b if b else t, :], state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeRecurrent)
        t = input_type.timesteps
        if t > 0:
            t = t - self.cropping[0] - self.cropping[1]
        return InputType.recurrent(input_type.size, t)


@register_layer
@dataclass
class Cropping2D(Layer):
    """Crop [b, h, w, c] borders (reference: Cropping2D)."""

    crop_top_bottom: Tuple[int, int] = (0, 0)
    crop_left_right: Tuple[int, int] = (0, 0)

    @staticmethod
    def _builder_positional(*args) -> dict:
        if len(args) == 1:
            v = int(args[0])
            return {"crop_top_bottom": (v, v), "crop_left_right": (v, v)}
        if len(args) == 2:
            return {"crop_top_bottom": _pair(args[0]),
                    "crop_left_right": _pair(args[1])}
        t, b, l, r = args
        return {"crop_top_bottom": (int(t), int(b)),
                "crop_left_right": (int(l), int(r))}

    def __post_init__(self):
        super().__post_init__()
        self.crop_top_bottom = _pair(self.crop_top_bottom)
        self.crop_left_right = _pair(self.crop_left_right)

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        (ct, cb), (cl, cr) = self.crop_top_bottom, self.crop_left_right
        h, w = x.shape[1], x.shape[2]
        return x[:, ct:h - cb if cb else h, cl:w - cr if cr else w, :], state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional)
        return InputType.convolutional(
            input_type.height - sum(self.crop_top_bottom),
            input_type.width - sum(self.crop_left_right),
            input_type.channels)


@register_layer
@dataclass
class Cropping3D(Layer):
    """Crop [b, d, h, w, c] borders (reference: Cropping3D)."""

    crop_depth: Tuple[int, int] = (0, 0)
    crop_height: Tuple[int, int] = (0, 0)
    crop_width: Tuple[int, int] = (0, 0)

    def __post_init__(self):
        super().__post_init__()
        self.crop_depth = _pair(self.crop_depth)
        self.crop_height = _pair(self.crop_height)
        self.crop_width = _pair(self.crop_width)

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        (cd0, cd1) = self.crop_depth
        (ch0, ch1) = self.crop_height
        (cw0, cw1) = self.crop_width
        d, h, w = x.shape[1], x.shape[2], x.shape[3]
        return x[:, cd0:d - cd1 if cd1 else d, ch0:h - ch1 if ch1 else h,
                 cw0:w - cw1 if cw1 else w, :], state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional3D)
        return InputType.convolutional_3d(
            input_type.depth - sum(self.crop_depth),
            input_type.height - sum(self.crop_height),
            input_type.width - sum(self.crop_width),
            input_type.channels)


# ---------------------------------------------------------------------------
# Zero padding
# ---------------------------------------------------------------------------
@register_layer
@dataclass
class ZeroPadding1DLayer(Layer):
    """Pad timesteps of [b, t, f] (reference: ZeroPadding1DLayer)."""

    padding: Tuple[int, int] = (0, 0)

    @staticmethod
    def _builder_positional(*args) -> dict:
        return {"padding": _pair(args if len(args) > 1 else args[0])}

    def __post_init__(self):
        super().__post_init__()
        self.padding = _pair(self.padding)

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        a, b = self.padding
        return jnp.pad(x, ((0, 0), (a, b), (0, 0))), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeRecurrent)
        t = input_type.timesteps
        if t > 0:
            t = t + self.padding[0] + self.padding[1]
        return InputType.recurrent(input_type.size, t)


@register_layer
@dataclass
class ZeroPaddingLayer(Layer):
    """Pad [b, h, w, c] borders (reference: ZeroPaddingLayer)."""

    pad_top_bottom: Tuple[int, int] = (0, 0)
    pad_left_right: Tuple[int, int] = (0, 0)

    @staticmethod
    def _builder_positional(*args) -> dict:
        if len(args) == 1:
            v = int(args[0])
            return {"pad_top_bottom": (v, v), "pad_left_right": (v, v)}
        if len(args) == 2:
            return {"pad_top_bottom": _pair(args[0]),
                    "pad_left_right": _pair(args[1])}
        t, b, l, r = args
        return {"pad_top_bottom": (int(t), int(b)),
                "pad_left_right": (int(l), int(r))}

    def __post_init__(self):
        super().__post_init__()
        self.pad_top_bottom = _pair(self.pad_top_bottom)
        self.pad_left_right = _pair(self.pad_left_right)

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        return jnp.pad(x, ((0, 0), self.pad_top_bottom,
                           self.pad_left_right, (0, 0))), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional)
        return InputType.convolutional(
            input_type.height + sum(self.pad_top_bottom),
            input_type.width + sum(self.pad_left_right),
            input_type.channels)


@register_layer
@dataclass
class ZeroPadding3DLayer(Layer):
    """Pad [b, d, h, w, c] borders (reference: ZeroPadding3DLayer)."""

    pad_depth: Tuple[int, int] = (0, 0)
    pad_height: Tuple[int, int] = (0, 0)
    pad_width: Tuple[int, int] = (0, 0)

    def __post_init__(self):
        super().__post_init__()
        self.pad_depth = _pair(self.pad_depth)
        self.pad_height = _pair(self.pad_height)
        self.pad_width = _pair(self.pad_width)

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        return jnp.pad(x, ((0, 0), self.pad_depth, self.pad_height,
                           self.pad_width, (0, 0))), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional3D)
        return InputType.convolutional_3d(
            input_type.depth + sum(self.pad_depth),
            input_type.height + sum(self.pad_height),
            input_type.width + sum(self.pad_width),
            input_type.channels)


# ---------------------------------------------------------------------------
# Block rearrangement
# ---------------------------------------------------------------------------
@register_layer
@dataclass
class SpaceToDepthLayer(Layer):
    """[b, h, w, c] -> [b, h/s, w/s, c*s*s] (reference: SpaceToDepthLayer).
    NHWC blocks gather into the channel dim (the reference's NCHW/NHWC
    dataFormat flag collapses: TPU layout is always NHWC)."""

    block_size: int = 2

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        s = self.block_size
        b, h, w, c = x.shape
        z = x.reshape(b, h // s, s, w // s, s, c)
        z = z.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // s, w // s,
                                                  s * s * c)
        return z, state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional)
        s = self.block_size
        return InputType.convolutional(input_type.height // s,
                                       input_type.width // s,
                                       input_type.channels * s * s)


@register_layer
@dataclass
class DepthToSpaceLayer(Layer):
    """[b, h, w, c] -> [b, h*s, w*s, c/(s*s)] (reference:
    DepthToSpaceLayer); exact inverse of SpaceToDepthLayer."""

    block_size: int = 2

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        s = self.block_size
        b, h, w, c = x.shape
        co = c // (s * s)
        z = x.reshape(b, h, w, s, s, co)
        z = z.transpose(0, 1, 3, 2, 4, 5).reshape(b, h * s, w * s, co)
        return z, state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional)
        s = self.block_size
        return InputType.convolutional(input_type.height * s,
                                       input_type.width * s,
                                       input_type.channels // (s * s))


@register_layer
@dataclass
class Upsampling1D(Layer):
    """Repeat timesteps (reference: Upsampling1D)."""

    size: int = 2

    @staticmethod
    def _builder_positional(*args) -> dict:
        return {"size": int(args[0])} if args else {}

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        return jnp.repeat(x, self.size, axis=1), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeRecurrent)
        t = input_type.timesteps
        return InputType.recurrent(input_type.size,
                                   t * self.size if t > 0 else t)


@register_layer
@dataclass
class Upsampling3D(Layer):
    """Nearest-neighbour volumetric upsampling (reference: Upsampling3D)."""

    size: Tuple[int, int, int] = (2, 2, 2)

    def __post_init__(self):
        super().__post_init__()
        if isinstance(self.size, int):
            self.size = (self.size,) * 3
        self.size = tuple(int(v) for v in self.size)

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        sd, sh, sw = self.size
        z = jnp.repeat(x, sd, axis=1)
        z = jnp.repeat(z, sh, axis=2)
        z = jnp.repeat(z, sw, axis=3)
        return z, state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional3D)
        sd, sh, sw = self.size
        return InputType.convolutional_3d(input_type.depth * sd,
                                          input_type.height * sh,
                                          input_type.width * sw,
                                          input_type.channels)


@register_layer
@dataclass
class RepeatVector(Layer):
    """[b, f] -> [b, n, f] (reference: RepeatVector)."""

    repetition_factor: int = 1

    @staticmethod
    def _builder_positional(*args) -> dict:
        return {"repetition_factor": int(args[0])} if args else {}

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        return jnp.broadcast_to(x[:, None, :],
                                (x.shape[0], self.repetition_factor,
                                 x.shape[1])), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeFeedForward)
        return InputType.recurrent(input_type.size, self.repetition_factor)


# ---------------------------------------------------------------------------
# Mask utilities
# ---------------------------------------------------------------------------
@register_layer
@dataclass
class MaskLayer(Layer):
    """Zero out masked timesteps of [b, t, f] activations (reference:
    conf.layers.util.MaskLayer — applies the feature mask so downstream
    non-mask-aware layers see clean zeros)."""

    def has_params(self) -> bool:
        return False

    def accepts_mask(self) -> bool:
        return True

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        if mask is not None and x.ndim == 3:
            x = x * mask[..., None].astype(x.dtype)
        return x, state

    def get_output_type(self, input_type):
        return input_type


@register_layer
@dataclass
class MaskZeroLayer(Layer):
    """Wrap a recurrent layer; timesteps whose inputs are all equal to
    ``mask_value`` are masked (reference: conf.layers.util.MaskZeroLayer).
    The derived mask multiplies the wrapped layer's output to zero at
    masked steps, matching the reference's zero-state carry semantics."""

    underlying: Optional[Layer] = None
    mask_value: float = 0.0

    def __post_init__(self):
        super().__post_init__()
        if isinstance(self.underlying, dict):
            self.underlying = Layer.from_map(self.underlying)

    # delegate the runtime protocol --------------------------------------
    def has_params(self) -> bool:
        return self.underlying.has_params()

    def has_state(self) -> bool:
        return self.underlying.has_state()

    def is_recurrent(self) -> bool:
        return self.underlying.is_recurrent()

    def zero_state(self, batch, dtype=jnp.float32):
        return self.underlying.zero_state(batch, dtype)

    def init_params(self, key, input_type, dtype=jnp.float32):
        return self.underlying.init_params(key, input_type, dtype)

    def init_state(self, input_type, dtype=jnp.float32):
        return self.underlying.init_state(input_type, dtype)

    def set_n_in(self, input_type, override):
        self.underlying.set_n_in(input_type, override)

    def get_output_type(self, input_type):
        return self.underlying.get_output_type(input_type)

    def forward(self, params, x, *, training, rng=None, state=None,
                **kw):
        derived = jnp.any(x != self.mask_value, axis=-1).astype(x.dtype)
        if self.underlying.accepts_mask():
            kw["mask"] = derived
        y, new_state = self.underlying.forward(params, x, training=training,
                                               rng=rng, state=state, **kw)
        if y.ndim == 3:
            y = y * derived[..., None]
        return y, new_state

    def to_map(self) -> dict:
        d = {"@class": type(self).__name__,
             "mask_value": self.mask_value,
             "underlying": self.underlying.to_map()}
        return d


@register_layer
@dataclass
class FrozenLayer(Layer):
    """Wrap any layer with parameters frozen (reference:
    conf.layers.misc.FrozenLayer / FrozenLayerWithBackprop — in the
    functional design ``stop_gradient`` on the wrapped params gives
    exactly both behaviours: zero param grads, epsilon still flows)."""

    underlying: Optional[Layer] = None

    def __post_init__(self):
        super().__post_init__()
        if isinstance(self.underlying, dict):
            self.underlying = Layer.from_map(self.underlying)

    def is_frozen(self) -> bool:
        # MultiLayerNetwork._regularization checks this: l1/l2 on frozen
        # weights would otherwise produce nonzero gradients the updater
        # applies, decaying the "frozen" params
        return True

    def has_params(self) -> bool:
        return self.underlying.has_params()

    def has_state(self) -> bool:
        return self.underlying.has_state()

    def is_recurrent(self) -> bool:
        return self.underlying.is_recurrent()

    def accepts_mask(self) -> bool:
        return self.underlying.accepts_mask()

    def zero_state(self, batch, dtype=jnp.float32):
        return self.underlying.zero_state(batch, dtype)

    def init_params(self, key, input_type, dtype=jnp.float32):
        return self.underlying.init_params(key, input_type, dtype)

    def init_state(self, input_type, dtype=jnp.float32):
        return self.underlying.init_state(input_type, dtype)

    def set_n_in(self, input_type, override):
        self.underlying.set_n_in(input_type, override)

    def get_output_type(self, input_type):
        return self.underlying.get_output_type(input_type)

    def forward(self, params, x, *, training, rng=None, state=None, **kw):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.underlying.forward(frozen, x, training=training,
                                       rng=rng, state=state, **kw)

    def to_map(self) -> dict:
        return {"@class": type(self).__name__,
                "underlying": self.underlying.to_map()}


@register_layer
@dataclass
class TimeDistributed(Layer):
    """Apply a feed-forward layer independently per timestep (reference:
    conf.layers.recurrent.TimeDistributed). [b, t, f] -> flatten to
    [b*t, f] -> wrapped layer -> restore."""

    underlying: Optional[Layer] = None

    def __post_init__(self):
        super().__post_init__()
        if isinstance(self.underlying, dict):
            self.underlying = Layer.from_map(self.underlying)

    def has_params(self) -> bool:
        return self.underlying.has_params()

    def has_state(self) -> bool:
        return self.underlying.has_state()

    def accepts_mask(self) -> bool:
        return False   # per-timestep application; mask handled upstream

    def init_state(self, input_type, dtype=jnp.float32):
        assert isinstance(input_type, InputTypeRecurrent)
        return self.underlying.init_state(
            InputType.feed_forward(input_type.size), dtype)

    def init_params(self, key, input_type, dtype=jnp.float32):
        assert isinstance(input_type, InputTypeRecurrent)
        return self.underlying.init_params(
            key, InputType.feed_forward(input_type.size), dtype)

    def set_n_in(self, input_type, override):
        assert isinstance(input_type, InputTypeRecurrent)
        self.underlying.set_n_in(InputType.feed_forward(input_type.size),
                                 override)

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeRecurrent)
        out = self.underlying.get_output_type(
            InputType.feed_forward(input_type.size))
        return InputType.recurrent(out.size, input_type.timesteps)

    def forward(self, params, x, *, training, rng=None, state=None, **kw):
        b, t, f = x.shape
        y, new_state = self.underlying.forward(
            params, x.reshape(b * t, f), training=training, rng=rng,
            state=state, **kw)
        return y.reshape(b, t, -1), new_state

    def to_map(self) -> dict:
        return {"@class": type(self).__name__,
                "underlying": self.underlying.to_map()}
