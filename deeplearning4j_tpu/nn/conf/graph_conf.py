"""ComputationGraphConfiguration + GraphBuilder.

Reference parity: ``org.deeplearning4j.nn.conf.ComputationGraphConfiguration``
and ``NeuralNetConfiguration.Builder().graphBuilder()`` (SURVEY.md D1/D3):
addInputs / addLayer / addVertex / setOutputs / setInputTypes, topo-sorted
DAG with per-vertex input lists, JSON round-trip.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from deeplearning4j_tpu.learning.updaters import IUpdater, Sgd
from deeplearning4j_tpu.nn.conf.builders import (BackpropType,
                                                 GradientNormalization)
from deeplearning4j_tpu.nn.conf.graph_vertices import GraphVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.conf.preprocessors import InputPreProcessor
from deeplearning4j_tpu.nn.weights import WeightInit


@dataclass
class VertexDef:
    """One node: a Layer or a GraphVertex + its input vertex names."""
    name: str
    content: Union[Layer, GraphVertex]
    inputs: List[str]
    preprocessor: Optional[InputPreProcessor] = None

    @property
    def is_layer(self) -> bool:
        return isinstance(self.content, Layer)


@dataclass
class ComputationGraphConfiguration:
    network_inputs: List[str] = field(default_factory=list)
    network_outputs: List[str] = field(default_factory=list)
    vertices: Dict[str, VertexDef] = field(default_factory=dict)
    input_types: List[InputType] = field(default_factory=list)
    seed: int = 12345
    updater: IUpdater = field(default_factory=lambda: Sgd(1e-3))
    weight_init: WeightInit = WeightInit.XAVIER
    l1: float = 0.0
    l2: float = 0.0
    gradient_normalization: GradientNormalization = \
        GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0
    backprop_type: BackpropType = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    dtype: str = "float32"
    compute_dtype: Optional[str] = None   # None = same as dtype
    #: activation rematerialization: cut the training forward walk
    #: into this many contiguous segments, each under
    #: ``jax.checkpoint`` — only segment-boundary activations are
    #: stored for backward, interior ones are recomputed (the
    #: sqrt(N)-checkpointing recipe; a TPU-first HBM-traffic knob
    #: with no reference equivalent). 0 = store everything.
    remat_segments: int = 0

    # ------------------------------------------------------------------
    def topo_order(self) -> List[str]:
        """Topologically sorted vertex names (inputs excluded)."""
        order: List[str] = []
        visited: Dict[str, int] = {}  # 0=visiting, 1=done

        def visit(name: str):
            if name in self.network_inputs:
                return
            st = visited.get(name)
            if st == 1:
                return
            if st == 0:
                raise ValueError(f"cycle at vertex {name!r}")
            visited[name] = 0
            for dep in self.vertices[name].inputs:
                visit(dep)
            visited[name] = 1
            order.append(name)

        for name in self.vertices:
            visit(name)
        return order

    # -- shape inference -------------------------------------------------
    def resolve_shapes(self):
        if not self.input_types:
            return
        types: Dict[str, InputType] = dict(zip(self.network_inputs,
                                               self.input_types))
        from deeplearning4j_tpu.nn.conf.builders import \
            _default_preprocessor
        for name in self.topo_order():
            v = self.vertices[name]
            in_types = [types[i] for i in v.inputs]
            cur = in_types[0] if in_types else None
            if v.is_layer:
                if v.preprocessor is None and cur is not None:
                    v.preprocessor = _default_preprocessor(cur, v.content)
                if v.preprocessor is not None:
                    cur = v.preprocessor.get_output_type(cur)
                v.content.set_n_in(cur, override=False)
                types[name] = v.content.get_output_type(cur)
            else:
                types[name] = v.content.get_output_type(in_types)
        self._resolved_types = types

    # -- JSON --------------------------------------------------------
    def to_json(self) -> str:
        d = {
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "vertices": [{
                "name": v.name,
                "kind": "layer" if v.is_layer else "vertex",
                "content": v.content.to_map(),
                "inputs": v.inputs,
                "preprocessor": v.preprocessor.to_map()
                                if v.preprocessor else None,
            } for v in self.vertices.values()],
            "input_types": [t.to_map() for t in self.input_types],
            "seed": self.seed,
            "updater": self.updater.to_map(),
            "weight_init": self.weight_init.name,
            "l1": self.l1, "l2": self.l2,
            "gradient_normalization": self.gradient_normalization.name,
            "gradient_normalization_threshold":
                self.gradient_normalization_threshold,
            "backprop_type": self.backprop_type.name,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "remat_segments": self.remat_segments,
        }
        return json.dumps(d, indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        d = json.loads(s)
        conf = ComputationGraphConfiguration(
            network_inputs=list(d["network_inputs"]),
            network_outputs=list(d["network_outputs"]),
            input_types=[InputType.from_map(t)
                         for t in d.get("input_types", [])],
            seed=d.get("seed", 12345),
            updater=IUpdater.from_map(d["updater"]),
            weight_init=WeightInit[d.get("weight_init", "XAVIER")],
            l1=d.get("l1", 0.0), l2=d.get("l2", 0.0),
            gradient_normalization=GradientNormalization[
                d.get("gradient_normalization", "NONE")],
            gradient_normalization_threshold=d.get(
                "gradient_normalization_threshold", 1.0),
            backprop_type=BackpropType[d.get("backprop_type", "STANDARD")],
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            remat_segments=d.get("remat_segments", 0),
        )
        for vd in d["vertices"]:
            content = Layer.from_map(vd["content"]) \
                if vd["kind"] == "layer" \
                else GraphVertex.from_map(vd["content"])
            conf.vertices[vd["name"]] = VertexDef(
                vd["name"], content, list(vd["inputs"]),
                InputPreProcessor.from_map(vd["preprocessor"])
                if vd.get("preprocessor") else None)
        conf.resolve_shapes()
        return conf


class GraphBuilder:
    """Reference: NeuralNetConfiguration.Builder().graphBuilder()."""

    def __init__(self, base):
        self._base = base
        self._conf = ComputationGraphConfiguration()

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._conf.network_inputs.extend(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._conf.input_types = list(types)
        return self

    def add_layer(self, name: str, layer: Layer,
                  *inputs: str) -> "GraphBuilder":
        # optional preprocessor as first input arg (reference overload)
        pre = None
        ins = list(inputs)
        if ins and isinstance(ins[0], InputPreProcessor):
            pre = ins.pop(0)
        self._conf.vertices[name] = VertexDef(name, layer, ins, pre)
        return self

    def add_vertex(self, name: str, vertex: GraphVertex,
                   *inputs: str) -> "GraphBuilder":
        self._conf.vertices[name] = VertexDef(name, vertex, list(inputs))
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._conf.network_outputs = list(names)
        return self

    def backprop_type(self, t: BackpropType) -> "GraphBuilder":
        self._conf.backprop_type = t
        return self

    def remat_segments(self, n: int) -> "GraphBuilder":
        """Rematerialize training activations in ``n`` checkpointed
        segments of the topo walk (0 = off). An explicit value here —
        including 0 — overrides the base builder's setting."""
        self._conf.remat_segments = int(n)
        self._remat_explicit = True
        return self

    def t_bptt_length(self, fwd: int, back: int = None) -> "GraphBuilder":
        self._conf.tbptt_fwd_length = fwd
        self._conf.tbptt_back_length = back if back is not None else fwd
        return self

    def build(self) -> ComputationGraphConfiguration:
        b = self._base
        c = self._conf
        c.seed = b._seed
        c.updater = b._updater
        c.weight_init = b._weight_init
        c.l1, c.l2 = b._l1, b._l2
        c.gradient_normalization = b._grad_norm
        c.gradient_normalization_threshold = b._grad_norm_threshold
        c.dtype = b._dtype
        c.compute_dtype = b._compute_dtype
        if not getattr(self, "_remat_explicit", False):
            c.remat_segments = getattr(b, "_remat_segments", 0)
        from deeplearning4j_tpu.nn.conf.builders import \
            apply_layer_defaults
        for v in c.vertices.values():
            if v.is_layer:
                apply_layer_defaults(v.content, b)
        if not c.network_outputs:
            raise ValueError("setOutputs(...) not called")
        c.resolve_shapes()
        return c
