"""Graph vertices: the DAG building blocks of ComputationGraph.

Reference parity: ``org.deeplearning4j.nn.conf.graph.*`` configs and their
``org.deeplearning4j.nn.graph.vertex.impl.*`` runtime twins (SURVEY.md D3):
MergeVertex, ElementWiseVertex, SubsetVertex, ScaleVertex, ShiftVertex,
StackVertex, UnstackVertex, PreprocessorVertex, L2NormalizeVertex. Layer
vertices wrap a Layer config. All are pure functions fused by XLA.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (InputType,
                                               InputTypeConvolutional,
                                               InputTypeFeedForward,
                                               InputTypeRecurrent)
from deeplearning4j_tpu.nn.conf.preprocessors import InputPreProcessor


class GraphVertex:
    """forward(inputs: list[Array]) -> Array; single-output vertices."""

    def forward(self, inputs: list, *, training: bool = False):
        raise NotImplementedError

    def get_output_type(self, input_types: List[InputType]) -> InputType:
        raise NotImplementedError

    def to_map(self) -> dict:
        d = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            d[k] = v.name if isinstance(v, enum.Enum) else v
        return d

    @staticmethod
    def from_map(d: dict) -> "GraphVertex":
        d = dict(d)
        cls = VERTEX_REGISTRY[d.pop("@class")]
        if cls is ElementWiseVertex and isinstance(d.get("op"), str):
            d["op"] = ElementWiseVertex.Op[d["op"]]
        return cls(**d)


@dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature/channel (last) axis (reference:
    MergeVertex — NCHW channel-1 there, NHWC channel-last here)."""

    def forward(self, inputs, *, training=False):
        return jnp.concatenate(inputs, axis=-1)

    def get_output_type(self, input_types):
        t0 = input_types[0]
        if isinstance(t0, InputTypeConvolutional):
            return InputType.convolutional(
                t0.height, t0.width,
                sum(t.channels for t in input_types))
        if isinstance(t0, InputTypeRecurrent):
            return InputType.recurrent(sum(t.size for t in input_types),
                                       t0.timesteps)
        return InputType.feed_forward(sum(t.size for t in input_types))


@dataclass
class ElementWiseVertex(GraphVertex):
    class Op(enum.Enum):
        Add = "add"
        Subtract = "subtract"
        Product = "product"
        Average = "average"
        Max = "max"
        Min = "min"

    op: "ElementWiseVertex.Op" = None

    def __post_init__(self):
        if isinstance(self.op, str):
            self.op = ElementWiseVertex.Op[self.op]
        if self.op is None:
            self.op = ElementWiseVertex.Op.Add

    def forward(self, inputs, *, training=False):
        op = self.op
        if op is ElementWiseVertex.Op.Add:
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op is ElementWiseVertex.Op.Subtract:
            if len(inputs) != 2:
                raise ValueError(
                    f"Subtract needs exactly 2 inputs, got {len(inputs)}")
            return inputs[0] - inputs[1]
        if op is ElementWiseVertex.Op.Product:
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op is ElementWiseVertex.Op.Average:
            return sum(inputs) / len(inputs)
        if op is ElementWiseVertex.Op.Max:
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        if op is ElementWiseVertex.Op.Min:
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.minimum(out, x)
            return out
        raise ValueError(op)

    def get_output_type(self, input_types):
        return input_types[0]


@dataclass
class SubsetVertex(GraphVertex):
    """Feature range [from_idx, to_idx] inclusive (reference: SubsetVertex)."""
    from_idx: int = 0
    to_idx: int = 0

    def forward(self, inputs, *, training=False):
        return inputs[0][..., self.from_idx:self.to_idx + 1]

    def get_output_type(self, input_types):
        n = self.to_idx - self.from_idx + 1
        t = input_types[0]
        if isinstance(t, InputTypeConvolutional):
            return InputType.convolutional(t.height, t.width, n)
        if isinstance(t, InputTypeRecurrent):
            return InputType.recurrent(n, t.timesteps)
        return InputType.feed_forward(n)


@dataclass
class ScaleVertex(GraphVertex):
    scale_factor: float = 1.0

    def forward(self, inputs, *, training=False):
        return inputs[0] * self.scale_factor

    def get_output_type(self, input_types):
        return input_types[0]


@dataclass
class ShiftVertex(GraphVertex):
    shift_factor: float = 0.0

    def forward(self, inputs, *, training=False):
        return inputs[0] + self.shift_factor

    def get_output_type(self, input_types):
        return input_types[0]


@dataclass
class StackVertex(GraphVertex):
    """Stack along batch dim (reference: StackVertex)."""

    def forward(self, inputs, *, training=False):
        return jnp.concatenate(inputs, axis=0)

    def get_output_type(self, input_types):
        return input_types[0]


@dataclass
class UnstackVertex(GraphVertex):
    """Slice the batch dim back apart (reference: UnstackVertex)."""
    from_idx: int = 0
    stack_size: int = 1

    def forward(self, inputs, *, training=False):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_idx * n:(self.from_idx + 1) * n]

    def get_output_type(self, input_types):
        return input_types[0]


@dataclass
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def forward(self, inputs, *, training=False):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + self.eps)
        return x / n

    def get_output_type(self, input_types):
        return input_types[0]


@dataclass
class PreprocessorVertex(GraphVertex):
    preprocessor: Optional[InputPreProcessor] = None

    def forward(self, inputs, *, training=False):
        return self.preprocessor.pre_process(inputs[0])

    def get_output_type(self, input_types):
        return self.preprocessor.get_output_type(input_types[0])

    def to_map(self):
        return {"@class": "PreprocessorVertex",
                "preprocessor": self.preprocessor.to_map()}


def _preproc_from_map(preprocessor):
    return PreprocessorVertex(InputPreProcessor.from_map(preprocessor))


VERTEX_REGISTRY: dict = {c.__name__: c for c in
                         (MergeVertex, ElementWiseVertex, SubsetVertex,
                          ScaleVertex, ShiftVertex, StackVertex,
                          UnstackVertex, L2NormalizeVertex)}
VERTEX_REGISTRY["PreprocessorVertex"] = \
    lambda preprocessor: _preproc_from_map(preprocessor)
