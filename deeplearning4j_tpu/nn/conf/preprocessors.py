"""Input preprocessors: shape adapters auto-inserted between layers.

Reference parity: ``org.deeplearning4j.nn.conf.preprocessor.*`` (SURVEY.md
D1): FeedForwardToCnnPreProcessor, CnnToFeedForwardPreProcessor,
RnnToFeedForwardPreProcessor, FeedForwardToRnnPreProcessor. All are pure
reshapes — XLA folds them into the surrounding ops.

Layout note: CNN activations are NHWC here (see inputs.py); the flat order
used by ``convolutional_flat`` is [h, w, c] row-major, which matches the
flattened NHWC buffer, so flatten/unflatten are views.
"""
from __future__ import annotations

from dataclasses import dataclass

from deeplearning4j_tpu.nn.conf.inputs import InputType


class InputPreProcessor:
    def pre_process(self, x):
        raise NotImplementedError

    def get_output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def to_map(self) -> dict:
        d = {"@class": type(self).__name__}
        d.update(self.__dict__)
        return d

    @staticmethod
    def from_map(d: dict) -> "InputPreProcessor":
        d = dict(d)
        cls = _REGISTRY[d.pop("@class")]
        return cls(**d)


@dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int
    width: int
    channels: int

    def pre_process(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def get_output_type(self, input_type):
        return InputType.convolutional(self.height, self.width,
                                       self.channels)


@dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int
    width: int
    channels: int

    def pre_process(self, x):
        return x.reshape(x.shape[0], -1)

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.height * self.width *
                                      self.channels)


@dataclass
class Cnn3DToFeedForwardPreProcessor(InputPreProcessor):
    """[b, d, h, w, c] -> [b, d*h*w*c] (reference:
    Cnn3DToFeedForwardPreProcessor)."""

    depth: int
    height: int
    width: int
    channels: int

    def pre_process(self, x):
        return x.reshape(x.shape[0], -1)

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.depth * self.height *
                                      self.width * self.channels)


@dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[b, t, f] -> [b*t, f] (reference folds time into batch)."""

    def pre_process(self, x):
        return x.reshape(-1, x.shape[-1])

    def get_output_type(self, input_type):
        return InputType.feed_forward(input_type.size)


@dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    timesteps: int = -1

    def pre_process(self, x):
        return x.reshape(x.shape[0] // max(self.timesteps, 1),
                         self.timesteps, x.shape[-1])

    def get_output_type(self, input_type):
        return InputType.recurrent(input_type.size, self.timesteps)


_REGISTRY = {c.__name__: c for c in
             (FeedForwardToCnnPreProcessor, CnnToFeedForwardPreProcessor,
              Cnn3DToFeedForwardPreProcessor,
              RnnToFeedForwardPreProcessor, FeedForwardToRnnPreProcessor)}
