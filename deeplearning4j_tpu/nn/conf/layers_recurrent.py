"""Recurrent layers: LSTM, GravesLSTM, SimpleRnn, GRU, Bidirectional.

Reference parity: ``org.deeplearning4j.nn.conf.layers.{LSTM, GravesLSTM,
GRU}``, ``recurrent.SimpleRnn``, ``recurrent.Bidirectional`` and their
runtime twins ``org.deeplearning4j.nn.layers.recurrent.*`` with the static
``LSTMHelpers`` math + ``CudnnLSTMHelper`` fast path (SURVEY.md D4/D9,
BASELINE config #3 "GravesLSTM char-RNN exercises CudnnLSTMHelper").

TPU-first design: the time loop is ``jax.lax.scan`` — XLA compiles it to a
single fused while-loop; the per-step input projection ``x @ W`` for ALL
timesteps is hoisted out of the scan as one big [b*t, 4H] matmul on the MXU
(the same restructuring cuDNN performs internally), leaving only the [b, H]
recurrent matmul inside the loop.

Activations are [batch, time, features]. Recurrent state is a dict
{"h": [b,H], ("c": [b,H])} threaded functionally: zero at each fit batch,
carried across tBPTT segments, persisted across ``rnn_time_step`` calls
(SURVEY.md section 5.7 semantics). Per-timestep masks zero the update and
hold the previous state, matching the reference's masked-RNN behavior.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.nn.conf.inputs import (InputType,
                                               InputTypeRecurrent)
from deeplearning4j_tpu.nn.conf.layers import Layer, register_layer
from deeplearning4j_tpu.nn.weights import WeightInit


@dataclass
class BaseRecurrentLayer(Layer):
    activation: Activation = Activation.TANH

    def is_recurrent(self) -> bool:
        return True

    def zero_state(self, batch: int, dtype=jnp.float32) -> dict:
        return {"h": jnp.zeros((batch, self.n_out), dtype)}

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeRecurrent) and \
                (override or not self.n_in):
            self.n_in = input_type.size

    def get_output_type(self, input_type):
        t = input_type.timesteps if isinstance(input_type,
                                               InputTypeRecurrent) else -1
        return InputType.recurrent(self.n_out, t)

    # mask: [b, t] or None. Subclasses implement _scan().
    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        x = self._maybe_dropout(x, training, rng)
        b = x.shape[0]
        if not state:
            state = self.zero_state(b, x.dtype)
        y, new_state = self._scan(params, x, state, mask)
        return y, new_state

    @staticmethod
    def _run_scan(step, carry, xw, mask):
        """Shared time-loop dispatch: ``step(carry, (xw_t, m_t|None))``.
        Owns the [b,t,...] <-> [t,b,...] swaps and the mask/no-mask
        branching for every recurrent subclass."""
        if mask is not None:
            last, ys = jax.lax.scan(step, carry,
                                    (xw.swapaxes(0, 1),
                                     mask.swapaxes(0, 1)))
        else:
            last, ys = jax.lax.scan(lambda c, xt: step(c, (xt, None)),
                                    carry, xw.swapaxes(0, 1))
        return last, ys.swapaxes(0, 1)


@dataclass
class SimpleRnn(BaseRecurrentLayer):
    """h_t = act(x W + h_{t-1} R + b) (reference: recurrent.SimpleRnn)."""

    has_bias: bool = True

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        k1, k2 = jax.random.split(key)
        p = {"W": wi.init(k1, (self.n_in, self.n_out), self.n_in,
                          self.n_out, dtype),
             "RW": wi.init(k2, (self.n_out, self.n_out), self.n_out,
                           self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def _scan(self, params, x, state, mask):
        act = self.activation.fn()
        # hoist the input projection out of the loop: one MXU matmul
        xw = x @ params["W"]
        if self.has_bias:
            xw = xw + params["b"]

        def step(h, inp):
            xw_t, m_t = inp
            h_new = act(xw_t + h @ params["RW"])
            if m_t is not None:
                h_new = jnp.where(m_t[:, None] > 0, h_new, h)
            return h_new, h_new

        h_last, ys = self._run_scan(step, state["h"], xw, mask)
        return ys, {"h": h_last}


@dataclass
class LSTM(BaseRecurrentLayer):
    """Standard LSTM, gate order [i, f, o, g] (reference: conf.layers.LSTM;
    the cuDNN helper path is here the scan+fused-matmul lowering)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: Activation = Activation.SIGMOID
    has_bias: bool = True

    def zero_state(self, batch: int, dtype=jnp.float32) -> dict:
        return {"h": jnp.zeros((batch, self.n_out), dtype),
                "c": jnp.zeros((batch, self.n_out), dtype)}

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        k1, k2 = jax.random.split(key)
        H = self.n_out
        p = {"W": wi.init(k1, (self.n_in, 4 * H), self.n_in, H, dtype),
             "RW": wi.init(k2, (H, 4 * H), H, H, dtype)}
        if self.has_bias:
            b = jnp.full((4 * H,), self.bias_init, dtype)
            # forget-gate bias init (reference default 1.0)
            b = b.at[H:2 * H].set(self.forget_gate_bias_init)
            p["b"] = b
        return p

    def _gates(self, z, c_prev, params):
        H = self.n_out
        gate = self.gate_activation.fn()
        act = self.activation.fn()
        i = gate(z[:, :H])
        f = gate(z[:, H:2 * H])
        o = gate(z[:, 2 * H:3 * H])
        g = act(z[:, 3 * H:])
        c = f * c_prev + i * g
        h = o * act(c)
        return h, c

    def _scan(self, params, x, state, mask):
        xw = x @ params["W"]
        if self.has_bias:
            xw = xw + params["b"]

        def step(carry, inp):
            h_prev, c_prev = carry
            xw_t, m_t = inp
            z = xw_t + h_prev @ params["RW"]
            h, c = self._gates(z, c_prev, params)
            if m_t is not None:
                keep = m_t[:, None] > 0
                h = jnp.where(keep, h, h_prev)
                c = jnp.where(keep, c, c_prev)
            return (h, c), h

        (h_last, c_last), ys = self._run_scan(
            step, (state["h"], state["c"]), xw, mask)
        return ys, {"h": h_last, "c": c_last}


@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013) — reference:
    conf.layers.GravesLSTM, the BASELINE config #3 layer. Peepholes add
    c_{t-1} terms to the input/forget gates and c_t to the output gate."""

    def init_params(self, key, input_type, dtype=jnp.float32):
        p = super().init_params(key, input_type, dtype)
        H = self.n_out
        k = jax.random.fold_in(key, 1)
        wi = self.weight_init or WeightInit.XAVIER
        p["pI"] = wi.init(jax.random.fold_in(k, 0), (H,), H, H, dtype)
        p["pF"] = wi.init(jax.random.fold_in(k, 1), (H,), H, H, dtype)
        p["pO"] = wi.init(jax.random.fold_in(k, 2), (H,), H, H, dtype)
        return p

    def _gates(self, z, c_prev, params):
        H = self.n_out
        gate = self.gate_activation.fn()
        act = self.activation.fn()
        i = gate(z[:, :H] + c_prev * params["pI"])
        f = gate(z[:, H:2 * H] + c_prev * params["pF"])
        g = act(z[:, 3 * H:])
        c = f * c_prev + i * g
        o = gate(z[:, 2 * H:3 * H] + c * params["pO"])
        h = o * act(c)
        return h, c


@dataclass
class GRU(BaseRecurrentLayer):
    """GRU (reference: conf.layers.GRU / nd4j gruCell op)."""

    gate_activation: Activation = Activation.SIGMOID
    has_bias: bool = True
    # separate recurrent bias on the candidate gate, gated by r
    # (Keras GRU reset_after=True semantics; set by the Keras importer)
    recurrent_bias: bool = False

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        k1, k2 = jax.random.split(key)
        H = self.n_out
        p = {"W": wi.init(k1, (self.n_in, 3 * H), self.n_in, H, dtype),
             "RW": wi.init(k2, (H, 3 * H), H, H, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((3 * H,), self.bias_init, dtype)
        if self.recurrent_bias:
            p["rb"] = jnp.zeros((H,), dtype)
        return p

    def _scan(self, params, x, state, mask):
        H = self.n_out
        gate = self.gate_activation.fn()
        act = self.activation.fn()
        xw = x @ params["W"]
        if self.has_bias:
            xw = xw + params["b"]

        # optional recurrent bias on the candidate gate (set by the
        # Keras importer for reset_after=True GRUs; absent otherwise)
        rb = params.get("rb")

        def step(h_prev, inp):
            xw_t, m_t = inp
            hr = h_prev @ params["RW"]
            r = gate(xw_t[:, :H] + hr[:, :H])
            zt = gate(xw_t[:, H:2 * H] + hr[:, H:2 * H])
            hr_n = hr[:, 2 * H:] if rb is None else hr[:, 2 * H:] + rb
            n = act(xw_t[:, 2 * H:] + r * hr_n)
            h = (1 - zt) * n + zt * h_prev
            if m_t is not None:
                h = jnp.where(m_t[:, None] > 0, h, h_prev)
            return h, h

        h_last, ys = self._run_scan(step, state["h"], xw, mask)
        return ys, {"h": h_last}


class BidirectionalMode(enum.Enum):
    CONCAT = "concat"
    ADD = "add"
    MUL = "mul"
    AVERAGE = "average"


@dataclass
class Bidirectional(BaseRecurrentLayer):
    """Wrapper running a recurrent layer forward + backward over time
    (reference: recurrent.Bidirectional(mode, layer))."""

    fwd: Optional[BaseRecurrentLayer] = None
    mode: BidirectionalMode = BidirectionalMode.CONCAT

    def __post_init__(self):
        super().__post_init__()
        if isinstance(self.mode, str):
            self.mode = BidirectionalMode[self.mode.upper()]
        if self.fwd is not None:
            self.n_out = self.fwd.n_out

    def zero_state(self, batch: int, dtype=jnp.float32) -> dict:
        return {"fwd": self.fwd.zero_state(batch, dtype),
                "bwd": self.fwd.zero_state(batch, dtype)}

    def set_n_in(self, input_type, override):
        super().set_n_in(input_type, override)
        self.fwd.set_n_in(input_type, override)

    def init_params(self, key, input_type, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        return {"fwd": self.fwd.init_params(k1, input_type, dtype),
                "bwd": self.fwd.init_params(k2, input_type, dtype)}

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        if not state:
            state = self.zero_state(x.shape[0], x.dtype)
        rng_f = rng_b = None
        if rng is not None:  # independent dropout masks per direction
            rng_f, rng_b = jax.random.split(rng)
        y_f, s_f = self.fwd.forward(params["fwd"], x, training=training,
                                    rng=rng_f, state=state["fwd"],
                                    mask=mask)
        x_rev = jnp.flip(x, axis=1)
        m_rev = jnp.flip(mask, axis=1) if mask is not None else None
        y_b, s_b = self.fwd.forward(params["bwd"], x_rev,
                                    training=training, rng=rng_b,
                                    state=state["bwd"], mask=m_rev)
        y_b = jnp.flip(y_b, axis=1)
        if self.mode is BidirectionalMode.CONCAT:
            y = jnp.concatenate([y_f, y_b], axis=-1)
        elif self.mode is BidirectionalMode.ADD:
            y = y_f + y_b
        elif self.mode is BidirectionalMode.MUL:
            y = y_f * y_b
        else:
            y = 0.5 * (y_f + y_b)
        return y, {"fwd": s_f, "bwd": s_b}

    def get_output_type(self, input_type):
        t = input_type.timesteps if isinstance(input_type,
                                               InputTypeRecurrent) else -1
        n = self.fwd.n_out * (2 if self.mode is BidirectionalMode.CONCAT
                              else 1)
        return InputType.recurrent(n, t)

    def to_map(self):
        return {"@class": "Bidirectional",
                "mode": self.mode.name,
                "fwd": self.fwd.to_map()}


@dataclass
class EmbeddingSequenceLayer(Layer):
    """[b, t] int tokens -> [b, t, n_out] (reference:
    conf.layers.EmbeddingSequenceLayer)."""

    has_bias: bool = False

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        return {"W": wi.init(key, (self.n_in, self.n_out), self.n_in,
                             self.n_out, dtype)}

    def forward(self, params, x, *, training, rng=None, state=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        return params["W"][idx], state

    def get_output_type(self, input_type):
        t = input_type.timesteps if isinstance(input_type,
                                               InputTypeRecurrent) else -1
        return InputType.recurrent(self.n_out, t)

    def set_n_in(self, input_type, override):
        pass  # n_in is the vocabulary size


@dataclass
class LastTimeStepLayer(Layer):
    """[b, t, f] -> [b, f], last unmasked step (reference:
    recurrent.LastTimeStep wrapper)."""

    def has_params(self) -> bool:
        return False

    def accepts_mask(self) -> bool:
        return True

    def forward(self, params, x, *, training, rng=None, state=None,
                mask=None):
        if mask is not None:
            idx = jnp.maximum(jnp.sum(mask > 0, axis=1) - 1, 0)
            return x[jnp.arange(x.shape[0]), idx.astype(jnp.int32)], state
        return x[:, -1], state

    def get_output_type(self, input_type):
        return InputType.feed_forward(input_type.size)

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeRecurrent):
            self.n_in = self.n_out = input_type.size


@dataclass
class ConvLSTM2D(BaseRecurrentLayer):
    """Convolutional LSTM (Shi et al. 2015) over [b, t, h, w, c]
    sequences — the Keras ``ConvLSTM2D`` import target (reference:
    ``KerasConvLSTM2D`` mapping in deeplearning4j-modelimport).

    Gate order [i, f, o, g], matching :class:`LSTM`.  The input conv
    (kernel ``W`` [kh, kw, C, 4F]) applies stride/padding; the
    recurrent conv (``RW`` [kh, kw, F, 4F]) is stride-1 SAME on the
    state grid.  TPU-first: the input conv for ALL timesteps is
    hoisted out of the scan as one batched MXU conv; only the
    recurrent conv runs per step."""

    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    convolution_mode: "ConvolutionMode" = None
    gate_activation: Activation = Activation.SIGMOID
    forget_gate_bias_init: float = 1.0
    has_bias: bool = True
    return_sequences: bool = True

    def __post_init__(self):
        super().__post_init__()
        from deeplearning4j_tpu.nn.conf.layers import ConvolutionMode
        if self.convolution_mode is None:
            self.convolution_mode = ConvolutionMode.SAME
        self.kernel_size = tuple(int(k) for k in self.kernel_size)
        self.stride = tuple(int(s) for s in self.stride)

    def _same(self) -> bool:
        from deeplearning4j_tpu.nn.conf.layers import ConvolutionMode
        return self.convolution_mode is ConvolutionMode.SAME

    def set_n_in(self, input_type, override):
        from deeplearning4j_tpu.nn.conf.inputs import \
            InputTypeConvolutional3D
        if not isinstance(input_type, InputTypeConvolutional3D):
            raise ValueError(
                f"ConvLSTM2D needs InputType.convolutional_3d "
                f"(time as depth), got {input_type}")
        if override or not self.n_in:
            self.n_in = input_type.channels
        self._grid = self._out_hw(input_type.height, input_type.width)

    def _out_hw(self, h, w):
        kh, kw = self.kernel_size
        sh, sw = self.stride
        if self._same():
            return (-(-h // sh), -(-w // sw))
        return ((h - kh) // sh + 1, (w - kw) // sw + 1)

    def zero_state(self, batch: int, dtype=jnp.float32) -> dict:
        gh, gw = self._grid
        z = jnp.zeros((batch, gh, gw, self.n_out), dtype)
        return {"h": z, "c": z}

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = self.kernel_size
        F = self.n_out
        wi = self.weight_init or WeightInit.XAVIER
        k1, k2 = jax.random.split(key)
        fan = kh * kw * self.n_in
        p = {"W": wi.init(k1, (kh, kw, self.n_in, 4 * F), fan,
                          kh * kw * F, dtype),
             "RW": wi.init(k2, (kh, kw, F, 4 * F), kh * kw * F,
                           kh * kw * F, dtype)}
        if self.has_bias:
            b = jnp.full((4 * F,), self.bias_init, dtype)
            p["b"] = b.at[F:2 * F].set(self.forget_gate_bias_init)
        return p

    def _scan(self, params, x, state, mask):
        F = self.n_out
        gate = self.gate_activation.fn()
        act = self.activation.fn()
        b, t, h, w, c = x.shape
        pad = "SAME" if self._same() else "VALID"
        # hoist the input conv over every timestep: one conv on the
        # [b*t] batch
        xw = jax.lax.conv_general_dilated(
            x.reshape(b * t, h, w, c), params["W"],
            window_strides=self.stride, padding=pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            xw = xw + params["b"]
        xw = xw.reshape((b, t) + xw.shape[1:])

        def step(carry, inp):
            h_prev, c_prev = carry
            xw_t, m_t = inp
            z = xw_t + jax.lax.conv_general_dilated(
                h_prev, params["RW"], window_strides=(1, 1),
                padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            i = gate(z[..., :F])
            f = gate(z[..., F:2 * F])
            o = gate(z[..., 2 * F:3 * F])
            g = act(z[..., 3 * F:])
            cc = f * c_prev + i * g
            hh = o * act(cc)
            if m_t is not None:
                keep = (m_t > 0)[:, None, None, None]
                hh = jnp.where(keep, hh, h_prev)
                cc = jnp.where(keep, cc, c_prev)
            return (hh, cc), hh

        (h_last, c_last), ys = self._run_scan(
            step, (state["h"], state["c"]), xw, mask)
        if not self.return_sequences:
            ys = h_last
        return ys, {"h": h_last, "c": c_last}

    def get_output_type(self, input_type):
        oh, ow = self._out_hw(input_type.height, input_type.width)
        if self.return_sequences:
            return InputType.convolutional_3d(input_type.depth, oh,
                                              ow, self.n_out)
        return InputType.convolutional(oh, ow, self.n_out)


def _bidir_from_map(d):
    return Bidirectional(fwd=Layer.from_map(d["fwd"]),
                         mode=BidirectionalMode[d["mode"]])


for _cls in (SimpleRnn, LSTM, GravesLSTM, GRU, EmbeddingSequenceLayer,
             LastTimeStepLayer, ConvLSTM2D):
    register_layer(_cls)

from deeplearning4j_tpu.nn.conf.layers import LAYER_REGISTRY  # noqa: E402

LAYER_REGISTRY["Bidirectional"] = lambda **d: _bidir_from_map(d)
