"""1D and 3D convolution-family layers (SURVEY.md D4: Conv1D/3D,
Subsampling1D/3D, Deconvolution3D, Cnn3DLossLayer).

Reference parity: ``org.deeplearning4j.nn.conf.layers.{Convolution1DLayer,
Subsampling1DLayer,Convolution3D,Subsampling3DLayer,Deconvolution3D,
Cnn3DLossLayer}``. The reference's 1D layers ride the RNN data format
[b, f, t]; here sequences are [b, t, f] (time-major-after-batch, the
layout every recurrent layer in this framework uses), so conv1d is
``lax.conv_general_dilated`` with ("NWC", "WIO", "NWC") — channels last
for the MXU. 3D is NDHWC / DHWIO (reference: NCDHW).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.nn.conf.inputs import (
    InputType, InputTypeConvolutional3D, InputTypeRecurrent)
from deeplearning4j_tpu.nn.conf.layers import (
    BaseOutputLayer, ConvolutionMode, Layer, PoolingType, register_layer)
from deeplearning4j_tpu.nn.weights import WeightInit


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        return tuple(int(i) for i in v)
    return (int(v),) * 3


# ---------------------------------------------------------------------------
# 1D family — operates on [b, t, f]
# ---------------------------------------------------------------------------
@register_layer
@dataclass
class Convolution1DLayer(Layer):
    """Temporal convolution (reference: Convolution1DLayer)."""

    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: ConvolutionMode = ConvolutionMode.SAME
    has_bias: bool = True
    #: causal (WaveNet-style) padding: left-pad (k-1)*dilation so
    #: output[t] sees only inputs <= t; overrides convolution_mode
    causal: bool = False

    @staticmethod
    def _builder_positional(*args) -> dict:
        return {"kernel_size": int(args[0])} if args else {}

    def __post_init__(self):
        super().__post_init__()
        for f in ("kernel_size", "stride", "padding", "dilation"):
            v = getattr(self, f)
            setattr(self, f, int(v[0] if isinstance(v, (tuple, list))
                                 else v))

    def is_recurrent(self) -> bool:
        return False

    def init_params(self, key, input_type, dtype=jnp.float32):
        k = self.kernel_size
        wi = self.weight_init or WeightInit.XAVIER
        p = {"W": wi.init(key, (k, self.n_in, self.n_out),
                          k * self.n_in, k * self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        if self.causal:
            pad = [((self.kernel_size - 1) * self.dilation, 0)]
        elif self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            pad = [(self.padding, self.padding)]
        # shared fused-epilogue entry point (ops/conv_pallas.py) —
        # dense fallback whenever the structural gates demote the site
        from deeplearning4j_tpu.ops.conv_pallas import conv_forward
        z = conv_forward(
            x, params["W"], window_strides=(self.stride,), padding=pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"),
            bias=params["b"] if self.has_bias else None,
            activation=self.activation)
        return z, state

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeRecurrent) and \
                (override or not self.n_in):
            self.n_in = input_type.size

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeRecurrent), input_type
        t = input_type.timesteps
        if t > 0:
            ek = (self.kernel_size - 1) * self.dilation + 1
            if self.causal:
                t = (t + (ek - 1) - ek) // self.stride + 1
            elif self.convolution_mode is ConvolutionMode.SAME:
                t = -(-t // self.stride)
            else:
                t = (t + 2 * self.padding - ek) // self.stride + 1
        return InputType.recurrent(self.n_out, t)


@register_layer
@dataclass
class Subsampling1DLayer(Layer):
    """Temporal pooling on [b, t, f] (reference: Subsampling1DLayer)."""

    pooling_type: PoolingType = PoolingType.MAX
    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    pnorm: int = 2

    def __post_init__(self):
        super().__post_init__()
        for f in ("kernel_size", "stride", "padding"):
            v = getattr(self, f)
            setattr(self, f, int(v[0] if isinstance(v, (tuple, list))
                                 else v))

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        k, s = self.kernel_size, self.stride
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            pad = [(0, 0), (self.padding, self.padding), (0, 0)]
        dims, strides = (1, k, 1), (1, s, 1)
        if self.pooling_type is PoolingType.MAX:
            z = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims,
                                      strides, pad)
        elif self.pooling_type is PoolingType.SUM:
            z = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                      pad)
        elif self.pooling_type is PoolingType.AVG:
            zs = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                       pad)
            n = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                      dims, strides, pad)
            z = zs / n
        else:
            p = float(self.pnorm)
            zs = jax.lax.reduce_window(jnp.abs(x) ** p, 0.0, jax.lax.add,
                                       dims, strides, pad)
            z = zs ** (1.0 / p)
        return z, state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeRecurrent), input_type
        t = input_type.timesteps
        if t > 0:
            if self.convolution_mode is ConvolutionMode.SAME:
                t = -(-t // self.stride)
            else:
                t = (t + 2 * self.padding - self.kernel_size) \
                    // self.stride + 1
        return InputType.recurrent(input_type.size, t)


# ---------------------------------------------------------------------------
# 3D family — operates on [b, d, h, w, c]
# ---------------------------------------------------------------------------
@register_layer
@dataclass
class Convolution3D(Layer):
    """Volumetric convolution (reference: Convolution3D, NCDHW; here
    NDHWC/DHWIO so XLA tiles the channel contraction onto the MXU)."""

    kernel_size: Tuple[int, int, int] = (3, 3, 3)
    stride: Tuple[int, int, int] = (1, 1, 1)
    padding: Tuple[int, int, int] = (0, 0, 0)
    dilation: Tuple[int, int, int] = (1, 1, 1)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE
    has_bias: bool = True

    @staticmethod
    def _builder_positional(*args) -> dict:
        if len(args) == 1:
            return {"kernel_size": _triple(args[0])}
        return {"kernel_size": tuple(int(a) for a in args)}

    def __post_init__(self):
        super().__post_init__()
        self.kernel_size = _triple(self.kernel_size)
        self.stride = _triple(self.stride)
        self.padding = _triple(self.padding)
        self.dilation = _triple(self.dilation)

    def _pad_cfg(self):
        if self.convolution_mode is ConvolutionMode.SAME:
            return "SAME"
        return [(p, p) for p in self.padding]

    def init_params(self, key, input_type, dtype=jnp.float32):
        kd, kh, kw = self.kernel_size
        vol = kd * kh * kw
        wi = self.weight_init or WeightInit.XAVIER
        p = {"W": wi.init(key, (kd, kh, kw, self.n_in, self.n_out),
                          vol * self.n_in, vol * self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        # shared fused-epilogue entry point (ops/conv_pallas.py) —
        # dense fallback whenever the structural gates demote the site
        from deeplearning4j_tpu.ops.conv_pallas import conv_forward
        z = conv_forward(
            x, params["W"], window_strides=self.stride,
            padding=self._pad_cfg(), rhs_dilation=self.dilation,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            bias=params["b"] if self.has_bias else None,
            activation=self.activation)
        return z, state

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeConvolutional3D) and \
                (override or not self.n_in):
            self.n_in = input_type.channels

    def _out_dim(self, size, i):
        k = (self.kernel_size[i] - 1) * self.dilation[i] + 1
        s = self.stride[i]
        if self.convolution_mode is ConvolutionMode.SAME:
            return -(-size // s)
        return (size + 2 * self.padding[i] - k) // s + 1

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional3D), input_type
        return InputType.convolutional_3d(
            self._out_dim(input_type.depth, 0),
            self._out_dim(input_type.height, 1),
            self._out_dim(input_type.width, 2), self.n_out)


@register_layer
@dataclass
class Deconvolution3D(Convolution3D):
    """Transposed volumetric convolution (reference: Deconvolution3D)."""

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            # conv_transpose explicit padding applies to the s-dilated
            # input; k-1-p per side yields the standard transposed-conv
            # output size (i-1)*s + k - 2p
            pad = [(k - 1 - p, k - 1 - p)
                   for k, p in zip(self.kernel_size, self.padding)]
        z = jax.lax.conv_transpose(
            x, params["W"], strides=self.stride, padding=pad,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def _out_dim(self, size, i):
        s = self.stride[i]
        if self.convolution_mode is ConvolutionMode.SAME:
            return size * s
        return (size - 1) * s + self.kernel_size[i] - 2 * self.padding[i]


@register_layer
@dataclass
class Subsampling3DLayer(Layer):
    """Volumetric pooling (reference: Subsampling3DLayer)."""

    pooling_type: PoolingType = PoolingType.MAX
    kernel_size: Tuple[int, int, int] = (2, 2, 2)
    stride: Tuple[int, int, int] = (2, 2, 2)
    padding: Tuple[int, int, int] = (0, 0, 0)
    convolution_mode: ConvolutionMode = ConvolutionMode.TRUNCATE

    def __post_init__(self):
        super().__post_init__()
        self.kernel_size = _triple(self.kernel_size)
        self.stride = _triple(self.stride)
        self.padding = _triple(self.padding)

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        kd, kh, kw = self.kernel_size
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            pad = [(0, 0)] + [(p, p) for p in self.padding] + [(0, 0)]
        dims = (1, kd, kh, kw, 1)
        strides = (1,) + self.stride + (1,)
        if self.pooling_type is PoolingType.MAX:
            z = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims,
                                      strides, pad)
        elif self.pooling_type is PoolingType.AVG:
            s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                      pad)
            n = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                      dims, strides, pad)
            z = s / n
        else:  # SUM
            z = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                      pad)
        return z, state

    def _out_dim(self, size, i):
        s = self.stride[i]
        if self.convolution_mode is ConvolutionMode.SAME:
            return -(-size // s)
        return (size + 2 * self.padding[i] - self.kernel_size[i]) // s + 1

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional3D), input_type
        return InputType.convolutional_3d(
            self._out_dim(input_type.depth, 0),
            self._out_dim(input_type.height, 1),
            self._out_dim(input_type.width, 2), input_type.channels)


@register_layer
@dataclass
class Cnn3DLossLayer(BaseOutputLayer):
    """Per-voxel loss head on [b, d, h, w, c] (reference: Cnn3DLossLayer)
    — no params, no flattening."""

    activation: Activation = Activation.IDENTITY

    def has_params(self) -> bool:
        return False

    def init_params(self, key, input_type, dtype=jnp.float32):
        return {}

    def set_n_in(self, input_type, override):
        pass

    def get_output_type(self, input_type):
        return input_type

    def wants_logits(self) -> bool:
        return False

    def forward(self, params, x, *, training, rng=None, state=None):
        return self.activation(x), state

    def forward_logits(self, params, x, *, training, rng=None, state=None):
        return x, state


@register_layer
@dataclass
class Deconvolution1D(Layer):
    """Temporal transposed convolution on [b, t, f] (reference: the
    Keras ``Conv1DTranspose`` import target; 1D sibling of
    Deconvolution2D/3D)."""

    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    convolution_mode: ConvolutionMode = ConvolutionMode.SAME
    has_bias: bool = True

    def __post_init__(self):
        super().__post_init__()
        for f in ("kernel_size", "stride", "padding"):
            v = getattr(self, f)
            setattr(self, f, int(v[0] if isinstance(v, (tuple, list))
                                 else v))

    def set_n_in(self, input_type, override):
        if isinstance(input_type, InputTypeRecurrent) and \
                (override or not self.n_in):
            self.n_in = input_type.size

    def init_params(self, key, input_type, dtype=jnp.float32):
        k = self.kernel_size
        wi = self.weight_init or WeightInit.XAVIER
        p = {"W": wi.init(key, (k, self.n_in, self.n_out),
                          k * self.n_in, k * self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            # conv_transpose explicit padding applies to the s-dilated
            # input; k-1-p per side yields (i-1)*s + k - 2p outputs
            k, p = self.kernel_size, self.padding
            pad = [(k - 1 - p, k - 1 - p)]
        z = jax.lax.conv_transpose(
            x, params["W"], strides=(self.stride,), padding=pad,
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeRecurrent), input_type
        t = input_type.timesteps
        if t > 0:
            if self.convolution_mode is ConvolutionMode.SAME:
                t = t * self.stride
            else:
                t = (t - 1) * self.stride + self.kernel_size \
                    - 2 * self.padding
        return InputType.recurrent(self.n_out, t)
