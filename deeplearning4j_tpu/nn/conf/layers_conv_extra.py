"""Additional convolution-family layers (SURVEY.md D4 long tail).

Reference parity: `conf.layers.SeparableConvolution2D` (Xception),
`conf.layers.Deconvolution2D` (transposed conv, UNet upsampling path),
`conf.layers.Upsampling2D` (nearest-neighbor). All NHWC / HWIO — the
XLA-native layouts.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (InputType,
                                               InputTypeConvolutional)
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                               ConvolutionMode, Layer,
                                               _pair, register_layer)
from deeplearning4j_tpu.nn.weights import WeightInit


@register_layer
@dataclass
class SeparableConvolution2D(ConvolutionLayer):
    """Depthwise-separable conv (reference:
    SeparableConvolution2D with depth_multiplier): depthwise
    [kh,kw,C,mult] then pointwise [1,1,C*mult,n_out]."""

    depth_multiplier: int = 1

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = self.kernel_size
        c_in = self.n_in
        m = self.depth_multiplier
        wi = self.weight_init or WeightInit.XAVIER
        k1, k2 = jax.random.split(key)
        p = {"dW": wi.init(k1, (kh, kw, c_in, m), kh * kw,
                           kh * kw * m, dtype),
             "pW": wi.init(k2, (1, 1, c_in * m, self.n_out),
                           c_in * m, self.n_out, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        c_in = x.shape[-1]
        kh, kw, _, m = params["dW"].shape
        # depthwise = grouped conv with feature_group_count = C
        dw = params["dW"].reshape(kh, kw, 1, c_in * m)
        z = jax.lax.conv_general_dilated(
            x, dw, window_strides=self.stride,
            padding=self._pad_cfg(), rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c_in)
        z = jax.lax.conv_general_dilated(
            z, params["pW"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state


@register_layer
@dataclass
class DepthwiseConvolution2D(ConvolutionLayer):
    """Depthwise conv without the pointwise stage (reference:
    conf.layers.DepthwiseConvolution2D): each input channel convolves
    with ``depth_multiplier`` filters; n_out = n_in * depth_multiplier."""

    depth_multiplier: int = 1

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = self.kernel_size
        m = self.depth_multiplier
        wi = self.weight_init or WeightInit.XAVIER
        p = {"dW": wi.init(key, (kh, kw, self.n_in, m), kh * kw,
                           kh * kw * m, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_in * m,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        c_in = x.shape[-1]
        kh, kw, _, m = params["dW"].shape
        dw = params["dW"].reshape(kh, kw, 1, c_in * m)
        z = jax.lax.conv_general_dilated(
            x, dw, window_strides=self.stride,
            padding=self._pad_cfg(), rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c_in)
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def get_output_type(self, input_type):
        out = super().get_output_type(input_type)
        return InputType.convolutional(
            out.height, out.width, self.n_in * self.depth_multiplier)


@register_layer
@dataclass
class Deconvolution2D(ConvolutionLayer):
    """Transposed convolution (reference: Deconvolution2D)."""

    def init_params(self, key, input_type, dtype=jnp.float32):
        kh, kw = self.kernel_size
        wi = self.weight_init or WeightInit.XAVIER
        k1, _ = jax.random.split(key)
        p = {"W": wi.init(k1, (kh, kw, self.n_in, self.n_out),
                          kh * kw * self.n_in, kh * kw * self.n_out,
                          dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def forward(self, params, x, *, training, rng=None, state=None):
        x = self._maybe_dropout(x, training, rng)
        if self.convolution_mode is ConvolutionMode.SAME:
            pad = "SAME"
        else:
            # conv_transpose explicit padding applies to the s-dilated
            # input; k-1-p per side yields the standard transposed-conv
            # output size (i-1)*s + k - 2p
            pad = [(k - 1 - p, k - 1 - p)
                   for k, p in zip(self.kernel_size, self.padding)]
        z = jax.lax.conv_transpose(
            x, params["W"], strides=self.stride, padding=pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            z = z + params["b"]
        return self.activation(z), state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional)
        h, w = input_type.height, input_type.width
        kh, kw = self.kernel_size
        sh, sw = self.stride
        if self.convolution_mode is ConvolutionMode.SAME:
            oh, ow = h * sh, w * sw
        else:
            ph, pw = self.padding
            oh = (h - 1) * sh + kh - 2 * ph
            ow = (w - 1) * sw + kw - 2 * pw
        return InputType.convolutional(oh, ow, self.n_out)


@register_layer
@dataclass
class Upsampling2D(Layer):
    """Nearest-neighbor upsampling (reference: Upsampling2D)."""

    size: Tuple[int, int] = (2, 2)

    @staticmethod
    def _builder_positional(*args) -> dict:
        return {"size": _pair(args[0])} if args else {}

    def __post_init__(self):
        super().__post_init__()
        self.size = _pair(self.size)

    def has_params(self) -> bool:
        return False

    def set_n_in(self, input_type, override):
        pass

    def forward(self, params, x, *, training, rng=None, state=None):
        sh, sw = self.size
        z = jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
        return z, state

    def get_output_type(self, input_type):
        assert isinstance(input_type, InputTypeConvolutional)
        return InputType.convolutional(input_type.height * self.size[0],
                                       input_type.width * self.size[1],
                                       input_type.channels)
