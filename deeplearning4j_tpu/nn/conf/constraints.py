"""Weight constraints: post-update parameter projections.

Reference parity: ``org.deeplearning4j.nn.conf.constraint`` —
``MaxNormConstraint``, ``MinMaxNormConstraint``, ``NonNegativeConstraint``,
``UnitNormConstraint`` (SURVEY.md D1). Semantics follow the reference:
constraints are applied to the parameters AFTER each updater step (a
projection, not a gradient penalty), inside the jitted train step so the
projection fuses with the update.

Norms are computed per output unit: over all axes EXCEPT the last, since
every weight tensor in this framework stores the output axis last
(dense ``[n_in, n_out]``, conv ``[kh, kw, c_in, c_out]`` — see
``nn/conf/layers.py``). An explicit ``dims`` overrides.
"""
from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-7


class LayerConstraint:
    """Base projection. Reference: o.d.nn.api.layers.LayerConstraint."""

    def apply(self, p):
        raise NotImplementedError

    def _norm(self, p, dims):
        if dims is None:
            dims = tuple(range(p.ndim - 1)) if p.ndim > 1 else (0,)
        return jnp.sqrt(jnp.sum(
            jnp.square(p.astype(jnp.float32)), axis=dims, keepdims=True))

    # -- serde ----------------------------------------------------------
    def to_map(self) -> dict:
        d = {"@class": type(self).__name__}
        d.update({k: v for k, v in self.__dict__.items()})
        return d

    @staticmethod
    def from_map(d: dict) -> "LayerConstraint":
        d = dict(d)
        cls = CONSTRAINT_REGISTRY[d.pop("@class")]
        if "dims" in d and isinstance(d["dims"], list):
            d["dims"] = tuple(d["dims"])
        return cls(**d)

    def __eq__(self, other):
        return type(self) is type(other) and \
            self.__dict__ == other.__dict__

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.__dict__.items())
        return f"{type(self).__name__}({args})"


class MaxNormConstraint(LayerConstraint):
    """Rescale any unit whose norm exceeds ``max_norm`` down to it.
    Reference: o.d.nn.conf.constraint.MaxNormConstraint."""

    def __init__(self, max_norm: float = 2.0, dims=None):
        self.max_norm = float(max_norm)
        self.dims = tuple(dims) if dims is not None else None

    def apply(self, p):
        n = self._norm(p, self.dims)
        scale = jnp.clip(n, None, self.max_norm) / (n + _EPS)
        return (p * scale.astype(p.dtype)).astype(p.dtype)


class MinMaxNormConstraint(LayerConstraint):
    """Constrain unit norms into ``[min_norm, max_norm]``, moving a
    fraction ``rate`` of the way there each step. Reference:
    o.d.nn.conf.constraint.MinMaxNormConstraint."""

    def __init__(self, min_norm: float = 0.0, max_norm: float = 2.0,
                 rate: float = 1.0, dims=None):
        self.min_norm = float(min_norm)
        self.max_norm = float(max_norm)
        self.rate = float(rate)
        self.dims = tuple(dims) if dims is not None else None

    def apply(self, p):
        n = self._norm(p, self.dims)
        target = jnp.clip(n, self.min_norm, self.max_norm)
        scale = self.rate * target / (n + _EPS) + (1.0 - self.rate)
        return (p * scale.astype(p.dtype)).astype(p.dtype)


class UnitNormConstraint(LayerConstraint):
    """Project every unit onto the unit sphere. Reference:
    o.d.nn.conf.constraint.UnitNormConstraint."""

    def __init__(self, dims=None):
        self.dims = tuple(dims) if dims is not None else None

    def apply(self, p):
        n = self._norm(p, self.dims)
        return (p / (n + _EPS).astype(p.dtype)).astype(p.dtype)


class NonNegativeConstraint(LayerConstraint):
    """Clamp parameters at zero. Reference:
    o.d.nn.conf.constraint.NonNegativeConstraint."""

    def apply(self, p):
        return jnp.maximum(p, jnp.zeros((), p.dtype))


CONSTRAINT_REGISTRY = {c.__name__: c for c in (
    MaxNormConstraint, MinMaxNormConstraint, UnitNormConstraint,
    NonNegativeConstraint)}


# ---------------------------------------------------------------------------
def _is_weight_param(layer, name: str, p) -> bool:
    # output-axis-last weight matrices/kernels; recurrent RW included,
    # the way the reference's constrainWeights covers all weight params
    return name in ("W", "RW") or p.ndim >= 2


def _is_bias_param(name: str) -> bool:
    return name == "b"


def apply_constraints(layer, params: dict) -> dict:
    """Project a layer's freshly-updated param dict through its
    configured constraints (no-op when the layer has none). Runs inside
    the jitted train step, after the updater (reference semantics:
    ``BaseConstraint.applyConstraint`` post-update)."""
    cw = getattr(layer, "constrain_weights", None)
    cb = getattr(layer, "constrain_bias", None)
    ca = getattr(layer, "constrain_all", None)
    cp = getattr(layer, "constrain_params", None)
    if not (cw or cb or ca or cp):
        return params
    out = {}
    for name, p in params.items():
        if isinstance(p, dict):
            # wrapper layers (Bidirectional fwd/bwd, TimeDistributed)
            # nest sub-param tables; constrain at the leaves
            out[name] = apply_constraints(layer, p)
            continue
        if cw and _is_weight_param(layer, name, p):
            for c in cw:
                p = c.apply(p)
        if cb and _is_bias_param(name):
            for c in cb:
                p = c.apply(p)
        if ca:
            for c in ca:
                p = c.apply(p)
        if cp:
            # exact param-name scoping (reference: BaseConstraint
            # carries a param-name set; Keras constraints are per-param
            # — kernel vs recurrent vs depthwise vs pointwise)
            for c in cp.get(name, ()):
                p = c.apply(p)
        out[name] = p
    return out


def constraints_to_map(v):
    """Serde helper for a list-of-constraints field (JSON round-trip)."""
    if v is None:
        return None
    return [c.to_map() for c in v]


def constraints_from_map(v):
    if v is None:
        return None
    return [LayerConstraint.from_map(m) for m in v]
