"""Specialised output layers: center loss + one-class NN.

Reference parity: ``org.deeplearning4j.nn.conf.layers.
CenterLossOutputLayer`` (softmax + intra-class compactness penalty,
face-embedding style) and ``conf.ocnn.OCNNOutputLayer`` (one-class NN
anomaly scoring, Chalapathy et al.'s OC-NN objective).

Functional-design note: these losses need more than the class
probabilities (center loss needs the penultimate features; OC-NN needs
its own params' norms and the r quantile). The output-layer protocol
stays pure by packing those extras into the logits tensor inside
``forward_logits`` and unpacking them in ``compute_loss`` — everything
remains one fused XLA program, no side state.

Divergence (documented): the reference updates class centers with a
dedicated alpha-EMA rule outside the updater; here centers are ordinary
parameters — the gradient of the center term, lambda*(c_y - f), descended
with the layer's updater reproduces the same EMA with
alpha = lr * lambda.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (BaseOutputLayer,
                                               register_layer)
from deeplearning4j_tpu.nn.weights import WeightInit


@register_layer
@dataclass
class CenterLossOutputLayer(BaseOutputLayer):
    """Softmax head + lambda/2 * ||f - c_y||^2 compactness penalty
    (reference: CenterLossOutputLayer; params include one center per
    class over the input features)."""

    alpha: float = 0.05        # kept for config parity (see module note)
    lambda_: float = 2e-4

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        k1, _ = jax.random.split(key)
        p = {"W": wi.init(k1, (self.n_in, self.n_out), self.n_in,
                          self.n_out, dtype),
             "centers": jnp.zeros((self.n_out, self.n_in), dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return p

    def wants_logits(self) -> bool:
        return True

    def forward_logits(self, params, x, *, training, rng=None,
                       state=None):
        x = self._maybe_dropout(x, training, rng)
        z = x @ params["W"]
        if self.has_bias:
            z = z + params["b"]
        # pack features + per-example distance-to-center machinery:
        # [logits | features | flattened per-class centers gathered later]
        # centers are gathered in compute_loss from the label one-hots,
        # so only [logits | f | f @ centers^T | row-norms] are needed:
        # we pack [logits, features, features @ centers.T, ||c||^2 row]
        # to keep compute_loss label-side only.
        fc = x @ params["centers"].T                     # [b, n_out]
        cn = jnp.sum(params["centers"] ** 2, axis=-1)    # [n_out]
        cn = jnp.broadcast_to(cn[None, :], fc.shape)
        fn = jnp.sum(x ** 2, axis=-1, keepdims=True)     # [b, 1]
        return jnp.concatenate([z, fc, cn, fn], axis=-1), state

    def compute_loss(self, labels, preds_or_logits, *, from_logits,
                     mask=None, average=True):
        if not from_logits or \
                preds_or_logits.shape[-1] == self.n_out:
            return super().compute_loss(labels, preds_or_logits,
                                        from_logits=from_logits,
                                        mask=mask, average=average)
        n = self.n_out
        z = preds_or_logits[..., :n]
        fc = preds_or_logits[..., n:2 * n]
        cn = preds_or_logits[..., 2 * n:3 * n]
        fn = preds_or_logits[..., 3 * n]
        base = super().compute_loss(labels, z, from_logits=True,
                                    mask=mask, average=average)
        # ||f - c_y||^2 = ||f||^2 - 2 f·c_y + ||c_y||^2 ; y one-hot
        dist = fn - 2.0 * jnp.sum(fc * labels, -1) + \
            jnp.sum(cn * labels, -1)
        if mask is not None:
            m = mask.reshape(dist.shape)
            dist = dist * m
            center = jnp.sum(dist) / jnp.maximum(jnp.sum(m), 1.0)
        else:
            center = jnp.mean(dist)
        return base + 0.5 * self.lambda_ * center

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


@register_layer
@dataclass
class OCNNOutputLayer(BaseOutputLayer):
    """One-class NN output layer (reference: conf.ocnn.OCNNOutputLayer):
    score(x) = w · act(x V); objective
    0.5||V||^2 + 0.5||w||^2 + (1/nu) mean(relu(r - score)) - r,
    with r a learned nu-quantile. Unsupervised: labels are ignored.
    Inference output is the decision value score - r ([b, 1]; >0 means
    inlier)."""

    hidden_size: int = 16
    nu: float = 0.04
    initial_r_value: float = 0.1
    activation: Activation = Activation.RELU
    loss_function: LossFunction = LossFunction.MSE   # unused; parity slot

    def __post_init__(self):
        super().__post_init__()
        self.n_out = 1

    def init_params(self, key, input_type, dtype=jnp.float32):
        wi = self.weight_init or WeightInit.XAVIER
        k1, k2 = jax.random.split(key)
        return {"V": wi.init(k1, (self.n_in, self.hidden_size), self.n_in,
                             self.hidden_size, dtype),
                "w": wi.init(k2, (self.hidden_size,), self.hidden_size,
                             1, dtype),
                "r": jnp.asarray(self.initial_r_value, dtype)}

    def _score(self, params, x):
        return self.activation(x @ params["V"]) @ params["w"]

    def wants_logits(self) -> bool:
        return True

    def forward(self, params, x, *, training, rng=None, state=None):
        return (self._score(params, x) - params["r"])[..., None], state

    def forward_logits(self, params, x, *, training, rng=None,
                       state=None):
        s = self._score(params, x)[..., None]                  # [b, 1]
        reg = 0.5 * (jnp.sum(params["V"] ** 2) +
                     jnp.sum(params["w"] ** 2))
        r = jnp.broadcast_to(params["r"], s.shape)
        reg = jnp.broadcast_to(reg, s.shape)
        return jnp.concatenate([s, r, reg], axis=-1), state

    def compute_loss(self, labels, preds_or_logits, *, from_logits,
                     mask=None, average=True):
        s = preds_or_logits[..., 0]
        r = preds_or_logits[..., 1]
        reg = preds_or_logits[..., 2]
        hinge = jnp.maximum(0.0, r - s)
        return jnp.mean(reg) + jnp.mean(hinge) / self.nu - jnp.mean(r)

    def get_output_type(self, input_type):
        return InputType.feed_forward(1)
