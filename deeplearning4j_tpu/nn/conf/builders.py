"""Network configuration: fluent builders + JSON round-trip.

Reference parity: ``org.deeplearning4j.nn.conf.NeuralNetConfiguration``
(Builder -> ListBuilder -> ``MultiLayerConfiguration``), SURVEY.md D1. The
JSON round-trip is a compatibility contract in the reference (old JSON must
load); the same guarantee holds here via the layer/preprocessor/updater
``to_map``/``from_map`` registries.
"""
from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning.updaters import IUpdater, Sgd
from deeplearning4j_tpu.nn.conf.inputs import (
    InputType, InputTypeConvolutional, InputTypeConvolutionalFlat,
    InputTypeFeedForward, InputTypeRecurrent)
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor, FeedForwardToCnnPreProcessor,
    InputPreProcessor, RnnToFeedForwardPreProcessor)
from deeplearning4j_tpu.nn.weights import WeightInit


class GradientNormalization(enum.Enum):
    """Reference: org.deeplearning4j.nn.conf.GradientNormalization."""
    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renorm_l2_layer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "renorm_l2_param"
    CLIP_ELEMENT_WISE_ABSOLUTE_VALUE = "clip_elem"
    CLIP_L2_PER_LAYER = "clip_l2_layer"
    CLIP_L2_PER_PARAM_TYPE = "clip_l2_param"


class BackpropType(enum.Enum):
    STANDARD = "standard"
    TRUNCATED_BPTT = "tbptt"


class WorkspaceMode(enum.Enum):
    """Kept for API parity; a no-op on TPU — XLA owns memory (SURVEY.md
    section 7: donation replaces workspaces)."""
    ENABLED = "enabled"
    NONE = "none"


@dataclass
class MultiLayerConfiguration:
    layers: List[Layer] = field(default_factory=list)
    input_preprocessors: Dict[int, InputPreProcessor] = \
        field(default_factory=dict)
    seed: int = 12345
    updater: IUpdater = field(default_factory=lambda: Sgd(1e-3))
    weight_init: WeightInit = WeightInit.XAVIER
    l1: float = 0.0
    l2: float = 0.0
    gradient_normalization: GradientNormalization = \
        GradientNormalization.NONE
    gradient_normalization_threshold: float = 1.0
    backprop_type: BackpropType = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    dtype: str = "float32"
    compute_dtype: Optional[str] = None   # None = same as dtype
    #: remat the training forward in this many jax.checkpoint'd
    #: segments of the layer stack (sqrt(N) checkpointing; 0 = off)
    remat_segments: int = 0
    input_type: Optional[InputType] = None

    # -- JSON ------------------------------------------------------------
    def to_json(self) -> str:
        d = {
            "layers": [l.to_map() for l in self.layers],
            "input_preprocessors": {str(k): v.to_map() for k, v in
                                    self.input_preprocessors.items()},
            "seed": self.seed,
            "updater": self.updater.to_map(),
            "weight_init": self.weight_init.name,
            "l1": self.l1,
            "l2": self.l2,
            "gradient_normalization": self.gradient_normalization.name,
            "gradient_normalization_threshold":
                self.gradient_normalization_threshold,
            "backprop_type": self.backprop_type.name,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "remat_segments": self.remat_segments,
            "input_type": self.input_type.to_map() if self.input_type
                          else None,
        }
        return json.dumps(d, indent=2)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        return MultiLayerConfiguration(
            layers=[Layer.from_map(m) for m in d["layers"]],
            input_preprocessors={int(k): InputPreProcessor.from_map(v)
                                 for k, v in
                                 d.get("input_preprocessors", {}).items()},
            seed=d.get("seed", 12345),
            updater=IUpdater.from_map(d["updater"]),
            weight_init=WeightInit[d.get("weight_init", "XAVIER")],
            l1=d.get("l1", 0.0),
            l2=d.get("l2", 0.0),
            gradient_normalization=GradientNormalization[
                d.get("gradient_normalization", "NONE")],
            gradient_normalization_threshold=d.get(
                "gradient_normalization_threshold", 1.0),
            backprop_type=BackpropType[d.get("backprop_type", "STANDARD")],
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            remat_segments=d.get("remat_segments", 0),
            input_type=InputType.from_map(d["input_type"])
                       if d.get("input_type") else None,
        )

    # -- shape inference (reference: setInputType walk) ------------------
    def resolve_shapes(self):
        """Infer n_in per layer and insert preprocessors, given input_type."""
        if self.input_type is None:
            return
        cur = self.input_type
        for i, layer in enumerate(self.layers):
            if i in self.input_preprocessors:
                cur = self.input_preprocessors[i].get_output_type(cur)
            else:
                pre = _default_preprocessor(cur, layer)
                if pre is not None:
                    self.input_preprocessors[i] = pre
                    cur = pre.get_output_type(cur)
            layer.set_n_in(cur, override=False)
            cur = layer.get_output_type(cur)

    def get_layer(self, i: int) -> Layer:
        return self.layers[i]


def _wants_cnn_input(layer: Layer) -> bool:
    from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                                   ConvolutionLayer,
                                                   SubsamplingLayer)
    return isinstance(layer, (ConvolutionLayer, SubsamplingLayer))


def _wants_ff_input(layer: Layer) -> bool:
    from deeplearning4j_tpu.nn.conf.layers import (BaseOutputLayer,
                                                   CnnLossLayer,
                                                   DenseLayer,
                                                   RnnOutputLayer)
    from deeplearning4j_tpu.nn.conf.layers_objdetect import \
        Yolo2OutputLayer
    from deeplearning4j_tpu.nn.conf.layers_vae import (
        AutoEncoder, VariationalAutoencoder)
    if isinstance(layer, (AutoEncoder, VariationalAutoencoder)):
        return True
    return isinstance(layer, DenseLayer) and not isinstance(
        layer, (RnnOutputLayer, CnnLossLayer, Yolo2OutputLayer))


def _default_preprocessor(cur: InputType, layer: Layer):
    """Insert the standard shape adapters (reference:
    InputType.getPreProcessorForInputType semantics)."""
    if isinstance(cur, InputTypeConvolutionalFlat) and _wants_cnn_input(
            layer):
        return FeedForwardToCnnPreProcessor(cur.height, cur.width,
                                            cur.channels)
    if isinstance(cur, InputTypeConvolutional) and _wants_ff_input(layer):
        return CnnToFeedForwardPreProcessor(cur.height, cur.width,
                                            cur.channels)
    from deeplearning4j_tpu.nn.conf.inputs import InputTypeConvolutional3D
    if isinstance(cur, InputTypeConvolutional3D) and \
            _wants_ff_input(layer):
        from deeplearning4j_tpu.nn.conf.preprocessors import \
            Cnn3DToFeedForwardPreProcessor
        return Cnn3DToFeedForwardPreProcessor(cur.depth, cur.height,
                                              cur.width, cur.channels)
    if isinstance(cur, InputTypeConvolutionalFlat) and _wants_ff_input(
            layer):
        return None  # already flat
    return None


def apply_layer_defaults(layer: Layer, base: "NeuralNetConfiguration.Builder"):
    """Flow global builder defaults down to a layer that didn't override
    them (shared by ListBuilder and GraphBuilder)."""
    if layer.updater is None:
        layer.updater = base._updater
    if layer.weight_init is None:
        layer.weight_init = base._weight_init
    if layer.l1 is None:
        layer.l1 = base._l1
    if layer.l2 is None:
        layer.l2 = base._l2
    if layer.dropout is None and base._dropout is not None:
        layer.dropout = base._dropout
    if layer.constrain_weights is None and base._constrain_weights:
        layer.constrain_weights = list(base._constrain_weights)
    if layer.constrain_bias is None and base._constrain_bias:
        layer.constrain_bias = list(base._constrain_bias)
    if layer.constrain_all is None and base._constrain_all:
        layer.constrain_all = list(base._constrain_all)


class ListBuilder:
    """Reference: NeuralNetConfiguration.ListBuilder."""

    def __init__(self, base: "NeuralNetConfiguration.Builder"):
        self._base = base
        self._layers: List[Layer] = []
        self._preprocessors: Dict[int, InputPreProcessor] = {}
        self._input_type: Optional[InputType] = None
        self._backprop_type = BackpropType.STANDARD
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def layer(self, *args) -> "ListBuilder":
        """layer(l) or layer(index, l)."""
        if len(args) == 2:
            idx, l = args
            while len(self._layers) <= idx:
                self._layers.append(None)  # type: ignore[arg-type]
            self._layers[idx] = l
        else:
            self._layers.append(args[0])
        return self

    def input_pre_processor(self, idx: int,
                            p: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[idx] = p
        return self

    def set_input_type(self, t: InputType) -> "ListBuilder":
        self._input_type = t
        return self

    def backprop_type(self, t: BackpropType) -> "ListBuilder":
        self._backprop_type = t
        return self

    def t_bptt_length(self, fwd: int, back: int = None) -> "ListBuilder":
        self._tbptt_fwd = fwd
        self._tbptt_back = back if back is not None else fwd
        return self

    def build(self) -> MultiLayerConfiguration:
        b = self._base
        conf = MultiLayerConfiguration(
            layers=list(self._layers),
            input_preprocessors=dict(self._preprocessors),
            seed=b._seed,
            updater=b._updater,
            weight_init=b._weight_init,
            l1=b._l1, l2=b._l2,
            gradient_normalization=b._grad_norm,
            gradient_normalization_threshold=b._grad_norm_threshold,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            dtype=b._dtype,
            compute_dtype=b._compute_dtype,
            remat_segments=b._remat_segments,
            input_type=self._input_type,
        )
        for l in conf.layers:
            apply_layer_defaults(l, b)
        conf.resolve_shapes()
        return conf


class NeuralNetConfiguration:
    """Entry point: ``NeuralNetConfiguration.Builder()``."""

    class Builder:
        def __init__(self):
            self._seed = 12345
            self._updater: IUpdater = Sgd(1e-3)
            self._weight_init = WeightInit.XAVIER
            self._l1 = 0.0
            self._l2 = 0.0
            self._dropout: Optional[float] = None
            self._activation: Optional[Activation] = None
            self._grad_norm = GradientNormalization.NONE
            self._grad_norm_threshold = 1.0
            self._dtype = "float32"
            self._compute_dtype: Optional[str] = None
            self._remat_segments = 0
            self._constrain_weights: list = []
            self._constrain_bias: list = []
            self._constrain_all: list = []

        def seed(self, s: int) -> "NeuralNetConfiguration.Builder":
            self._seed = int(s)
            return self

        def updater(self, u: IUpdater) -> "NeuralNetConfiguration.Builder":
            self._updater = u
            return self

        def weight_init(self, w: WeightInit
                        ) -> "NeuralNetConfiguration.Builder":
            self._weight_init = w
            return self

        def l1(self, v: float) -> "NeuralNetConfiguration.Builder":
            self._l1 = float(v)
            return self

        def l2(self, v: float) -> "NeuralNetConfiguration.Builder":
            self._l2 = float(v)
            return self

        def dropout(self, p: float) -> "NeuralNetConfiguration.Builder":
            self._dropout = float(p)
            return self

        def activation(self, a: Activation
                       ) -> "NeuralNetConfiguration.Builder":
            self._activation = a
            return self

        def constrain_weights(self, *constraints
                              ) -> "NeuralNetConfiguration.Builder":
            """Post-update projections on every layer's weight params
            (reference: Builder.constrainWeights)."""
            self._constrain_weights = list(constraints)
            return self

        def constrain_bias(self, *constraints
                           ) -> "NeuralNetConfiguration.Builder":
            self._constrain_bias = list(constraints)
            return self

        def constrain_all_parameters(
                self, *constraints) -> "NeuralNetConfiguration.Builder":
            """Reference: Builder.constrainAllParameters."""
            self._constrain_all = list(constraints)
            return self

        def gradient_normalization(
                self, g: GradientNormalization
        ) -> "NeuralNetConfiguration.Builder":
            self._grad_norm = g
            return self

        def gradient_normalization_threshold(
                self, t: float) -> "NeuralNetConfiguration.Builder":
            self._grad_norm_threshold = float(t)
            return self

        def data_type(self, dtype: str
                      ) -> "NeuralNetConfiguration.Builder":
            self._dtype = dtype
            return self

        def compute_data_type(self, dtype: Optional[str]
                              ) -> "NeuralNetConfiguration.Builder":
            """Mixed precision: run forward/backward math in this
            dtype (canonically 'bfloat16' on TPU — MXU-native) while
            parameters/optimizer state stay in ``data_type``."""
            self._compute_dtype = dtype
            return self

        def remat_segments(self, n: int
                           ) -> "NeuralNetConfiguration.Builder":
            """Rematerialize training activations in ``n``
            ``jax.checkpoint``'d segments of the stack — only
            segment-boundary activations are stored for backward
            (sqrt(N) checkpointing trades recompute FLOPs for HBM
            activation traffic; 0 = store everything)."""
            self._remat_segments = int(n)
            return self

        def list(self) -> ListBuilder:  # noqa: A003
            return ListBuilder(self)

        def graph_builder(self):
            from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
            return GraphBuilder(self)
