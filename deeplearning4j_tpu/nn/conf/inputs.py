"""Input types for layer shape inference.

Reference parity: ``org.deeplearning4j.nn.conf.inputs.InputType`` (SURVEY.md
D1) — the shape-inference currency flowing through ``setInputType``:
each layer maps an input type to an output type, and mismatches insert
preprocessors.

TPU-first divergence (documented): convolutional activations are **NHWC**
(XLA:TPU's preferred layout; the MXU tiles the trailing channel dim),
where the reference is NCHW. ``InputType.convolutional(h, w, c)`` keeps the
reference's argument order; only the in-memory layout differs.
"""
from __future__ import annotations

from dataclasses import dataclass


class InputType:
    @staticmethod
    def feed_forward(size: int) -> "InputTypeFeedForward":
        return InputTypeFeedForward(int(size))

    @staticmethod
    def recurrent(size: int, timesteps: int = -1) -> "InputTypeRecurrent":
        return InputTypeRecurrent(int(size), int(timesteps))

    @staticmethod
    def convolutional(height: int, width: int,
                      channels: int) -> "InputTypeConvolutional":
        return InputTypeConvolutional(int(height), int(width), int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int,
                           channels: int) -> "InputTypeConvolutionalFlat":
        return InputTypeConvolutionalFlat(int(height), int(width),
                                          int(channels))

    @staticmethod
    def convolutional_3d(depth: int, height: int, width: int,
                         channels: int) -> "InputTypeConvolutional3D":
        return InputTypeConvolutional3D(int(depth), int(height), int(width),
                                        int(channels))

    # -- serde ----------------------------------------------------------
    def to_map(self) -> dict:
        d = {"@class": type(self).__name__}
        d.update(self.__dict__)
        return d

    @staticmethod
    def from_map(d: dict) -> "InputType":
        d = dict(d)
        cls = _REGISTRY[d.pop("@class")]
        return cls(**d)

    def arrays_per_example(self) -> int:
        raise NotImplementedError


@dataclass
class InputTypeFeedForward(InputType):
    size: int

    def arrays_per_example(self) -> int:
        return self.size

    def shape(self, batch: int = -1):
        return (batch, self.size)


@dataclass
class InputTypeRecurrent(InputType):
    size: int
    timesteps: int = -1

    def arrays_per_example(self) -> int:
        return self.size * max(self.timesteps, 1)

    def shape(self, batch: int = -1):
        return (batch, self.timesteps, self.size)


@dataclass
class InputTypeConvolutional(InputType):
    height: int
    width: int
    channels: int

    def arrays_per_example(self) -> int:
        return self.height * self.width * self.channels

    def shape(self, batch: int = -1):
        # NHWC (TPU-first; see module docstring)
        return (batch, self.height, self.width, self.channels)


@dataclass
class InputTypeConvolutionalFlat(InputType):
    height: int
    width: int
    channels: int

    def arrays_per_example(self) -> int:
        return self.height * self.width * self.channels

    def get_flattened_size(self) -> int:
        return self.arrays_per_example()

    def shape(self, batch: int = -1):
        return (batch, self.arrays_per_example())


@dataclass
class InputTypeConvolutional3D(InputType):
    """Volumetric input, NDHWC (reference: InputType.InputTypeConvolutional3D,
    which is NCDHW; the TPU layout keeps channels trailing for the MXU)."""

    depth: int
    height: int
    width: int
    channels: int

    def arrays_per_example(self) -> int:
        return self.depth * self.height * self.width * self.channels

    def shape(self, batch: int = -1):
        return (batch, self.depth, self.height, self.width, self.channels)


_REGISTRY = {c.__name__: c for c in
             (InputTypeFeedForward, InputTypeRecurrent,
              InputTypeConvolutional, InputTypeConvolutionalFlat,
              InputTypeConvolutional3D)}
