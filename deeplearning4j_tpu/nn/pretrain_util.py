"""Shared data plumbing for layerwise pretraining — one place for the
one-shot-iterable materialization and the features-only batch walk used
by both MultiLayerNetwork.pretrain_layer and
ComputationGraph.pretrain_vertex (they must not drift: reference
``pretrain(DataSetIterator)`` accepts the same inputs on both)."""
from __future__ import annotations


def materialize_once(data):
    """Listify a non-resettable iterable (e.g. a generator) so every
    layer/epoch of a greedy pretrain sees the full data; pass through
    DataSets, arrays, lists, and resettable iterators unchanged."""
    if not (hasattr(data, "features") or hasattr(data, "reset") or
            hasattr(data, "shape") or isinstance(data, (list, tuple))):
        return list(data)
    return data


def feature_batches(data, as_list: bool = False):
    """Yield feature batches from a DataSet / bare array / iterator /
    list. ``as_list=True`` wraps singles in a list (the
    ComputationGraph multi-input convention)."""
    def wrap(f):
        if as_list:
            return f if isinstance(f, list) else [f]
        return f

    if hasattr(data, "features"):               # DataSet
        yield wrap(data.features)
    elif hasattr(data, "shape"):                # bare array
        yield wrap(data)
    else:                                       # iterator / list
        if hasattr(data, "reset"):
            data.reset()
        for ds in data:
            yield wrap(ds.features if hasattr(ds, "features") else ds)
