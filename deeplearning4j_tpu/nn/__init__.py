from deeplearning4j_tpu.nn.conf import (  # noqa: F401
    NeuralNetConfiguration, MultiLayerConfiguration, InputType)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401
