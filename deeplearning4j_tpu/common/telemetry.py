"""Unified telemetry spine: metrics registry + one-timeline tracing.

The reference stack's training-health story (StatsListener,
PerformanceListener, the Vert.x UI — SURVEY.md D7/D17) observes the
train loop only.  The perf-critical subsystems grown since (device
prefetcher, compile cache, batched serving) were invisible outside
one-off benchmarks; this module is the process-wide instrument panel
they all report into — the TVM "measure, then tune" discipline
(PAPERS.md 1802.04799) applied to the runtime itself.

Three pieces:

- :class:`MetricsRegistry` — a thread-safe, process-wide registry of
  labeled :class:`Counter`/:class:`Gauge`/:class:`Histogram` metrics.
  Every hot path (prefetch feeder, fit funnels, serving queue,
  checkpoint writer) records into it; it renders as a Prometheus
  text-format page (``UIServer`` serves it at ``/metrics``), folds into
  ``ui.stats`` reports via :class:`MetricsReporterListener`, and lands
  in ``bench.py`` JSON via :meth:`MetricsRegistry.summary`.
- :func:`span` — a context manager recording wall-clock spans into a
  shared chrome-trace event buffer, in the SAME format
  ``ui.profiling.ProfilingListener`` emits, so host spans, feeder-
  thread spans, and ``jax.profiler`` TPU traces load into one
  chrome://tracing / Perfetto timeline.  :func:`export_chrome_trace`
  writes the buffer; :func:`merge_chrome_traces` folds several trace
  files (ours or jax.profiler's) into one.
- ``DL4J_TPU_TELEMETRY`` gate (default on) — when off, every record
  call is a single attribute check and spans don't allocate
  (``benchmarks/bench_telemetry.py`` is the overhead microbench).

Metric names follow Prometheus conventions (``dl4j_`` namespace,
``_seconds``/``_bytes``/``_total`` unit suffixes); the catalog lives in
README "Observability" and ``scripts/check_telemetry_catalog.py`` keeps
code and catalog honest.
"""
from __future__ import annotations

import gzip
import json
import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.optimize.listeners import TrainingListener

log = logging.getLogger("deeplearning4j_tpu")

#: default latency buckets (seconds) — microseconds (counter overhead,
#: queue pops) up to tens of seconds (BERT-scale compiles, checkpoints)
DEFAULT_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
                   1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

#: buckets for 0..1 ratios (batch occupancy)
RATIO_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: dict) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _render_labels(key: _LabelKey, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    """Base: name, help text, per-registry enabled flag shared by
    reference (the registry flips ``_state['on']`` for all metrics at
    once — record calls check one dict slot, no lock)."""

    kind = "untyped"

    def __init__(self, name: str, help: str, state: dict):
        self.name = name
        self.help = help
        self._state = state        # {'on': bool}, shared with registry
        self._lock = threading.Lock()
        self._series: Dict[_LabelKey, object] = {}


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if not self._state["on"]:
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def bind(self, **labels) -> "_BoundCounter":
        """Pre-resolve a label set for per-step hot paths (see
        Histogram.bind)."""
        return _BoundCounter(self, _label_key(labels))

    def value(self, **labels) -> float:
        return self._series.get(_label_key(labels), 0)

    def _render(self) -> List[str]:
        return [f"{self.name}{_render_labels(k)} {v:g}"
                for k, v in sorted(self._series.items())]

    def _snapshot(self):
        return {";".join(f"{k}={v}" for k, v in key) or "": val
                for key, val in self._series.items()}


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not self._state["on"]:
            return
        with self._lock:
            self._series[_label_key(labels)] = value

    def inc(self, amount: float = 1, **labels) -> None:
        if not self._state["on"]:
            return
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + amount

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        return self._series.get(_label_key(labels), 0)

    def _render(self) -> List[str]:
        return [f"{self.name}{_render_labels(k)} {v:g}"
                for k, v in sorted(self._series.items())]

    def _snapshot(self):
        return {";".join(f"{k}={v}" for k, v in key) or "": val
                for key, val in self._series.items()}


class _HistSeries:
    __slots__ = ("counts", "sum", "count", "exemplar")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)     # last slot = +Inf
        self.sum = 0.0
        self.count = 0
        #: most recent (value, {label: value}, unix_ts) exemplar — a
        #: concrete request (trace id) behind the aggregate, OpenMetrics
        #: style, so a bad bucket links to a timeline
        self.exemplar: Optional[Tuple[float, dict, float]] = None


class Histogram(_Metric):
    """Fixed-bucket histogram (Prometheus classic): per-label-set
    bucket counts + sum + count; rendering is cumulative with the
    ``le`` label, as scrapers expect."""

    kind = "histogram"

    def __init__(self, name, help, state,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, state)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, **labels) -> None:
        if not self._state["on"]:
            return
        self._observe_key(_label_key(labels), value)

    def observe_with_exemplar(self, value: float, exemplar: dict,
                              **labels) -> None:
        """Observe ``value`` and attach ``exemplar`` (e.g.
        ``{"trace_id": ...}``) to the series — the latest exemplar is
        kept per label set and rendered OpenMetrics-style on the +Inf
        bucket, so a latency spike on a dashboard links to the concrete
        request timeline that produced it."""
        if not self._state["on"]:
            return
        self._observe_key(_label_key(labels), value,
                          exemplar=dict(exemplar))

    def _observe_key(self, key: _LabelKey, value: float,
                     exemplar: Optional[dict] = None) -> None:
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets))
            i = 0
            for b in self.buckets:          # linear scan: ~20 buckets,
                if value <= b:              # cheaper than bisect setup
                    break
                i += 1
            s.counts[i] += 1
            s.sum += value
            s.count += 1
            if exemplar is not None:
                s.exemplar = (float(value), exemplar, time.time())

    def exemplar_of(self, **labels) -> Optional[dict]:
        """The latest exemplar attached to a series, as
        ``{"value", "labels", "ts"}`` (None when the series has never
        seen one)."""
        s = self._series.get(_label_key(labels))
        if s is None or s.exemplar is None:
            return None
        value, ex_labels, ts = s.exemplar
        return {"value": value, "labels": dict(ex_labels), "ts": ts}

    def bind(self, **labels) -> "_BoundHistogram":
        """Pre-resolve a label set: the returned handle's ``observe``
        skips per-call label-key construction — for per-step hot
        paths (step_span caches one per model name)."""
        return _BoundHistogram(self, _label_key(labels))

    @contextmanager
    def time(self, **labels):
        """Observe the wall-clock duration of the with-block."""
        if not self._state["on"]:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, **labels)

    def quantile(self, q: float, **labels) -> float:
        """Estimated ``q``-quantile (0..1) from the cumulative bucket
        counts — the ``histogram_quantile`` discipline: linear
        interpolation inside the winning bucket, +Inf observations
        clamp to the top finite edge. NaN with no observations (a
        quantile of an empty series is undefined; 0.0 would read as
        "everything was instant" on a dashboard).
        An estimate bounded by bucket resolution, not an exact order
        statistic — serving benchmarks report p50/p95/p99 from the
        live registry with it."""
        s = self._series.get(_label_key(labels))
        if s is None or s.count == 0:
            return float("nan")
        target = q * s.count
        cum, lo = 0.0, 0.0
        for edge, c in zip(self.buckets, s.counts):
            if c and cum + c >= target:
                return lo + (edge - lo) * (target - cum) / c
            cum += c
            lo = edge
        return self.buckets[-1]

    def count_of(self, **labels) -> int:
        s = self._series.get(_label_key(labels))
        return s.count if s else 0

    def sum_of(self, **labels) -> float:
        s = self._series.get(_label_key(labels))
        return s.sum if s else 0.0

    def _render(self) -> List[str]:
        out = []
        for key, s in sorted(self._series.items()):
            cum = 0
            for b, c in zip(self.buckets, s.counts):
                cum += c
                le = 'le="%g"' % b
                out.append(f"{self.name}_bucket"
                           f"{_render_labels(key, le)} {cum}")
            inf = 'le="+Inf"'
            inf_line = (f"{self.name}_bucket"
                        f"{_render_labels(key, inf)} {s.count}")
            if s.exemplar is not None:
                # OpenMetrics exemplar syntax on the terminal bucket;
                # plain-text scrapers that stop at the value ignore it
                value, ex_labels, ts = s.exemplar
                ex = ",".join(f'{k}="{v}"'
                              for k, v in sorted(ex_labels.items()))
                inf_line += f" # {{{ex}}} {value:g} {ts:.3f}"
            out.append(inf_line)
            out.append(f"{self.name}_sum{_render_labels(key)} {s.sum:g}")
            out.append(f"{self.name}_count{_render_labels(key)}"
                       f" {s.count}")
        return out

    def _snapshot(self):
        return {";".join(f"{k}={v}" for k, v in key) or "": {
                    "count": s.count, "sum": s.sum,
                    "mean": (s.sum / s.count if s.count else 0.0)}
                for key, s in self._series.items()}


class _BoundHistogram:
    __slots__ = ("_h", "_key")

    def __init__(self, h: Histogram, key: _LabelKey):
        self._h = h
        self._key = key

    def observe(self, value: float) -> None:
        if self._h._state["on"]:
            self._h._observe_key(self._key, value)


class _BoundCounter:
    __slots__ = ("_c", "_key")

    def __init__(self, c: Counter, key: _LabelKey):
        self._c = c
        self._key = key

    def inc(self, amount: float = 1) -> None:
        c = self._c
        if c._state["on"]:
            with c._lock:
                c._series[self._key] = \
                    c._series.get(self._key, 0) + amount


class MetricsRegistry:
    """Process-wide, thread-safe metric registry.  Registration is
    idempotent: ``counter(name, ...)`` returns the existing metric when
    ``name`` is already registered (instrument sites in different
    modules share series by name), and raises on a kind mismatch."""

    _instance: Optional["MetricsRegistry"] = None
    _instance_lock = threading.Lock()

    def __init__(self, enabled: Optional[bool] = None):
        if enabled is None:
            enabled = Environment.get().telemetry
        self._state = {"on": bool(enabled)}
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    @classmethod
    def get(cls) -> "MetricsRegistry":
        inst = cls._instance
        if inst is not None:
            return inst
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def _reset_for_tests(cls):
        """Drop the singleton (and the trace buffer) so a test sees a
        clean panel; the next ``get()`` re-reads the env gate."""
        with cls._instance_lock:
            cls._instance = None
        _trace_buffer.clear()
        from deeplearning4j_tpu.common import faults, stepstats
        stepstats.StepStats._reset_for_tests()
        faults._reset_for_tests()
        for hook in list(_reset_hooks):
            hook()

    # -- gate ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._state["on"]

    def set_enabled(self, on: bool) -> None:
        self._state["on"] = bool(on)

    # -- registration --------------------------------------------------
    def _register(self, cls, name, help, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}, not {cls.kind}")
                return m
            m = cls(name, help, self._state, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._register(Histogram, name, help, buckets=buckets)

    # -- export --------------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m._render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """{name: {labelkey: value-or-hist-summary}} — the raw panel,
        JSON-serializable (MetricsReporterListener report payload)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: m._snapshot() for m in metrics}

    def summary(self) -> dict:
        """Compact snapshot for bench.py JSON: drops empty metrics."""
        return {k: v for k, v in self.snapshot().items() if v}


# ----------------------------------------------------------------------
# module-level conveniences: instrument sites call these; they resolve
# the singleton and are idempotent per metric name
def counter(name: str, help: str = "") -> Counter:
    return MetricsRegistry.get().counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return MetricsRegistry.get().gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return MetricsRegistry.get().histogram(name, help, buckets=buckets)


def enabled() -> bool:
    return MetricsRegistry.get().enabled


#: callables invoked by MetricsRegistry._reset_for_tests — modules
#: holding their own process-wide singletons (serving.slo,
#: serving.reqrec, common.tracectx) register here at import time so the
#: existing autouse test fixtures reset them too, without this module
#: having to import upward into the serving package
_reset_hooks: List = []


def on_reset(hook) -> None:
    """Register a zero-arg callable to run on every
    ``MetricsRegistry._reset_for_tests()`` (idempotent per hook)."""
    if hook not in _reset_hooks:
        _reset_hooks.append(hook)


# ----------------------------------------------------------------------
# one-timeline tracing: a shared chrome-trace event buffer, same event
# schema as ui.profiling.ProfilingListener so everything merges
class _TraceBuffer:
    """RING buffer: past ``max_events`` the OLDEST events are evicted,
    so a week-long run keeps the most recent window (the flight
    recorder's dump-on-crash wants the end of the run, not the start)
    at bounded host memory.  Evictions count into ``dropped`` (exported
    in trace metadata) and the
    ``dl4j_trace_events_dropped_total`` counter."""

    def __init__(self, max_events: int = 200_000):
        self.max_events = int(os.environ.get(
            "DL4J_TPU_TELEMETRY_MAX_EVENTS", str(max_events)))
        self._lock = threading.Lock()
        self.events: "deque[dict]" = deque()
        self.dropped = 0

    def append(self, ev: dict) -> None:
        n_evicted = 0
        with self._lock:
            self.events.append(ev)
            # max_events is a plain attribute (tests resize it live),
            # so ring capacity is enforced here, not via deque(maxlen)
            while len(self.events) > self.max_events:
                self.events.popleft()
                self.dropped += 1
                n_evicted += 1
        if n_evicted:
            counter("dl4j_trace_events_dropped_total",
                    "chrome-trace span-buffer ring evictions (oldest "
                    "events displaced once the buffer is full)"
                    ).inc(n_evicted)

    def clear(self) -> None:
        with self._lock:
            self.events = deque()
            self.dropped = 0


_trace_buffer = _TraceBuffer()


@contextmanager
def span(name: str, **attrs):
    """Record a wall-clock chrome-trace span ("X" event) for the
    with-block onto THIS thread's timeline row.  Near-free when
    telemetry is off.  Attrs land in the event's ``args`` and show in
    the trace viewer's detail pane."""
    if not MetricsRegistry.get().enabled:
        yield
        return
    t0 = time.time()
    try:
        yield
    finally:
        t1 = time.time()
        _trace_buffer.append({
            "name": name, "ph": "X", "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFF,
            "ts": int(t0 * 1e6), "dur": int((t1 - t0) * 1e6),
            "args": attrs})


def instant(name: str, **attrs) -> None:
    """Record a zero-duration chrome-trace instant event (retraces,
    cache evictions — things with a WHEN but no duration)."""
    if not MetricsRegistry.get().enabled:
        return
    _trace_buffer.append({
        "name": name, "ph": "i", "s": "p", "pid": os.getpid(),
        "tid": threading.get_ident() & 0xFFFF,
        "ts": int(time.time() * 1e6), "args": attrs})


def span_at(name: str, t_wall: float, dur_s: float, **attrs) -> None:
    """Record a chrome-trace span with EXPLICIT start/duration — for
    phases measured by another thread (a batcher flush attributing
    queue wait back to each request) where a with-block cannot wrap
    the interval. ``t_wall`` is a unix timestamp (seconds)."""
    if not MetricsRegistry.get().enabled:
        return
    _trace_buffer.append({
        "name": name, "ph": "X", "pid": os.getpid(),
        "tid": threading.get_ident() & 0xFFFF,
        "ts": int(t_wall * 1e6), "dur": max(0, int(dur_s * 1e6)),
        "args": attrs})


def trace_events() -> List[dict]:
    return list(_trace_buffer.events)


def export_chrome_trace(path: str,
                        metadata: Optional[dict] = None) -> str:
    """Write the shared span buffer as chrome://tracing JSON (the
    format ProfilingListener and jax.profiler also emit).  ``metadata``
    keys (e.g. ``host`` / ``clock_offset_s`` stamped by a scaling-
    observatory worker) merge into the document metadata, where
    :func:`merge_host_traces` reads them back."""
    with _trace_buffer._lock:
        events = list(_trace_buffer.events)
        dropped = _trace_buffer.dropped
    meta = {"dropped_events": dropped}
    if metadata:
        meta.update(metadata)
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": meta}
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def _load_trace(path: str) -> dict:
    op = gzip.open if str(path).endswith(".gz") else open
    with op(path, "rt") as f:
        doc = json.load(f)
    return doc if isinstance(doc, dict) else {"traceEvents": doc}


def merge_chrome_traces(output_path: str, *paths: str) -> str:
    """Concatenate the traceEvents of several chrome-trace files —
    telemetry spans, ProfilingListener iteration spans, and a
    ``jax.profiler`` trace (``.trace.json.gz`` under its log dir) —
    into ONE file whose timeline shows host and device side by side.
    Events already share the epoch-microsecond clock; pids/tids keep
    the sources on separate rows."""
    events: List[dict] = []
    meta: dict = {}
    for p in paths:
        doc = _load_trace(p)
        events.extend(doc.get("traceEvents", []))
        meta.update(doc.get("metadata", {}))
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": meta}
    with open(output_path, "w") as f:
        json.dump(doc, f)
    return output_path


def merge_host_traces(output_path: str, *sources) -> str:
    """Fold per-HOST trace files into one clock-corrected timeline.

    Each source is either a path (no correction) or a dict::

        {"path": ..., "host": "worker3", "clock_offset_s": 0.012}

    ``clock_offset_s`` is how far that host's clock runs AHEAD of the
    reference (leader) clock — the value ``StepStatsClient`` estimates
    in its connect handshake — so every event timestamp is shifted by
    ``-offset`` to express it on the leader clock; a source omitting it
    falls back to a ``clock_offset_s`` key in its own trace metadata
    (what :func:`export_chrome_trace` stamps on workers).  Pids are
    remapped per source so same-pid workers on different hosts land on
    separate rows, each labeled with its host via ``process_name``
    metadata events."""
    events: List[dict] = []
    meta: dict = {"hosts": []}
    for idx, src in enumerate(sources):
        if isinstance(src, (str, os.PathLike)):
            src = {"path": src}
        doc = _load_trace(src["path"])
        doc_meta = doc.get("metadata", {}) or {}
        host = src.get("host") or doc_meta.get("host") \
            or f"host{idx}"
        offset_s = src.get("clock_offset_s")
        if offset_s is None:
            offset_s = doc_meta.get("clock_offset_s", 0.0)
        shift_us = int(float(offset_s) * 1e6)
        pid_map: Dict[object, int] = {}
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            pid = pid_map.get(ev.get("pid"))
            if pid is None:
                pid = 1000 * (idx + 1) + len(pid_map)
                pid_map[ev.get("pid")] = pid
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = int(ev["ts"]) - shift_us
            events.append(ev)
        for pid in sorted(pid_map.values()):
            events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "tid": 0, "args": {"name": host}})
        meta["hosts"].append({"host": host,
                              "clock_offset_s": float(offset_s),
                              "events": len(doc.get("traceEvents",
                                                    []))})
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": meta}
    with open(output_path, "w") as f:
        json.dump(doc, f)
    return output_path


_STEP_HELP = ("host-observed train-step wall time: dispatch plus "
              "whatever sync the funnel performs (seconds)")


class _StepSpan:
    """The fit-funnel instrumentation point: times the with-block into
    the ``dl4j_train_step_seconds`` histogram (labeled by model class)
    AND records a ``train_step`` chrome-trace span — one call site per
    funnel keeps MLN/graph/SameDiff step timing comparable.

    Hand-rolled (slots, cached bound histogram per model name) rather
    than @contextmanager: this runs once per train step, and the <1%
    overhead budget is measured against millisecond steps."""

    __slots__ = ("model", "attrs", "_bound", "t0", "p0", "duration")

    def __init__(self, model: str, attrs: dict):
        self.model = model
        self.attrs = attrs

    def __enter__(self):
        # the clock always runs (two perf_counter calls even when
        # telemetry is off): the flight recorder reads ``duration``
        # after the with-block, independent of the metrics gate
        self.duration = 0.0
        self.p0 = time.perf_counter()
        reg = MetricsRegistry.get()
        if not reg._state["on"]:
            self._bound = None
            return self
        cache = reg.__dict__.setdefault("_step_bound", {})
        b = cache.get(self.model)
        if b is None:
            b = cache[self.model] = histogram(
                "dl4j_train_step_seconds",
                _STEP_HELP).bind(model=self.model)
        self._bound = b
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.p0
        self.duration = dt
        if self._bound is None:
            return False
        self._bound.observe(dt)
        _trace_buffer.append({
            "name": "train_step", "ph": "X", "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFF,
            "ts": int(self.t0 * 1e6), "dur": int(dt * 1e6),
            "args": {"model": self.model, **self.attrs}})
        return False


def step_span(model: str, **attrs) -> _StepSpan:
    return _StepSpan(model, attrs)


def observe_feed_stall(seconds: float, source: str) -> None:
    """Time a consumer spent blocked waiting for its next batch —
    non-zero buckets here mean the input pipeline, not the device, is
    the bottleneck (the ladder `benchmarks/bench_input_pipeline.py`
    measures, now visible in production runs)."""
    histogram("dl4j_feed_stall_seconds",
              "time the step loop waited on the input pipeline for "
              "its next batch (seconds)").observe(seconds,
                                                  source=source)
    # route into the scaling observatory's per-step breakdown as
    # data_wait (lazy import: stepstats imports this module)
    from deeplearning4j_tpu.common import stepstats
    stepstats.note_data_wait(seconds, source)


# ----------------------------------------------------------------------
class MetricsReporterListener(TrainingListener):
    """Folds registry snapshots into ``ui.stats`` reports every
    ``frequency`` iterations, so the dashboard (and anything tailing a
    FileStatsStorage JSONL) charts runtime metrics — queue depths,
    cache hits, step-time quantiles — alongside score curves.  Attach
    like any TrainingListener; reports carry a ``telemetry`` key."""

    def __init__(self, storage=None, frequency: int = 10):
        if storage is None:
            from deeplearning4j_tpu.ui.stats import InMemoryStatsStorage
            storage = InMemoryStatsStorage()
        self.storage = storage
        self.frequency = max(1, int(frequency))

    def iteration_done(self, model, iteration: int, epoch: int):
        if iteration % self.frequency:
            return
        self.storage.put_report({
            "iteration": iteration,
            "epoch": epoch,
            "time": time.time(),
            "score": float(model.score()),
            "layers": {},
            "telemetry": MetricsRegistry.get().summary()})
