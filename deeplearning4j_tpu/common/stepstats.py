"""Scaling observatory: per-step time decomposition + cross-host view.

The telemetry spine (PR 2) and the diagnostics layer (PR 7) record
*individual* instruments — step histograms, collective spans, feed
stalls.  This module is the layer that turns them into the one record a
scaling investigation needs: **where did this step's time go**, per
step, per worker, across hosts.  Four pieces:

- :class:`StepStats` — a process-wide collector the fit funnels close
  once per train-step dispatch.  Instrument sites route into it
  (``telemetry.observe_feed_stall`` → ``data_wait``,
  ``diagnostics.collective_span`` → ``collective``/``updater``/
  ``host_sync``, the checkpoint listener → ``checkpoint_stall``), and
  the close computes the ``compute`` residual, so every
  :class:`StepBreakdown`'s phases sum to ~the observed step wall time.
  Surfaced as ``dl4j_step_phase_seconds{phase}``, in the
  flight-recorder ring (a ``phases`` key per record), and as the
  ``step_breakdown`` block in ``bench.py``.
- :class:`StepStatsAggregator` / :class:`StepStatsClient` — the
  cross-host sidecar: each worker ships its breakdowns to the leader
  over a line-JSON TCP socket (riding beside, not inside, the gradient
  exchange — the exchange itself is a compiled collective).  The
  connect handshake is an NTP-lite timestamp exchange, so every worker
  knows its clock offset vs the leader (used by the cross-host trace
  merge).  The leader merges per-step, computes per-worker skew
  (``dl4j_straggler_skew_seconds``), and trips straggler detection
  (``dl4j_straggler_trips_total`` + a log line naming the offending
  host and its slowest phase) when one worker exceeds
  ``DL4J_TPU_STRAGGLER_FACTOR`` × the step mean.
- :func:`scaling_block` — the scaling-efficiency record bench.py (and a
  pod sweep) writes: per-chip throughput at each mesh size vs the
  smallest-size baseline, with the observatory's worker skew attached.
- :class:`ProfileCapture` — the on-demand bounded profile behind
  ``POST /api/profile?steps=N`` on the UIServer: at most one capture at
  a time, auto-finalizing after N closed steps (or a wall-clock
  expiry), dumping the observatory chrome trace plus, when available, a
  merged ``jax.profiler`` device trace.

Gate: ``DL4J_TPU_STEPSTATS`` (default on, and implies
``DL4J_TPU_TELEMETRY``); the whole layer rides the <1% step-overhead
budget — ``benchmarks/bench_telemetry.py`` has the observatory leg.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.environment import Environment

log = logging.getLogger("deeplearning4j_tpu")

#: the step-time decomposition every breakdown carries, in display
#: order.  ``compute`` is the residual of the step span after the
#: in-step phases; ``data_wait`` / ``checkpoint_stall`` / ``host_sync``
#: accrue BETWEEN step spans and extend the total beyond it.
PHASES = ("data_wait", "compute", "collective", "updater",
          "host_sync", "checkpoint_stall", "pipeline")

#: collective kinds → breakdown phase.  ``update_exchange`` is special:
#: its span WRAPS the fused train step, so only its excess over the
#: wrapped step is collective time (see :meth:`StepStats.note_collective`).
_COLLECTIVE_PHASE = {
    "update_exchange": "collective",
    "global_assembly": "host_sync",
    "state_placement": "updater",
}

_PHASE_HELP = ("per-step time decomposition: seconds attributed to "
               "each phase (data_wait | compute | collective | updater "
               "| host_sync | checkpoint_stall | pipeline) of one "
               "train-step dispatch; ``pipeline`` is the measured "
               "schedule bubble (stage idle time while peers compute)")


class StepStats:
    """Process-wide per-step breakdown collector (thread-safe).

    Instrument sites ``note_*`` into the pending accumulators; the fit
    funnel's ``diagnostics.after_step`` closes the step, which snapshots
    the accumulators into a :class:`StepBreakdown`-shaped dict, appends
    it to a bounded ring, observes ``dl4j_step_phase_seconds``, and
    feeds every registered sink (cross-host client, profile capture).
    """

    _instance: Optional["StepStats"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._gate = Environment.get().stepstats
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(
            maxlen=int(os.environ.get("DL4J_TPU_STEPSTATS_STEPS",
                                      "1024")))
        # pending accumulators since the last closed step
        self._in_step: Dict[str, float] = {}       # subtract from compute
        self._out_step: Dict[str, float] = {}      # extend the total
        self._collectives: Dict[str, float] = {}
        #: step seconds closed but not yet consumed by an
        #: ``update_exchange`` span (the span wraps the step)
        self._unconsumed_step_s = 0.0
        self._last: Optional[dict] = None
        # running totals for summary()
        self._n_steps = 0
        self._totals = {p: 0.0 for p in PHASES}
        self._total_step_s = 0.0
        self._total_s = 0.0
        self._sinks: List[Callable[[dict], None]] = []
        self._worker = {"worker": 0, "host": socket.gethostname(),
                        "n_workers": 1}
        self._bound_hists: Dict[str, object] = {}

    @classmethod
    def get(cls) -> "StepStats":
        inst = cls._instance
        if inst is not None:
            return inst
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def _reset_for_tests(cls):
        with cls._instance_lock:
            cls._instance = None

    # -- gating --------------------------------------------------------
    def enabled(self) -> bool:
        return self._gate and telemetry.enabled()

    def set_enabled(self, on: bool) -> None:
        self._gate = bool(on)

    # -- worker identity (cross-host shipping labels) ------------------
    def set_worker(self, worker: int, n_workers: int,
                   host: Optional[str] = None) -> None:
        self._worker = {"worker": int(worker),
                        "host": host or socket.gethostname(),
                        "n_workers": int(n_workers)}

    def add_sink(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn not in self._sinks:
                self._sinks.append(fn)

    def remove_sink(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    # -- instrument-site hooks -----------------------------------------
    def note_data_wait(self, seconds: float, source: str = "") -> None:
        """Feed-stall time the step loop spent blocked on its next
        batch (routed from ``telemetry.observe_feed_stall``)."""
        if not self.enabled():
            return
        with self._lock:
            self._out_step["data_wait"] = \
                self._out_step.get("data_wait", 0.0) + seconds

    def note_checkpoint_stall(self, seconds: float) -> None:
        """Step-loop-blocking checkpoint time (snapshot + join of the
        previous async write; the whole write when synchronous)."""
        if not self.enabled():
            return
        with self._lock:
            self._out_step["checkpoint_stall"] = \
                self._out_step.get("checkpoint_stall", 0.0) + seconds

    def note_in_step(self, phase: str, seconds: float) -> None:
        """A phase measured INSIDE the step span (e.g. the
        accumulation-window updater apply) — subtracted from the
        ``compute`` residual at close."""
        if not self.enabled():
            return
        with self._lock:
            self._in_step[phase] = self._in_step.get(phase, 0.0) \
                + seconds

    @contextmanager
    def phase(self, name: str):
        """Time the with-block as an in-step phase (also emits a
        ``step.<name>`` trace span)."""
        if not self.enabled():
            yield
            return
        t0 = time.perf_counter()
        with telemetry.span(f"step.{name}"):
            yield
        self.note_in_step(name, time.perf_counter() - t0)

    def note_collective(self, kind: str, seconds: float) -> None:
        """Route one closed ``collective_span`` into the breakdown.

        ``update_exchange`` wraps the fused train step, so the step
        seconds already closed inside it are subtracted and only the
        EXCESS (host dispatch + post-step sync around the fused
        program) lands in the last breakdown's ``collective`` phase;
        every other kind accrues as an out-of-step phase per
        ``_COLLECTIVE_PHASE``."""
        if not self.enabled():
            return
        with self._lock:
            if kind == "update_exchange":
                excess = max(seconds - self._unconsumed_step_s, 0.0)
                self._unconsumed_step_s = 0.0
                self._collectives[kind] = \
                    self._collectives.get(kind, 0.0) + seconds
                if self._last is not None:
                    self._last["phases"]["collective"] += excess
                    self._last["total_seconds"] += excess
                    self._last["collectives"][kind] = \
                        self._last["collectives"].get(kind, 0.0) \
                        + seconds
                    self._collectives.pop(kind, None)
                    self._totals["collective"] += excess
                    self._total_s += excess
                amount = excess
            else:
                phase = _COLLECTIVE_PHASE.get(kind, "collective")
                self._out_step[phase] = \
                    self._out_step.get(phase, 0.0) + seconds
                self._collectives[kind] = \
                    self._collectives.get(kind, 0.0) + seconds
                amount = seconds
        if amount:
            self._observe_phase(
                _COLLECTIVE_PHASE.get(kind, "collective"), amount)

    # -- the per-step close --------------------------------------------
    def close_step(self, model: str, step: int,
                   step_seconds: float) -> Optional[dict]:
        """Snapshot the pending accumulators into one breakdown record
        for the step dispatch that just finished (called from
        ``diagnostics.after_step``/``record_step`` with the
        ``step_span`` duration).  Returns the record, or None when the
        layer is off."""
        if not self.enabled() or step_seconds is None:
            return None
        with self._lock:
            in_step, self._in_step = self._in_step, {}
            out_step, self._out_step = self._out_step, {}
            colls, self._collectives = self._collectives, {}
            compute = max(step_seconds - sum(in_step.values()), 0.0)
            phases = {p: 0.0 for p in PHASES}
            phases["compute"] = compute
            for p, s in in_step.items():
                phases[p] = phases.get(p, 0.0) + s
            for p, s in out_step.items():
                phases[p] = phases.get(p, 0.0) + s
            rec = {
                "step": int(step),
                "model": model,
                "t": time.time(),
                **self._worker,
                "step_seconds": float(step_seconds),
                "total_seconds": float(step_seconds
                                       + sum(out_step.values())),
                "phases": phases,
                "collectives": colls,
            }
            self._ring.append(rec)
            self._last = rec
            self._unconsumed_step_s = min(
                self._unconsumed_step_s + step_seconds, 3600.0)
            self._n_steps += 1
            for p, s in phases.items():
                self._totals[p] += s
            self._total_step_s += step_seconds
            self._total_s += rec["total_seconds"]
            sinks = list(self._sinks)
        # metrics + sinks outside the lock
        self._observe_phase("compute", compute, model=model)
        for p, s in {**in_step, **out_step}.items():
            if s and p not in ("host_sync", "updater", "collective"):
                # collective-kind phases were observed at note time
                self._observe_phase(p, s, model=model)
        for fn in sinks:
            try:
                fn(rec)
            except Exception as e:      # noqa: BLE001 — a dead sink
                log.warning("stepstats sink failed: %r", e)
        return rec

    def _observe_phase(self, phase: str, seconds: float,
                       model: str = "") -> None:
        if not telemetry.enabled():
            return
        h = telemetry.histogram("dl4j_step_phase_seconds", _PHASE_HELP)
        h.observe(seconds, phase=phase)

    # -- reads ---------------------------------------------------------
    def last(self) -> Optional[dict]:
        return self._last

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def summary(self) -> dict:
        """The ``step_breakdown`` block for bench JSON: mean seconds
        per phase (summing to ~the mean total step time) and each
        phase's share of the run."""
        with self._lock:
            n = self._n_steps
            if not n:
                return {"steps": 0}
            return {
                "steps": n,
                "mean_step_seconds": self._total_step_s / n,
                "mean_total_seconds": self._total_s / n,
                "phases_mean_seconds": {
                    p: self._totals[p] / n for p in PHASES},
                "phases_pct": {
                    p: round(100.0 * self._totals[p]
                             / max(self._total_s, 1e-12), 2)
                    for p in PHASES},
            }


# ----------------------------------------------------------------------
# module-level conveniences (what instrument sites call)
def collector() -> StepStats:
    return StepStats.get()


def note_data_wait(seconds: float, source: str = "") -> None:
    StepStats.get().note_data_wait(seconds, source)


def note_checkpoint_stall(seconds: float) -> None:
    StepStats.get().note_checkpoint_stall(seconds)


def note_collective(kind: str, seconds: float) -> None:
    StepStats.get().note_collective(kind, seconds)


def close_step(model: str, step: int, span) -> Optional[dict]:
    """Close the current step from a ``telemetry.step_span`` (or any
    object with a ``duration``); None-safe."""
    dur = getattr(span, "duration", None)
    if dur is None:
        return None
    return StepStats.get().close_step(model, step, dur)


# ----------------------------------------------------------------------
# clock sync (NTP-lite): the worker sends t0 on its clock, the leader
# replies its own timestamp, the worker notes t1 on receipt
def estimate_clock_offset(t0_local: float, t_remote: float,
                          t1_local: float) -> float:
    """Seconds the LOCAL clock is ahead of the remote one, assuming a
    symmetric network path: ``offset = (t0+t1)/2 - t_remote``.
    Subtract ``offset`` from local timestamps to express them on the
    remote (leader) clock — what the cross-host trace merge does."""
    return (t0_local + t1_local) / 2.0 - t_remote


class StepStatsAggregator:
    """Leader-side cross-host breakdown merge + straggler detection.

    Listens on a TCP port; each worker's :class:`StepStatsClient`
    connects, performs the clock handshake, then streams one JSON line
    per step breakdown.  When every expected worker has reported a
    step, the step merges: per-worker skew vs the step mean lands in
    ``dl4j_straggler_skew_seconds{worker}``; a worker slower than
    ``trip_factor`` × mean (with the mean above ``min_step_seconds``,
    so microsecond noise cannot trip) increments
    ``dl4j_straggler_trips_total{worker,phase}`` and logs the offending
    host plus the phase that grew the most vs the other workers."""

    def __init__(self, expected_workers: int, *, port: int = 0,
                 host: str = "127.0.0.1",
                 trip_factor: Optional[float] = None,
                 min_step_seconds: Optional[float] = None,
                 history: int = 4096):
        if trip_factor is None:
            trip_factor = Environment.get().straggler_factor
        if min_step_seconds is None:
            min_step_seconds = Environment.get().straggler_min_step
        self.expected_workers = int(expected_workers)
        self.trip_factor = float(trip_factor)
        self.min_step_seconds = float(min_step_seconds)
        self.merged: "deque[dict]" = deque(maxlen=history)
        self.worker_offsets: Dict[int, float] = {}
        self.worker_hosts: Dict[int, str] = {}
        self.trips = 0
        self._steps: Dict[int, Dict[int, dict]] = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._closing = False
        self._threads: List[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="dl4j-obs-accept")
        t.start()
        self._threads.append(t)

    # -- wire ----------------------------------------------------------
    def _accept_loop(self):
        while not self._closing:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="dl4j-obs-conn")
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket):
        try:
            f = conn.makefile("rwb")
            for raw in f:
                try:
                    msg = json.loads(raw.decode())
                except json.JSONDecodeError:
                    continue
                if "hello" in msg:
                    # clock handshake: reply the leader timestamp
                    h = msg["hello"]
                    with self._lock:
                        self.worker_hosts[int(h.get("worker", -1))] = \
                            str(h.get("host", "?"))
                    f.write(json.dumps(
                        {"t_leader": time.time()}).encode() + b"\n")
                    f.flush()
                elif "offset_s" in msg:
                    with self._lock:
                        self.worker_offsets[int(msg["worker"])] = \
                            float(msg["offset_s"])
                elif "step" in msg:
                    self.ingest(msg)
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- merge ---------------------------------------------------------
    def ingest(self, rec: dict) -> Optional[dict]:
        """Fold one worker breakdown in; returns the merged record when
        this report completes its step (also the direct-call path the
        tests and a single-process harness use — no socket needed)."""
        step = int(rec["step"])
        worker = int(rec.get("worker", 0))
        with self._lock:
            bucket = self._steps.setdefault(step, {})
            bucket[worker] = rec
            if len(bucket) < self.expected_workers:
                return None
            del self._steps[step]
        return self._merge(step, bucket)

    def _merge(self, step: int, bucket: Dict[int, dict]) -> dict:
        times = {w: float(r["step_seconds"])
                 for w, r in bucket.items()}
        mean = sum(times.values()) / len(times)
        skew = {w: t - mean for w, t in times.items()}
        worst = max(times, key=times.get)
        max_skew = times[worst] - mean
        tripped = (mean > self.min_step_seconds
                   and times[worst] > self.trip_factor * mean)
        slow_phase = self._slowest_phase(bucket, worst)
        if telemetry.enabled():
            g = telemetry.gauge(
                "dl4j_straggler_skew_seconds",
                "per-worker deviation of step wall time from the "
                "cross-host step mean (signed seconds; the leader "
                "updates every merged step)")
            for w, s in skew.items():
                g.set(s, worker=str(w))
        merged = {
            "step": step,
            "workers": len(bucket),
            "mean_step_seconds": mean,
            "skew_seconds": skew,
            "max_skew_seconds": max_skew,
            "worst_worker": worst,
            "worst_host": bucket[worst].get("host", "?"),
            "worst_phase": slow_phase,
            "tripped": bool(tripped),
        }
        if tripped:
            # `trips` is read by report() from the leader thread while
            # every connection thread can be in here — same lock as
            # the merge bookkeeping
            with self._lock:
                self.trips += 1
            if telemetry.enabled():
                telemetry.counter(
                    "dl4j_straggler_trips_total",
                    "straggler-detector trips: one worker exceeded "
                    "DL4J_TPU_STRAGGLER_FACTOR x the cross-host step "
                    "mean, by worker and its slowest phase").inc(
                        worker=str(worst), phase=slow_phase)
                telemetry.instant("straggler_trip", step=step,
                                  worker=worst, phase=slow_phase)
            log.warning(
                "straggler: step %d worker %d (%s) took %.4fs vs "
                "%.4fs mean (>%.1fx) — slowest phase: %s",
                step, worst, merged["worst_host"], times[worst],
                mean, self.trip_factor, slow_phase)
        with self._lock:
            self.merged.append(merged)
        return merged

    @staticmethod
    def _slowest_phase(bucket: Dict[int, dict], worst: int) -> str:
        """The phase where the worst worker lost the most time vs the
        mean of the OTHER workers — the observatory's attribution of a
        straggler to collective / input / compute."""
        others = [r for w, r in bucket.items() if w != worst]
        worst_ph = bucket[worst].get("phases", {})
        best_phase, best_excess = "compute", float("-inf")
        for p in PHASES:
            mine = float(worst_ph.get(p, 0.0))
            ref = (sum(float(r.get("phases", {}).get(p, 0.0))
                       for r in others) / len(others)) if others else 0.0
            if mine - ref > best_excess:
                best_phase, best_excess = p, mine - ref
        return best_phase

    # -- reads ---------------------------------------------------------
    def report(self) -> dict:
        """The cross-host summary the leader folds into bench JSON:
        mean step time, worker skew, trip count."""
        with self._lock:
            merged = list(self.merged)
            trips = self.trips
        if not merged:
            return {"steps_merged": 0, "trips": trips}
        mean = sum(m["mean_step_seconds"] for m in merged) / len(merged)
        return {
            "steps_merged": len(merged),
            "workers": merged[-1]["workers"],
            "mean_step_seconds": mean,
            "max_skew_seconds": max(m["max_skew_seconds"]
                                    for m in merged),
            "trips": trips,
            "worker_clock_offsets_s": dict(self.worker_offsets),
        }

    def close(self):
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass


class StepStatsClient:
    """Worker-side shipper: clock handshake on connect, then one JSON
    line per breakdown.  Register with
    ``StepStats.get().add_sink(client.ship)``.

    A shipping failure marks the sink dead but schedules a RECONNECT
    with capped exponential backoff instead of disabling it for the
    rest of the run (a leader restart — e.g. after a preemption resume
    — used to silence every worker permanently).  Records offered while
    disconnected are dropped; observability must never take training
    down, so reconnect errors only push the retry further out.

    ``clock`` is injectable so tests can simulate skewed hosts."""

    def __init__(self, host: str, port: int, *, worker: int,
                 hostname: Optional[str] = None,
                 clock: Callable[[], float] = time.time,
                 timeout: float = 5.0,
                 reconnect_backoff: float = 0.5,
                 max_backoff: float = 30.0):
        self.worker = int(worker)
        self.clock = clock
        self._host, self._port, self._timeout = host, int(port), timeout
        self._hostname = hostname
        self._backoff = float(reconnect_backoff)
        self._max_backoff = float(max_backoff)
        self._fail_streak = 0
        self._retry_at = 0.0
        self._closed = False
        self._sock = None
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout)
        self._f = self._sock.makefile("rwb")
        # NTP-lite handshake: offset of OUR clock vs the leader's
        t0 = self.clock()
        self._send({"hello": {"worker": self.worker,
                              "host": self._hostname
                              or socket.gethostname(),
                              "t0": t0}})
        reply = json.loads(self._f.readline().decode())
        t1 = self.clock()
        self.clock_offset_s = estimate_clock_offset(
            t0, float(reply["t_leader"]), t1)
        self._send({"worker": self.worker,
                    "offset_s": self.clock_offset_s})
        self._dead = False
        self._fail_streak = 0

    def _send(self, obj: dict) -> None:
        self._f.write(json.dumps(obj).encode() + b"\n")
        self._f.flush()

    def _note_failure(self, what: str, e: BaseException) -> None:
        self._dead = True
        self._fail_streak += 1
        delay = min(self._backoff * 2 ** (self._fail_streak - 1),
                    self._max_backoff)
        self._retry_at = time.monotonic() + delay
        log.warning("stepstats client: %s failed (%r); retry in %.1fs",
                    what, e, delay)

    def ship(self, rec: dict) -> None:
        if self._dead:
            if self._closed or time.monotonic() < self._retry_at:
                return
            try:
                self._sock.close()
            except OSError:
                pass
            try:
                self._connect()
                log.info("stepstats client: reconnected to %s:%d",
                         self._host, self._port)
            except (OSError, ValueError) as e:
                self._note_failure("reconnect", e)
                return
        try:
            self._send(rec)
        except (OSError, ValueError) as e:
            self._note_failure("shipping", e)

    def close(self):
        self._dead = True
        self._closed = True
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass


# ----------------------------------------------------------------------
def scaling_block(measure_result: dict, *,
                  observatory: Optional[dict] = None) -> dict:
    """The bench-JSON ``scaling`` block from a
    ``parallel.scaling.measure_dp_scaling`` result: per-chip
    throughput and efficiency at every mesh size vs the smallest-size
    baseline, with the cross-host observatory's skew report attached
    when a leader ran one."""
    sizes = [int(n) for n in measure_result["sizes"]]
    base = int(measure_result.get("base", min(sizes)))
    tp = {int(n): float(v)
          for n, v in measure_result["throughput"].items()}
    block = {
        "baseline_chips": base,
        "sizes": sizes,
        "throughput_per_chip": {str(n): tp[n] / n for n in sizes},
        "efficiency": {str(n): (tp[n] / n) / (tp[base] / base)
                       for n in sizes},
        "max_worker_skew_seconds": 0.0,
    }
    if observatory:
        block["observatory"] = observatory
        block["max_worker_skew_seconds"] = float(
            observatory.get("max_skew_seconds", 0.0))
    return block


# ----------------------------------------------------------------------
# on-demand bounded profiling (POST /api/profile)
class CaptureActiveError(RuntimeError):
    """A capture is already running (the endpoint maps this to 409)."""


class ProfileCapture:
    """At most ONE bounded capture per process: counts down ``steps``
    closed breakdowns (or a wall-clock expiry as the backstop — a
    stalled job must not pin the profiler forever), then finalizes:
    stops the optional ``jax.profiler`` trace, exports the observatory
    chrome trace, and merges the two when the device trace exists."""

    _active: Optional["ProfileCapture"] = None
    _last_result: Optional[dict] = None
    _cls_lock = threading.Lock()

    def __init__(self, steps: int, out_dir: str, *,
                 use_jax: bool = True,
                 expire_seconds: Optional[float] = None):
        self.steps = max(1, min(int(steps), 100_000))
        self.remaining = self.steps
        self.out_dir = out_dir
        self.use_jax = bool(use_jax)
        self.expire_seconds = float(
            expire_seconds if expire_seconds is not None
            else max(30.0, self.steps * 2.0))
        self.started_at = time.time()
        self._jax_started = False
        self._done = False
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None

    # -- lifecycle -----------------------------------------------------
    @classmethod
    def start(cls, steps: int, *, out_dir: Optional[str] = None,
              use_jax: bool = True,
              expire_seconds: Optional[float] = None) -> dict:
        """Begin a capture; raises :class:`CaptureActiveError` when one
        is already running."""
        with cls._cls_lock:
            if cls._active is not None:
                raise CaptureActiveError(
                    f"a capture started {time.time() - cls._active.started_at:.0f}s "
                    f"ago is still active "
                    f"({cls._active.remaining} steps remaining)")
            if out_dir is None:
                base = Environment.get().flight_recorder_dir \
                    or "flightrec"
                out_dir = os.path.join(
                    base, f"profile_{int(time.time())}_{os.getpid()}")
            cap = cls(steps, out_dir, use_jax=use_jax,
                      expire_seconds=expire_seconds)
            cls._active = cap
        os.makedirs(out_dir, exist_ok=True)
        if cap.use_jax:
            try:
                import jax
                jax.profiler.start_trace(out_dir)
                cap._jax_started = True
            except Exception as e:  # noqa: BLE001 — observatory trace
                log.warning("jax.profiler capture unavailable: %r", e)
        StepStats.get().add_sink(cap._on_step)
        cap._timer = threading.Timer(cap.expire_seconds,
                                     cap.finalize, args=("expired",))
        cap._timer.daemon = True
        cap._timer.start()
        return cap.status()

    def _on_step(self, rec: dict) -> None:
        with self._lock:
            self.remaining -= 1
            done = self.remaining <= 0
        if done:
            self.finalize("complete")

    def finalize(self, reason: str) -> Optional[dict]:
        with self._lock:
            if self._done:
                return None
            self._done = True
        if self._timer is not None:
            self._timer.cancel()
        StepStats.get().remove_sink(self._on_step)
        artifacts = []
        if self._jax_started:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                log.warning("jax.profiler stop failed: %r", e)
        obs = os.path.join(self.out_dir, "observatory.trace.json")
        try:
            telemetry.export_chrome_trace(obs)
            artifacts.append(obs)
        except OSError as e:
            log.warning("observatory trace export failed: %r", e)
        # merge the device trace (jax writes
        # <dir>/plugins/profile/<run>/*.trace.json.gz) when present
        try:
            import glob as _glob
            dev = sorted(_glob.glob(os.path.join(
                self.out_dir, "plugins", "profile", "*",
                "*.trace.json.gz")))
            if dev and artifacts:
                merged = os.path.join(self.out_dir,
                                      "merged.trace.json")
                telemetry.merge_chrome_traces(merged, obs, *dev)
                artifacts.append(merged)
        except Exception as e:  # noqa: BLE001 — merge is best-effort
            log.warning("profile trace merge failed: %r", e)
        result = {
            "reason": reason,
            "steps_requested": self.steps,
            "steps_captured": self.steps - max(self.remaining, 0),
            "seconds": round(time.time() - self.started_at, 3),
            "out_dir": self.out_dir,
            "artifacts": artifacts,
            "jax_profiler": self._jax_started,
        }
        if telemetry.enabled():
            telemetry.counter(
                "dl4j_profile_captures_total",
                "on-demand profile captures finalized, by reason "
                "(complete | expired | cancelled)").inc(reason=reason)
        with ProfileCapture._cls_lock:
            ProfileCapture._last_result = result
            if ProfileCapture._active is self:
                ProfileCapture._active = None
        log.info("profile capture finalized (%s): %s", reason,
                 artifacts)
        return result

    def status(self) -> dict:
        return {"active": not self._done,
                "remaining_steps": max(self.remaining, 0),
                "steps": self.steps,
                "out_dir": self.out_dir,
                "started_at": self.started_at,
                "expire_seconds": self.expire_seconds,
                "jax_profiler": self._jax_started}

    # -- module-level views -------------------------------------------
    @classmethod
    def current_status(cls) -> dict:
        with cls._cls_lock:
            active = cls._active
            last = cls._last_result
        if active is not None:
            return active.status()
        out = {"active": False}
        if last is not None:
            out["last"] = last
        return out

    @classmethod
    def _reset_for_tests(cls):
        with cls._cls_lock:
            active, cls._active = cls._active, None
            cls._last_result = None
        if active is not None:
            active.finalize("cancelled")
