"""Persistent XLA compilation cache + retrace guard for the jit funnels.

The reference pays JVM warmup once per process; our analogue is XLA
compile latency, which every fresh process pays in full at the first
``fit``/``output`` call — minutes at ResNet/BERT scale on TPU. jax ships
a content-addressed on-disk compilation cache (the TVM compile-cache
idea): keyed by (HLO, compile options, backend version), so a second
process compiling the SAME network loads the serialized executable
instead of re-running XLA. :func:`enable_persistent_cache` points jax at
a per-user cache dir; every train-step/inference funnel calls it before
its first ``jax.jit`` so the cache is on by default
(``DL4J_TPU_COMPILE_CACHE=0`` opts out, ``DL4J_TPU_COMPILE_CACHE_DIR``
relocates it).

:class:`RetraceGuard` is the other half of compile-latency hygiene: the
cache cannot help a process that keeps compiling NEW programs. jit
retraces per input signature, so ragged minibatches or unbucketed
sequence lengths silently turn one network into dozens of compiled
programs. The guard counts distinct signatures per network and warns
once past a threshold, pointing at padding/bucketing.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.environment import Environment

log = logging.getLogger("deeplearning4j_tpu")

_lock = threading.Lock()
_enabled_dir: Optional[str] = None


def default_cache_dir() -> str:
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "deeplearning4j_tpu", "xla-cache")


def enable_persistent_cache() -> Optional[str]:
    """Idempotently enable jax's on-disk compilation cache. Returns the
    cache dir, or None when disabled. Safe to call from every funnel:
    only the first call mutates jax config.

    Default ON for accelerator backends (TPU/GPU — where XLA compiles
    for minutes and D2H copies are real copies). On the CPU backend the
    cache requires an EXPLICIT ``DL4J_TPU_COMPILE_CACHE=1``: cpu
    ``device_get``/``np.asarray`` return zero-copy views of XLA
    buffers, and a cache-loaded executable honors buffer donation that
    a freshly-compiled CPU one may not — code holding views across a
    donating step (a pattern CPU-only tests get away with) would see
    its arrays mutate."""
    global _enabled_dir
    env = Environment.get()
    if not env.compile_cache:
        return None
    with _lock:
        if _enabled_dir is not None:
            return _enabled_dir
        import jax
        if "DL4J_TPU_COMPILE_CACHE" not in os.environ and \
                jax.default_backend() == "cpu":
            return None
        d = env.compile_cache_dir or default_cache_dir()
        try:
            os.makedirs(d, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", d)
            # cache unconditionally: the default gates (>=1s compile,
            # min entry size) exist for shared-filesystem TPU pods;
            # here losing sub-second CPU entries would make the
            # second-process win untestable and skip exactly the
            # programs unit-scale users compile
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            # jax memoizes "is the cache used?" at the FIRST compile of
            # the process — which has usually already happened (PRNGKey
            # init, dtype conversions) by the time a train step is
            # built. Drop that verdict so the new dir takes effect.
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception as e:          # unwritable dir / exotic jax
            log.warning("persistent compilation cache disabled: %s", e)
            return None
        _enabled_dir = d
        log.debug("persistent XLA compilation cache at %s", d)
        return d


def _reset_for_tests():
    """Disable the cache and forget the enabled state so a test can
    exercise enablement without leaving the persistent cache live for
    the rest of the process (cache-LOADED executables honor donation —
    see enable_persistent_cache — which would perturb unrelated tests
    holding numpy views of donated buffers)."""
    global _enabled_dir
    with _lock:
        if _enabled_dir is not None:
            import jax
            from jax._src import compilation_cache as _cc
            jax.config.update("jax_compilation_cache_dir", None)
            _cc.reset_cache()
        _enabled_dir = None


def signature_of(*xs) -> tuple:
    """Hashable (shape, dtype) signature of a batch's arrays; None
    passes through, lists/tuples recurse (graph multi-input)."""
    out = []
    for x in xs:
        if x is None:
            out.append(None)
        elif isinstance(x, (list, tuple)):
            out.append(signature_of(*x))
        elif hasattr(x, "shape"):
            out.append((tuple(x.shape), str(getattr(x, "dtype", ""))))
        else:
            out.append(type(x).__name__)
    return tuple(out)


class RetraceGuard:
    """Counts the distinct input signatures one network has compiled
    and warns ONCE when the count exceeds the threshold — each new
    signature is a full XLA recompile (shape churn defeats both the
    in-process jit cache and the persistent cache's amortization)."""

    def __init__(self, name: str, threshold: Optional[int] = None):
        self.name = name
        self.threshold = (threshold if threshold is not None
                          else Environment.get().retrace_warn_threshold)
        self._sigs: set = set()
        self._warned = False
        # bound once: record() runs every step, and the hit path must
        # not pay a registry lookup + label-key build per step
        self._hits = telemetry.counter(
            "dl4j_compile_cache_hits_total",
            "steps whose input signature matched an "
            "already-compiled program (no retrace)").bind(
                network=self.name)

    def record(self, *batch_arrays) -> bool:
        """Record one dispatch; returns True when the signature was
        already known (no retrace) — callers gate their own
        cold-compile accounting on it (serving bucket misses)."""
        sig = signature_of(*batch_arrays)
        if sig in self._sigs:
            # known signature: the in-process executable is reused
            self._hits.inc()
            return True
        self._sigs.add(sig)
        # new signature: jit traces + compiles (the persistent on-disk
        # cache may still serve the binary — this counts compiles the
        # PROCESS had to go through, i.e. retrace pressure)
        telemetry.counter(
            "dl4j_compile_cache_misses_total",
            "steps whose input signature was new to this process "
            "(trace + XLA compile or persistent-cache load)"
        ).inc(network=self.name)
        if len(self._sigs) > 1:
            telemetry.counter(
                "dl4j_retrace_total",
                "recompiles past a network's first signature "
                "(shape/dtype churn)").inc(network=self.name)
            telemetry.instant("retrace", network=self.name,
                              signature=repr(sig),
                              n_signatures=len(self._sigs))
        if not self._warned and len(self._sigs) > self.threshold:
            self._warned = True
            log.warning(
                "%s has now compiled %d distinct input signatures — "
                "every new batch shape/dtype recompiles the whole XLA "
                "program. Pad minibatches to a fixed batch size (or "
                "bucket sequence lengths) so the step compiles once.",
                self.name, len(self._sigs))
        return False

    @property
    def n_signatures(self) -> int:
        return len(self._sigs)
