"""Global runtime flags facade.

Reference parity: ``org.nd4j.linalg.factory.Nd4j.getEnvironment()`` backed by
libnd4j's native ``Environment`` (include/system/Environment.h) plus the
``ND4JSystemProperties`` / ``ND4JEnvironmentVars`` flag surface (SURVEY.md
section 5.6). On TPU the native knobs become XLA/libtpu options; this facade
keeps one place for debug/verbose/profiling toggles and maps what it can onto
jax config.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field


@dataclass
class _Env:
    debug: bool = False
    verbose: bool = False
    profiling: bool = False
    check_for_nan: bool = False
    check_for_inf: bool = False
    allow_helpers: bool = True          # reference: cuDNN/oneDNN enablement
    default_float_dtype: str = "float32"
    # TPU-specific: matmul precision for f32 ops ('default'|'high'|'highest')
    matmul_precision: str = "default"
    # device-side input staging (datasets.prefetch.DevicePrefetcher):
    # fit() wraps iterators so the H2D copy of batch n+1 overlaps the
    # device step on batch n. Depth 2 = classic double buffering.
    device_prefetch: bool = True
    device_prefetch_depth: int = 2
    # persistent XLA compilation cache (common.compilecache): second
    # process compiling the same program loads the binary from disk
    compile_cache: bool = True
    compile_cache_dir: str = ""         # "" -> ~/.cache/deeplearning4j_tpu
    # warn after this many distinct compiled input signatures per
    # network (shape churn -> retrace storm; pad or bucket instead)
    retrace_warn_threshold: int = 5
    # unified telemetry spine (common.telemetry): metrics registry +
    # chrome-trace spans across train/infer/ETL; /metrics on UIServer
    telemetry: bool = True
    # ZeRO-1 cross-replica sharded weight update (parallel.zero): on a
    # dp>1 mesh the updater + its state run on a 1/N parameter shard
    # per replica instead of fully replicated. 0 restores the dense
    # replicated update exactly.
    sharded_update: bool = True
    # full FSDP / ZeRO-3 (parallel.zero): params + grads resident 1/N
    # per replica with per-layer just-in-time all-gather. 0 demotes
    # update_exchange="fsdp" requests to the ZeRO-1 sharded update.
    # fsdp_prefetch additionally emits layer k+1's gather while layer
    # k computes (off -> strictly on-demand gathers).
    fsdp: bool = True
    fsdp_prefetch: bool = True
    # encoded update exchange (parallel.zero / parallel.encoding): the
    # compressed-collective fourth rung (threshold sign·tau, int8,
    # 1-bit) with error-feedback residuals. 0 demotes
    # update_exchange="encoded" requests to the ZeRO-1 sharded update
    # (the exchange survives, only the compression drops).
    encoded_update: bool = True
    # numerics watchdog (common.diagnostics): opt-in sampled non-finite
    # check on loss / global grad norm inside the fit funnels; a trip
    # raises a structured NumericsEvent instead of training on NaNs
    numerics_watchdog: bool = False
    numerics_sample: int = 1            # check every Nth step
    # flight recorder (common.diagnostics): bounded ring of per-step
    # records, dumped to JSONL + chrome trace on crash/SIGTERM/watchdog
    flight_recorder: bool = True
    flight_recorder_steps: int = 256    # ring capacity (last N steps)
    flight_recorder_dir: str = "flightrec"  # dump dir (created on dump)
    flight_recorder_keep: int = 8       # newest K dumps retained
    # refresh HBM gauges from jax device memory stats every Nth
    # recorded step (the stats call is cheap but not free)
    hbm_sample_steps: int = 16
    # fault tolerance (common.faults): supervised in-process retries
    # after a training failure — attempts before giving up, and the
    # base of the capped exponential backoff between them (seconds)
    resume_retries: int = 3
    resume_backoff: float = 1.0
    # truly-async checkpoint snapshots (utils.checkpoint): fork a
    # donation-safe ON-DEVICE copy on the step path and defer the
    # device->host transfer to the background checkpoint writer. 0
    # restores the eager (step-loop-blocking) device_get.
    async_snapshot: bool = True
    # scaling observatory (common.stepstats): per-step phase
    # decomposition + cross-host straggler detection
    stepstats: bool = True
    straggler_factor: float = 2.0       # trip: worker > factor x mean
    straggler_min_step: float = 1e-3    # no trips below this mean step
    extra: dict = field(default_factory=dict)

    def set_debug(self, v: bool):
        self.debug = bool(v)

    def set_verbose(self, v: bool):
        self.verbose = bool(v)

    def set_profiling(self, v: bool):
        self.profiling = bool(v)


class Environment:
    """Process-wide singleton, env-var seeded.

    Env vars (analogue of ND4JEnvironmentVars):
      DL4J_TPU_DEBUG, DL4J_TPU_VERBOSE, DL4J_TPU_PROFILING,
      DL4J_TPU_CHECK_NAN, DL4J_TPU_CHECK_INF, DL4J_TPU_ALLOW_HELPERS,
      DL4J_TPU_DEVICE_PREFETCH, DL4J_TPU_DEVICE_PREFETCH_DEPTH,
      DL4J_TPU_COMPILE_CACHE, DL4J_TPU_COMPILE_CACHE_DIR,
      DL4J_TPU_RETRACE_WARN, DL4J_TPU_TELEMETRY,
      DL4J_TPU_SHARDED_UPDATE, DL4J_TPU_FSDP,
      DL4J_TPU_FSDP_PREFETCH, DL4J_TPU_ENCODED_UPDATE,
      DL4J_TPU_NUMERICS_WATCHDOG,
      DL4J_TPU_NUMERICS_SAMPLE, DL4J_TPU_FLIGHT_RECORDER,
      DL4J_TPU_FLIGHT_RECORDER_STEPS, DL4J_TPU_FLIGHT_RECORDER_DIR,
      DL4J_TPU_FLIGHT_RECORDER_KEEP, DL4J_TPU_HBM_SAMPLE_STEPS,
      DL4J_TPU_STEPSTATS, DL4J_TPU_STRAGGLER_FACTOR,
      DL4J_TPU_STRAGGLER_MIN_STEP, DL4J_TPU_RESUME_RETRIES,
      DL4J_TPU_RESUME_BACKOFF, DL4J_TPU_ASYNC_SNAPSHOT

    Read live (not cached here) by their subsystems:
      DL4J_TPU_GRAPHOPT (post-import GraphOptimizer pipeline, default
      on; =0 kills), DL4J_TPU_DUMP_GRAPHOPT (op-walk dumps around
      each mutating pass), DL4J_TPU_FLASH_ATTENTION (tri-state: =1
      forces the Pallas flash sdpa backend, =0 kills it, unset =
      auto heuristic), DL4J_TPU_FUSED_BN_BWD (fused BN backward:
      default on-for-TPU; =0 kills, =1 forces anywhere),
      DL4J_TPU_FUSED_CONV (tri-state like the flash gate: the Pallas
      conv/BN/ReLU epilogue family — conv-bias-act, BN statistics +
      normalize, matmul+epilogue for aligned 1x1 convs),
      DL4J_TPU_PAGED_ATTENTION (tri-state: the paged decode-attention
      Pallas kernel for the serving KV pool; all four gates resolve
      through the ops/kernel_select.py ladder: structural gate, then
      force/kill, then auto heuristic, every decision counted in
      dl4j_kernel_select_total),
      DL4J_TPU_CHAOS (common.faults fault injection: comma-separated
      kill_after_steps=N / hard_kill_after_steps=N /
      slow_worker=SECONDS / torn_checkpoint=1),
      DL4J_TPU_LAYERPROF (common.layerprof layer-attribution scopes:
      default on — the annotations are trace-time-only metadata with
      zero steady-state step cost; =0 kills them;
      Environment.extra["layerprof"] overrides the env var),
      DL4J_TPU_REQUEST_TRACE (common.tracectx per-request serving
      spans + exemplars: default on; =0 kills — request trace ids
      still mint so responses/logs stay joinable),
      DL4J_TPU_ACCESS_LOG / DL4J_TPU_ACCESS_LOG_SAMPLE (httputil
      sampled JSONL access log: path turns it on, sample rate keeps
      a deterministic 1-in-N slice),
      DL4J_TPU_REQREC / DL4J_TPU_REQREC_CAPACITY /
      DL4J_TPU_REQREC_DIR / DL4J_TPU_REQREC_SHED_THRESHOLD /
      DL4J_TPU_REQREC_SHED_WINDOW_S /
      DL4J_TPU_REQREC_STORM_COOLDOWN_S (serving.reqrec request
      flight recorder: default on, 512-record ring, dump dir falls
      back to DL4J_TPU_FLIGHT_RECORDER_DIR; storm = threshold sheds
      inside the window, then a cooldown between dumps),
      DL4J_TPU_SLO_TARGET / DL4J_TPU_SLO_FAST_S / DL4J_TPU_SLO_SLOW_S
      (serving.slo error-budget accounting: in-SLO target fraction,
      default 0.99, over fast/slow burn-rate windows, default
      300 s / 3600 s),
      DL4J_TPU_HTTP_HOST (bind interface for every HTTP server —
      httputil, ui.server, serving.router; default 127.0.0.1,
      loopback only; set 0.0.0.0 to expose beyond the host),
      DL4J_TPU_OBSERVATORY_PORT (parallel.sharedtraining leader port
      for the cross-worker step-stats aggregator, default 9470),
      DL4J_TPU_TELEMETRY_MAX_EVENTS (common.telemetry trace-event
      ring capacity, default 200000),
      DL4J_TPU_STEPSTATS_STEPS (common.stepstats per-step ring size,
      default 1024),
      DL4J_TPU_DATA_DIR (datasets: directory holding real iris.csv /
      MNIST IDX files; synthetic fallbacks are used when unset),
      DL4J_TPU_NATIVE_LIB (native.bridge: explicit path to the
      compiled helper library — load-or-fail, no silent fallback;
      the sanitizer suite points it at the ASan+UBSan build),
      DL4J_TPU_DISABLE_NATIVE (=1 forces the pure-Python fallbacks
      even when the native library is buildable),
      DL4J_TPU_TEST_PLATFORM (tests/benchmarks only: platform pin
      for the suite — default cpu with an 8-device virtual mesh;
      =axon runs against real accelerators),
      DL4J_TPU_ENCODED_SCHEME (parallel.encoding: default wire codec
      for update_exchange="encoded" when no EncodingSpec is passed —
      threshold | int8 | 1bit, default threshold),
      DL4J_TPU_KV_DTYPE (serving.batcher: KV-block pool dtype for
      generative serving — float32 | bfloat16, default float32; a
      per-model generate={"kv_dtype": ...} overrides it),
      DL4J_TPU_SERVING_PARAM_DTYPE (serving.registry: default
      register(param_dtype=...) low-precision residency cast for
      sharded/fsdp-resident serving params — bf16 | int8, unset =
      full precision)
    """

    _inst: _Env | None = None
    _lock = threading.Lock()

    @classmethod
    def get(cls) -> _Env:
        inst = cls._inst
        if inst is not None:    # lock-free fast path (ops call this per-op)
            return inst
        with cls._lock:
            if cls._inst is None:
                def b(name, dflt=False):
                    return os.environ.get(name, str(int(dflt))) in (
                        "1", "true", "True", "yes")
                cls._inst = _Env(
                    debug=b("DL4J_TPU_DEBUG"),
                    verbose=b("DL4J_TPU_VERBOSE"),
                    profiling=b("DL4J_TPU_PROFILING"),
                    check_for_nan=b("DL4J_TPU_CHECK_NAN"),
                    check_for_inf=b("DL4J_TPU_CHECK_INF"),
                    allow_helpers=b("DL4J_TPU_ALLOW_HELPERS", True),
                    device_prefetch=b("DL4J_TPU_DEVICE_PREFETCH", True),
                    device_prefetch_depth=int(os.environ.get(
                        "DL4J_TPU_DEVICE_PREFETCH_DEPTH", "2")),
                    compile_cache=b("DL4J_TPU_COMPILE_CACHE", True),
                    compile_cache_dir=os.environ.get(
                        "DL4J_TPU_COMPILE_CACHE_DIR", ""),
                    retrace_warn_threshold=int(os.environ.get(
                        "DL4J_TPU_RETRACE_WARN", "5")),
                    telemetry=b("DL4J_TPU_TELEMETRY", True),
                    sharded_update=b("DL4J_TPU_SHARDED_UPDATE", True),
                    fsdp=b("DL4J_TPU_FSDP", True),
                    fsdp_prefetch=b("DL4J_TPU_FSDP_PREFETCH", True),
                    encoded_update=b("DL4J_TPU_ENCODED_UPDATE", True),
                    numerics_watchdog=b("DL4J_TPU_NUMERICS_WATCHDOG"),
                    numerics_sample=int(os.environ.get(
                        "DL4J_TPU_NUMERICS_SAMPLE", "1")),
                    flight_recorder=b("DL4J_TPU_FLIGHT_RECORDER", True),
                    flight_recorder_steps=int(os.environ.get(
                        "DL4J_TPU_FLIGHT_RECORDER_STEPS", "256")),
                    flight_recorder_dir=os.environ.get(
                        "DL4J_TPU_FLIGHT_RECORDER_DIR", "flightrec"),
                    flight_recorder_keep=int(os.environ.get(
                        "DL4J_TPU_FLIGHT_RECORDER_KEEP", "8")),
                    hbm_sample_steps=int(os.environ.get(
                        "DL4J_TPU_HBM_SAMPLE_STEPS", "16")),
                    resume_retries=int(os.environ.get(
                        "DL4J_TPU_RESUME_RETRIES", "3")),
                    resume_backoff=float(os.environ.get(
                        "DL4J_TPU_RESUME_BACKOFF", "1.0")),
                    async_snapshot=b("DL4J_TPU_ASYNC_SNAPSHOT", True),
                    stepstats=b("DL4J_TPU_STEPSTATS", True),
                    straggler_factor=float(os.environ.get(
                        "DL4J_TPU_STRAGGLER_FACTOR", "2.0")),
                    straggler_min_step=float(os.environ.get(
                        "DL4J_TPU_STRAGGLER_MIN_STEP", "1e-3")),
                )
            return cls._inst

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._inst = None
