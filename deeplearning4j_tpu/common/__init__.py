from deeplearning4j_tpu.common.dtypes import DataType  # noqa: F401
from deeplearning4j_tpu.common.environment import Environment  # noqa: F401
