"""Preemption capture, auto-resume policy, and chaos injection.

The elasticity story (ROADMAP item 4, SURVEY.md §5.3) in three layers:

- :class:`PreemptionGuard` turns a SIGTERM / preemption notice into a
  cooperative flag instead of an immediate death: the training loop
  checks :func:`preemption_requested` at the next step boundary, writes
  one final checkpoint, and raises :class:`TrainingPreempted` — a clean
  resumable exit (exit code 75, EX_TEMPFAIL: supervisors read it as
  "retry me").  The guard composes with the PR-7 flight recorder's
  SIGTERM plumbing in either install order: whichever handler runs
  first dumps the recorder ring (dedup inside ``dump``) and sets the
  flag; neither re-delivers the killing signal while a capture is
  possible.  A SECOND notice means the grace period is over — the
  default disposition is restored and the process dies as SIGTERM.

- the resume policy knobs (``DL4J_TPU_RESUME_RETRIES`` /
  ``DL4J_TPU_RESUME_BACKOFF``) drive the supervised retry loops in
  ``utils.checkpoint.FaultTolerantTrainer`` and
  ``parallel.sharedtraining.SharedTrainingMaster.fit``: capped
  exponential backoff, then restart from the newest valid checkpoint.

- :class:`ChaosMonkey` (``DL4J_TPU_CHAOS``, read live) injects the
  faults the harness must survive: SIGTERM after N steps, a hard kill
  (no capture), a per-step slowdown, a torn newest checkpoint.  It is
  fed from the ``diagnostics.record_step``/``after_step`` funnels so
  every fit path (MLN / graph / SameDiff) is injectable.

Metrics: ``dl4j_preemption_total``, ``dl4j_resume_total`` (label
``kind``: ``restart`` = a new process picked up an existing checkpoint
dir, ``inprocess`` = a supervised retry reloaded after a failure),
``dl4j_lost_steps_total``, ``dl4j_chaos_injections_total``.
"""
from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Optional

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.environment import Environment

log = logging.getLogger("deeplearning4j_tpu")

#: clean-preemption exit status (EX_TEMPFAIL — "try again later");
#: supervisors distinguish it from a crash and simply re-run the job
PREEMPTED_EXIT_CODE = 75

#: backoff ceiling for the supervised retry loops (seconds)
MAX_RESUME_BACKOFF_S = 30.0


class TrainingPreempted(Exception):
    """Raised at a step boundary AFTER the preemption notice has been
    captured and the final checkpoint made durable.  Catch it at the
    job top level and ``sys.exit(e.exit_code)`` — re-running the same
    command resumes from the checkpoint dir with nothing lost."""

    exit_code = PREEMPTED_EXIT_CODE


# ----------------------------------------------------------------------
# preemption capture
def _is_flight_recorder_handler(fn) -> bool:
    try:
        from deeplearning4j_tpu.common.diagnostics import FlightRecorder
        return isinstance(getattr(fn, "__self__", None), FlightRecorder)
    except Exception:       # noqa: BLE001 — never break signal dispatch
        return False


class PreemptionGuard:
    """Process-wide SIGTERM → cooperative-flag converter.

    ``install()`` is idempotent and safe off the main thread (where it
    degrades to cooperative :meth:`request` only — Python restricts
    ``signal.signal`` to the main thread).  The handler never raises
    and never blocks: it sets the flag, counts the preemption, dumps
    the flight-recorder ring, and returns so the in-flight train step
    finishes normally."""

    _instance: Optional["PreemptionGuard"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._requested = threading.Event()
        self._installed = False
        self._prev = None

    @classmethod
    def get(cls) -> "PreemptionGuard":
        inst = cls._instance
        if inst is not None:
            return inst
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def _reset_for_tests(cls):
        with cls._instance_lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.uninstall()

    # ------------------------------------------------------------------
    def install(self) -> "PreemptionGuard":
        if self._installed:
            return self
        try:
            self._prev = signal.signal(signal.SIGTERM, self._on_sigterm)
            self._installed = True
        except ValueError:
            # not the main thread: cooperative request() still works
            self._prev = None
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        try:
            signal.signal(signal.SIGTERM, self._prev
                          if self._prev is not None else signal.SIG_DFL)
        except (ValueError, TypeError):
            pass
        self._prev = None

    def _on_sigterm(self, signum, frame):
        if self._requested.is_set():
            # second notice: the grace period is over — die as SIGTERM
            try:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
            except ValueError:
                pass
            os.kill(os.getpid(), signal.SIGTERM)
            return
        self.request("sigterm")
        prev = self._prev
        # chain to a prior handler UNLESS it is the flight recorder's:
        # its fallback re-delivers the signal with the default
        # disposition, which would kill the process before the final
        # snapshot (request() already dumped the ring for it)
        if callable(prev) and not _is_flight_recorder_handler(prev):
            try:
                prev(signum, frame)
            except Exception:   # noqa: BLE001 — capture must proceed
                pass

    # ------------------------------------------------------------------
    def request(self, reason: str = "sigterm") -> None:
        """Mark a preemption notice (signal handler or cooperative —
        e.g. a cloud metadata watcher thread)."""
        if self._requested.is_set():
            return
        self._requested.set()
        log.warning("preemption notice (%s): finishing the current "
                    "step, then snapshotting for resume", reason)
        if telemetry.enabled():
            telemetry.counter(
                "dl4j_preemption_total",
                "preemption notices captured (SIGTERM or cooperative "
                "request), by reason").inc(reason=reason)
        try:
            from deeplearning4j_tpu.common.diagnostics import \
                FlightRecorder
            FlightRecorder.get().dump("preemption")
        except Exception:       # noqa: BLE001 — never break capture
            pass

    def requested(self) -> bool:
        return self._requested.is_set()

    def clear(self) -> None:
        """Re-arm after a handled preemption (tests; supervisors that
        keep the process alive across the resume)."""
        self._requested.clear()


def install_preemption_capture() -> PreemptionGuard:
    return PreemptionGuard.get().install()


def preemption_requested() -> bool:
    return PreemptionGuard.get().requested()


# ----------------------------------------------------------------------
# resume policy + accounting
def resume_retries() -> int:
    return max(int(Environment.get().resume_retries), 0)


def resume_backoff(attempt: int) -> float:
    """Delay before retry ``attempt`` (1-based): capped exponential."""
    base = max(float(Environment.get().resume_backoff), 0.0)
    return min(base * (2 ** max(attempt - 1, 0)), MAX_RESUME_BACKOFF_S)


def note_resume(kind: str, lost_steps: int = 0) -> None:
    """Count one resume-from-checkpoint.  ``kind``: ``restart`` (a new
    process picked up an existing checkpoint dir) or ``inprocess``
    (the supervised retry loop reloaded after a failure).
    ``lost_steps`` = iterations trained past the restored checkpoint
    and therefore re-run."""
    if not telemetry.enabled():
        return
    telemetry.counter(
        "dl4j_resume_total",
        "training resumes from checkpoint, by kind (restart = new "
        "process found an existing checkpoint dir; inprocess = "
        "supervised retry loop reloaded after a failure)").inc(
            kind=kind)
    if lost_steps > 0:
        telemetry.counter(
            "dl4j_lost_steps_total",
            "train iterations lost to a failure/preemption (trained "
            "past the restored checkpoint, re-run after resume)").inc(
                int(lost_steps))


# ----------------------------------------------------------------------
# chaos injection
class ChaosMonkey:
    """Fault injector behind ``DL4J_TPU_CHAOS`` (read live, parsed
    once per process).  Comma-separated directives:

    - ``kill_after_steps=N`` — SIGTERM to self after N train
      iterations (the graceful path: a captured preemption when the
      guard is installed);
    - ``hard_kill_after_steps=N`` — ``os._exit(137)`` after N
      iterations (the SIGKILL path: no final snapshot, resume falls
      back to the last cadence checkpoint);
    - ``slow_worker=SECONDS`` — sleep that long every iteration (a
      straggler for the observatory to flag);
    - ``torn_checkpoint=1`` — after the preemption snapshot, truncate
      the newest checkpoint on disk (resume must skip it and fall
      back; fires once).
    """

    def __init__(self, spec: str):
        self.kill_after = 0
        self.hard_kill_after = 0
        self.slow = 0.0
        self.torn = False
        self._steps = 0
        self._slow_noted = False
        for directive in spec.split(","):
            directive = directive.strip()
            if not directive:
                continue
            key, _, val = directive.partition("=")
            key = key.strip()
            val = val.strip() or "1"
            try:
                if key == "kill_after_steps":
                    self.kill_after = int(val)
                elif key == "hard_kill_after_steps":
                    self.hard_kill_after = int(val)
                elif key == "slow_worker":
                    self.slow = float(val)
                elif key == "torn_checkpoint":
                    self.torn = val not in ("0", "false", "False")
                else:
                    log.warning("DL4J_TPU_CHAOS: unknown directive %r",
                                directive)
            except ValueError:
                log.warning("DL4J_TPU_CHAOS: bad value in %r", directive)

    @staticmethod
    def _note(kind: str) -> None:
        if telemetry.enabled():
            telemetry.counter(
                "dl4j_chaos_injections_total",
                "faults injected by the DL4J_TPU_CHAOS harness, by "
                "kind").inc(kind=kind)

    def on_step(self) -> None:
        self._steps += 1
        if self.slow > 0:
            if not self._slow_noted:
                self._slow_noted = True
                self._note("slow_worker")
            time.sleep(self.slow)
        if self.kill_after and self._steps == self.kill_after:
            self._note("sigterm")
            log.warning("chaos: SIGTERM to self after %d steps",
                        self._steps)
            os.kill(os.getpid(), signal.SIGTERM)
        if self.hard_kill_after and self._steps == self.hard_kill_after:
            self._note("hard_kill")
            log.warning("chaos: hard kill after %d steps", self._steps)
            os._exit(137)

    def maybe_tear(self, save_dir) -> bool:
        """Truncate the newest checkpoint in ``save_dir`` (once)."""
        if not self.torn:
            return False
        from deeplearning4j_tpu.utils.checkpoint import \
            CheckpointListener
        cp = CheckpointListener.last_checkpoint_in(save_dir)
        if cp is None:
            return False
        data = cp.read_bytes()
        cp.write_bytes(data[:max(len(data) // 3, 1)])
        self.torn = False
        self._note("torn_checkpoint")
        log.warning("chaos: tore newest checkpoint %s", cp)
        return True


_monkey: Optional[ChaosMonkey] = None
_monkey_parsed = False
_monkey_lock = threading.Lock()


def chaos_monkey() -> Optional[ChaosMonkey]:
    """The process's chaos injector, or None when ``DL4J_TPU_CHAOS``
    is unset/empty.  Parsed once; near-free afterwards (the step
    funnels call this every iteration)."""
    global _monkey, _monkey_parsed
    if _monkey_parsed:
        return _monkey
    with _monkey_lock:
        if not _monkey_parsed:
            spec = os.environ.get("DL4J_TPU_CHAOS", "").strip()
            _monkey = ChaosMonkey(spec) if spec else None
            _monkey_parsed = True
    return _monkey


def chaos_step() -> None:
    cm = chaos_monkey()
    if cm is not None:
        cm.on_step()


def _reset_for_tests() -> None:
    global _monkey, _monkey_parsed
    with _monkey_lock:
        _monkey = None
        _monkey_parsed = False
    PreemptionGuard._reset_for_tests()
