"""Shared stdlib-HTTP plumbing for the in-process servers.

Both network faces of the system — the training dashboard
(``ui.server.UIServer``) and the inference server
(``serving.server.InferenceServer``) — ride the same zero-dependency
``ThreadingHTTPServer`` pattern: silent request logging, explicit
Content-Length framing, JSON bodies, and the Prometheus ``/metrics``
renderer. This module is the one copy of that plumbing.

It also owns the zero-copy ``.npy`` codec for the serving hot path:
:func:`npy_view` parses a raw ``.npy`` request body into an ndarray
*view over the received bytes* (no second materialization of the
tensor), and :func:`npy_header` + :meth:`QuietHandler.send_body_parts`
stream a response as header-then-array-buffer without ever joining
them into one intermediate bytes object. ``bench_serving.py`` measures
the serialization tax this removes against the JSON path.

Bind host: ``DL4J_TPU_HTTP_HOST`` (default ``127.0.0.1`` — loopback
only; set ``0.0.0.0`` to expose a server beyond the host, e.g. from a
container).

Access log: ``DL4J_TPU_ACCESS_LOG=<path>`` turns on a sampled
structured (JSONL) access log for every server riding
:class:`QuietHandler` — one line per completed request with method,
path, status, response bytes, duration, and the request's trace id
(the serving observatory's join key between the access log, the
chrome-trace span tree, and the latency-histogram exemplars).
``DL4J_TPU_ACCESS_LOG_SAMPLE`` (default ``1.0``) keeps every
``1/rate``-th request deterministically — hot fleets log a thin,
unbiased slice instead of every request.
"""
from __future__ import annotations

import io
import itertools
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.common import telemetry

#: (path, log-every-nth) — cached once per process; tests reset via
#: the telemetry reset hook after flipping the env vars
_access_conf: Optional[Tuple[str, int]] = None


def _access_log_conf() -> Tuple[str, int]:
    global _access_conf
    if _access_conf is None:
        path = os.environ.get("DL4J_TPU_ACCESS_LOG", "")
        try:
            rate = float(os.environ.get(
                "DL4J_TPU_ACCESS_LOG_SAMPLE", "1"))
        except ValueError:
            rate = 1.0
        every = 0 if not path or rate <= 0 else \
            max(1, int(round(1.0 / min(1.0, rate))))
        _access_conf = (path, every)
    return _access_conf


def _reset_access_conf() -> None:
    global _access_conf
    _access_conf = None


telemetry.on_reset(_reset_access_conf)


def npy_view(buf) -> "np.ndarray":
    """An ndarray view over a raw ``.npy`` byte buffer — header parsed
    in place, data NOT copied (``np.frombuffer`` aliases ``buf``; the
    view is read-only when ``buf`` is ``bytes``).

    Contrast ``np.load(io.BytesIO(body))``, which materializes a
    second copy of the tensor per request. Object-dtype payloads are
    rejected (they would need pickle — never on a network path).
    Raises ``ValueError`` on anything that is not a well-formed v1/v2
    ``.npy`` frame."""
    f = io.BytesIO(buf)
    try:
        version = np.lib.format.read_magic(f)
    except Exception as e:
        raise ValueError(f"not a .npy payload: {e}") from e
    if version == (1, 0):
        shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
    elif version == (2, 0):
        shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
    else:
        raise ValueError(f"unsupported .npy version {version}")
    if dtype.hasobject:
        raise ValueError("object-dtype .npy payloads are not served "
                         "(pickle is never read off the network)")
    count = 1
    for s in shape:
        count *= int(s)
    a = np.frombuffer(buf, dtype=dtype, count=count, offset=f.tell())
    return a.reshape(shape, order="F" if fortran else "C")


def npy_header(arr: "np.ndarray") -> bytes:
    """The ``.npy`` v1 magic + header bytes describing ``arr`` —
    everything that precedes the raw data buffer. Streaming
    ``npy_header(a)`` then ``memoryview(a)`` IS the file
    ``np.save`` would have written, minus the intermediate copy."""
    f = io.BytesIO()
    np.lib.format.write_array_header_1_0(
        f, np.lib.format.header_data_from_array_1_0(arr))
    return f.getvalue()


def bind_host() -> str:
    """The interface every server binds (env-configurable per
    process; read at ``start()`` time so tests can flip it)."""
    return os.environ.get("DL4J_TPU_HTTP_HOST", "127.0.0.1")


class QuietHandler(BaseHTTPRequestHandler):
    """BaseHTTPRequestHandler minus the stderr request log, plus the
    response/body helpers every endpoint needs."""

    #: ThreadingHTTPServer threads die with the process
    daemon_threads = True

    #: chunked transfer-encoding (the streaming :generate response)
    #: requires HTTP/1.1; every non-chunked response still carries an
    #: explicit Content-Length, so keep-alive connections never hang
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):       # silence request logging
        pass

    # -- sampled structured access log ---------------------------------
    #: shared across handler threads: the deterministic 1-in-N sampler
    _access_seq = itertools.count(1)
    _access_write_lock = threading.Lock()
    #: per-request state (reset in parse_request; class-level defaults
    #: cover requests that never parse, e.g. a closed keep-alive)
    _t_req = 0.0
    _resp_status: Optional[int] = None
    _resp_bytes = 0
    #: set by the serving server/router during request handling — the
    #: access log's join key into the span tree
    _trace_id: Optional[str] = None

    def parse_request(self):
        # per-request reset: handler threads serve many keep-alive
        # requests, so stale status/trace ids must not carry over
        self._t_req = time.monotonic()
        self._resp_status = None
        self._resp_bytes = 0
        self._trace_id = None
        return super().parse_request()

    def send_response(self, code, message=None):
        if self._resp_status is None:   # first status wins (chunked
            self._resp_status = int(code)   # streams send one)
        super().send_response(code, message)

    def handle_one_request(self):
        super().handle_one_request()
        try:
            self._access_log()
        except Exception:       # noqa: BLE001 — logging must never
            pass                # break the serving path

    def _access_log(self) -> None:
        path, every = _access_log_conf()
        if not every or self._resp_status is None:
            return
        if next(QuietHandler._access_seq) % every:
            return
        line = json.dumps({
            "t": time.time(),
            "method": self.command,
            "path": self.path,
            "status": self._resp_status,
            "bytes": self._resp_bytes,
            "duration_ms": round(
                (time.monotonic() - self._t_req) * 1e3, 3),
            "trace_id": self._trace_id,
        })
        with QuietHandler._access_write_lock:
            with open(path, "a") as f:
                f.write(line + "\n")

    # -- responses -----------------------------------------------------
    def send_body(self, body: bytes, content_type: str,
                  code: int = 200, headers: Optional[dict] = None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)
        self._resp_bytes += len(body)

    def send_body_parts(self, parts: Sequence, content_type: str,
                        code: int = 200,
                        headers: Optional[dict] = None):
        """Stream a response as a sequence of byte-like parts (bytes /
        memoryview / C-contiguous ndarray) with ONE summed
        Content-Length and sequential socket writes — no join into an
        intermediate buffer. The zero-copy ``.npy`` response path:
        ``send_body_parts([npy_header(a), memoryview(a)], ...)``."""
        views = [memoryview(p).cast("B") for p in parts]
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length",
                         str(sum(v.nbytes for v in views)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        for v in views:
            self.wfile.write(v)
            self._resp_bytes += v.nbytes

    def send_json(self, obj, code: int = 200,
                  headers: Optional[dict] = None):
        self.send_body(json.dumps(obj).encode(), "application/json",
                       code, headers)

    def send_html(self, text: str, code: int = 200):
        self.send_body(text.encode(), "text/html; charset=utf-8", code)

    def send_metrics(self):
        """The process-wide telemetry registry in Prometheus text
        exposition format (0.0.4) — the ``/metrics`` endpoint.

        HBM gauges refresh scrape-time (dl4j_hbm_live_bytes /
        dl4j_hbm_peak_bytes) so both the UIServer and the serving
        endpoint report current device memory — bench_serving
        correlates p99 latency with memory headroom from this."""
        from deeplearning4j_tpu.common.telemetry import MetricsRegistry
        try:
            from deeplearning4j_tpu.common import diagnostics
            diagnostics.update_hbm_gauges()
        except Exception:   # noqa: BLE001 — scrape must never 500 on
            pass            # a backend without memory stats
        self.send_body(MetricsRegistry.get().render_prometheus()
                       .encode(),
                       "text/plain; version=0.0.4; charset=utf-8")

    # -- chunked streaming (the :generate token stream) ----------------
    def begin_chunks(self, content_type: str, code: int = 200,
                     headers: Optional[dict] = None):
        """Open a chunked transfer-encoding response: status +
        headers now, body in :meth:`send_chunk` pieces as they become
        available (tokens as they decode), closed by
        :meth:`end_chunks`. No Content-Length — the frame IS the
        protocol."""
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Transfer-Encoding", "chunked")
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self._chunking = True

    def send_chunk(self, data: bytes):
        """One chunk frame (size line + payload), flushed immediately
        so the client sees the token the moment it decodes. Raises
        ``OSError``/``BrokenPipeError`` on client disconnect — the
        caller's signal to cancel the producing stream."""
        if not data:
            return              # a zero-size frame would end the body
        self.wfile.write(b"%X\r\n" % len(data))
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()
        self._resp_bytes += len(data)

    def end_chunks(self):
        """The terminal zero-length chunk — a well-formed end of body;
        the (HTTP/1.1 keep-alive) connection stays reusable."""
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()
        self._chunking = False

    def abort_chunks(self):
        """Terminate a chunk stream after a mid-stream handler
        exception WITHOUT the terminal chunk: the client's de-chunker
        sees a truncated body (a clean, immediate protocol error)
        instead of blocking forever on a wedged keep-alive connection.
        The socket is closed after the handler returns."""
        self.close_connection = True
        try:
            self.wfile.flush()
        except OSError:
            pass                # the client may already be gone
        self._chunking = False

    # -- requests ------------------------------------------------------
    def read_body(self) -> bytes:
        """The request body, bounded by its Content-Length frame."""
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n > 0 else b""


def start_http_server(handler_cls, port: int = 0
                      ) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """Bind ``handler_cls`` on (bind_host(), port) and serve from a
    daemon thread; port 0 picks a free port (read it back from
    ``httpd.server_address``)."""
    httpd = ThreadingHTTPServer((bind_host(), port), handler_cls)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, thread
