"""Shared stdlib-HTTP plumbing for the in-process servers.

Both network faces of the system — the training dashboard
(``ui.server.UIServer``) and the inference server
(``serving.server.InferenceServer``) — ride the same zero-dependency
``ThreadingHTTPServer`` pattern: silent request logging, explicit
Content-Length framing, JSON bodies, and the Prometheus ``/metrics``
renderer. This module is the one copy of that plumbing.

Bind host: ``DL4J_TPU_HTTP_HOST`` (default ``127.0.0.1`` — loopback
only; set ``0.0.0.0`` to expose a server beyond the host, e.g. from a
container).
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple


def bind_host() -> str:
    """The interface every server binds (env-configurable per
    process; read at ``start()`` time so tests can flip it)."""
    return os.environ.get("DL4J_TPU_HTTP_HOST", "127.0.0.1")


class QuietHandler(BaseHTTPRequestHandler):
    """BaseHTTPRequestHandler minus the stderr request log, plus the
    response/body helpers every endpoint needs."""

    #: ThreadingHTTPServer threads die with the process
    daemon_threads = True

    def log_message(self, *args):       # silence request logging
        pass

    # -- responses -----------------------------------------------------
    def send_body(self, body: bytes, content_type: str,
                  code: int = 200, headers: Optional[dict] = None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def send_json(self, obj, code: int = 200,
                  headers: Optional[dict] = None):
        self.send_body(json.dumps(obj).encode(), "application/json",
                       code, headers)

    def send_html(self, text: str, code: int = 200):
        self.send_body(text.encode(), "text/html; charset=utf-8", code)

    def send_metrics(self):
        """The process-wide telemetry registry in Prometheus text
        exposition format (0.0.4) — the ``/metrics`` endpoint.

        HBM gauges refresh scrape-time (dl4j_hbm_live_bytes /
        dl4j_hbm_peak_bytes) so both the UIServer and the serving
        endpoint report current device memory — bench_serving
        correlates p99 latency with memory headroom from this."""
        from deeplearning4j_tpu.common.telemetry import MetricsRegistry
        try:
            from deeplearning4j_tpu.common import diagnostics
            diagnostics.update_hbm_gauges()
        except Exception:   # noqa: BLE001 — scrape must never 500 on
            pass            # a backend without memory stats
        self.send_body(MetricsRegistry.get().render_prometheus()
                       .encode(),
                       "text/plain; version=0.0.4; charset=utf-8")

    # -- requests ------------------------------------------------------
    def read_body(self) -> bytes:
        """The request body, bounded by its Content-Length frame."""
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n > 0 else b""


def start_http_server(handler_cls, port: int = 0
                      ) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """Bind ``handler_cls`` on (bind_host(), port) and serve from a
    daemon thread; port 0 picks a free port (read it back from
    ``httpd.server_address``)."""
    httpd = ThreadingHTTPServer((bind_host(), port), handler_cls)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, thread
