"""Layer-level attribution observatory: per-layer time/flops/bytes.

The telemetry spine (PR 2) measures the process, diagnostics (PR 7)
the device, and the scaling observatory (PR 9) the step — but none of
them says **which layer** the headroom lives in.  This module closes
that gap on three legs:

1. **Annotation** — the fit funnels wrap every layer/vertex/op trace
   in :func:`scope`, which enters ``jax.named_scope("dl4j.<name>")``
   so the compiled HLO's per-instruction ``op_name`` metadata carries
   layer identity through forward (``jvp(dl4j.<name>)``) AND backward
   (``transpose(jvp(dl4j.<name>))``) — including the custom_vjp
   backward of the hand-written Pallas kernels, whose transpose rules
   inherit the enclosing scope.  ``scope`` also pushes onto a
   thread-local stack that :mod:`ops.kernel_select` reads at trace
   time, so every kernel-dispatch decision is attributed to the layer
   whose trace made it.  Annotations are metadata-only: steady-state
   step cost is ZERO (the context manager runs at trace time, never
   per executed step), which is how the layer rides the established
   <1% overhead budget with the gate default-ON.

2. **Static attribution** — :func:`attribute_compiled` partitions a
   compiled program's whole-model ``cost_analysis()`` flops/bytes by
   scope: the optimized HLO text is parsed per instruction (fusion
   interiors included — each fused instruction keeps its own
   metadata), an analytic cost model weighs every instruction (dot =
   2·out·k, conv = 2·out·window·Cin/g, elementwise = out elems,
   fusion boundary bytes at the call site), and the per-scope raw
   weights proportionally partition the XLA totals — so per-layer
   sums reconcile with the whole-model ``cost_analysis`` totals BY
   CONSTRUCTION (the CI gate re-checks it), while ``raw_model``
   reports the unscaled parser totals and their error vs XLA for
   honesty.

3. **Dynamic attribution** — :func:`attribute_trace` buckets
   device-op durations from a chrome trace (the PR-9
   ``ProfileCapture`` artifacts) by the same scope metadata into
   per-layer fwd/bwd milliseconds; :func:`join_dynamic` merges them
   into a static report and runs ``diagnostics.roofline`` per layer,
   so every fused-kernel claim reads "layer X moved from a% to b% of
   roof".  On CPU (where ``jax.profiler`` emits no scoped device
   ops) the bench leg falls back to sharing measured step time by the
   static roofline-time weights, marked ``time_source`` so proxy
   milliseconds are never mistaken for chip measurements.

Surfaces: ``model.layer_report()`` (MultiLayerNetwork /
ComputationGraph / Bert), ``GET /api/layers`` on the UIServer,
``dl4j_layer_seconds{layer,pass}`` + ``dl4j_layer_flops`` /
``dl4j_layer_bytes`` metrics, the ``layer_attribution`` bench block,
a ``top_layer`` field on flight-recorder step records, and the
``scripts/dl4j_layers.py`` CLI table.  Gate: ``DL4J_TPU_LAYERPROF``
(default on; ``Environment.extra["layerprof"]`` overrides, like the
kernel gates).
"""
from __future__ import annotations

import logging
import os
import re
import threading
from typing import Dict, List, Optional

import jax

from deeplearning4j_tpu.common import telemetry

log = logging.getLogger(__name__)

#: prefix all scope annotations carry inside HLO metadata
SCOPE_PREFIX = "dl4j."

#: v5e peaks, mirroring benchmarks/cost_util.py (library code must not
#: import the benchmarks package)
DEFAULT_PEAK_TFLOPS = 197.0
DEFAULT_HBM_GBPS = 819.0

_layer_seconds = telemetry.histogram(
    "dl4j_layer_seconds",
    "per-layer device time from dynamic trace attribution, by layer "
    "scope and pass (fwd/bwd) — seconds per attributed capture")
_layer_flops = telemetry.gauge(
    "dl4j_layer_flops",
    "per-layer share of the compiled step's cost-analysis flops "
    "(static scope partition; refreshed per layer_report)")
_layer_bytes = telemetry.gauge(
    "dl4j_layer_bytes",
    "per-layer share of the compiled step's cost-analysis bytes "
    "accessed (static scope partition; refreshed per layer_report)")

_tls = threading.local()
_state_lock = threading.Lock()
_last_report: Optional[dict] = None
_top_layer: Optional[str] = None
#: trace-time kernel-decision join: scope -> kernel family -> decision
_decisions: Dict[str, Dict[str, dict]] = {}


# ----------------------------------------------------------------------
# gate + annotation
def enabled() -> bool:
    """The ``DL4J_TPU_LAYERPROF`` tri-state gate (default ON);
    ``Environment.extra["layerprof"]`` overrides the env var, like the
    kernel-select gates."""
    from deeplearning4j_tpu.common.environment import Environment
    flag = Environment.get().extra.get("layerprof")
    if flag is None:
        flag = os.environ.get("DL4J_TPU_LAYERPROF")
    if flag is None or str(flag) == "":
        return True
    return str(flag) in ("1", "true", "True", "yes")


class _NullScope:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullScope()

_SAFE_RE = re.compile(r"[^0-9A-Za-z_.]")


def sanitize(name: str) -> str:
    """Scope names must survive the HLO metadata round-trip: restrict
    to the characters the attribution regex can re-extract."""
    return _SAFE_RE.sub("_", str(name)) or "_"


class _Scope:
    """Trace-time layer annotation: pushes the name onto jax's name
    stack (HLO metadata) AND a thread-local stack (the kernel-select
    join).  Runs only while a program is being traced — never on the
    executed step path."""

    __slots__ = ("name", "_ns")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self.name)
        self._ns = jax.named_scope(SCOPE_PREFIX + self.name)
        self._ns.__enter__()
        return self

    def __exit__(self, *exc):
        try:
            return self._ns.__exit__(*exc)
        finally:
            _tls.stack.pop()


def scope(name: str):
    """Annotate the with-block as layer ``name`` (sanitized).  A
    no-op context when the gate is off."""
    if not enabled():
        return _NULL
    return _Scope(sanitize(name))


def current_scope() -> Optional[str]:
    """The innermost active :func:`scope` name on this thread (trace
    time only), or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


# ----------------------------------------------------------------------
# kernel-decision join (fed by ops.kernel_select.select at trace time)
def note_selection(selection) -> None:
    """Record a :class:`ops.kernel_select.Selection` against the layer
    scope whose trace made it."""
    sc = current_scope() or "_unscoped"
    with _state_lock:
        per = _decisions.setdefault(sc, {})
        prev = per.get(selection.kernel)
        if prev is None:
            per[selection.kernel] = {
                "kernel": selection.kernel,
                "fused": bool(selection.fused),
                "decision": selection.decision,
                "reason": selection.reason,
                "sites": 1,
            }
        else:
            prev.update(fused=bool(selection.fused),
                        decision=selection.decision,
                        reason=selection.reason)
            prev["sites"] += 1


def kernel_decisions(scope_name: Optional[str] = None) -> dict:
    """The recorded trace-time decisions: for one scope (``{kernel:
    decision}``) or all scopes when ``scope_name`` is None."""
    with _state_lock:
        if scope_name is not None:
            return {k: dict(v)
                    for k, v in _decisions.get(scope_name, {}).items()}
        return {s: {k: dict(v) for k, v in per.items()}
                for s, per in _decisions.items()}


def reset_decisions() -> None:
    with _state_lock:
        _decisions.clear()


# ----------------------------------------------------------------------
# HLO parsing: per-instruction analytic cost model keyed by scope
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_HEAD_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_CALL_RE = re.compile(r"^(.*?)\s+([a-z][a-z0-9\-]*)\(")
_META_RE = re.compile(r'metadata=\{[^{}]*?op_name="([^"]*)"')
_SCOPE_META_RE = re.compile(r"dl4j\.([0-9A-Za-z_.]*[0-9A-Za-z_])")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")
_CDIMS_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")
_WINDOW_RE = re.compile(r"window=\{[^}]*size=([0-9x]+)")
_GROUPS_RE = re.compile(r"feature_group_count=([0-9]+)")
_DIMLABELS_RE = re.compile(r"dim_labels=([a-z0-9?]+)_([a-z0-9?]+)->")

#: ~1 flop per output element (the HloCostAnalysis convention for
#: simple elementwise math; comparisons/selects/copies count zero)
_ELEMENTWISE_FLOP = frozenset((
    "add", "subtract", "multiply", "divide", "remainder", "maximum",
    "minimum", "negate", "abs", "sign", "floor", "ceil",
    "round-nearest-afz", "round-nearest-even", "clamp",
))
_TRANSCENDENTAL = frozenset((
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "logistic", "tanh", "rsqrt", "sqrt", "cbrt", "power", "sine",
    "cosine", "tan", "atan2", "erf",
))
#: never materialized / free at runtime: no byte traffic of their own
_FREE_BYTES = frozenset((
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "partition-id", "replica-id", "iota",
    # control-flow shells: the work lives in the called computations
    "while", "conditional", "call",
))


def _shape_cost(text: str):
    """(elements, bytes) summed over every ``dtype[dims]`` shape token
    in ``text`` (tuple types contribute every component)."""
    elems = byts = 0
    for dt, dims in _SHAPE_RE.findall(text):
        unit = _DTYPE_BYTES.get(dt)
        if unit is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        elems += n
        byts += n * unit
    return elems, byts


def _shape_dims(text: str) -> Optional[List[int]]:
    """Dims of the FIRST shape token in ``text`` (an operand's array
    shape), or None."""
    m = _SHAPE_RE.search(text)
    if not m or m.group(1) not in _DTYPE_BYTES:
        return None
    return [int(d) for d in m.group(2).split(",") if d]


def _split_call(rest: str):
    """``rest`` starts at the call '('; returns (args, attrs) with
    balanced-paren scanning (metadata op_names contain parens, so a
    greedy regex would mis-split)."""
    depth = 0
    for j, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[1:j], rest[j + 1:]
    return rest[1:], ""


def _operand_bytes(args: str, symtab: Dict[str, tuple],
                   index: int) -> float:
    """Byte size of the ``index``-th operand of a call, from its
    inline shape when present or the computation symbol table."""
    toks, depth, start = [], 0, 0
    for j, ch in enumerate(args):
        if ch in "[{(":
            depth += 1
        elif ch in "]})":
            depth -= 1
        elif ch == "," and depth == 0:
            toks.append(args[start:j])
            start = j + 1
    toks.append(args[start:])
    tok = toks[index] if index < len(toks) else ""
    b = _shape_cost(tok)[1]
    if b:
        return float(b)
    m = _OPERAND_RE.search(tok)
    if m:
        ent = symtab.get(m.group(1))
        if ent:
            return float(ent[1])
    return 0.0


class _ScopeCost:
    __slots__ = ("flops_fwd", "flops_bwd", "bytes_fwd", "bytes_bwd",
                 "transcendentals")

    def __init__(self):
        self.flops_fwd = self.flops_bwd = 0.0
        self.bytes_fwd = self.bytes_bwd = 0.0
        self.transcendentals = 0.0


def _conv_flops(out_elems, args, attrs, symtab):
    """2 · out · window · Cin/groups — window from the textual window
    spec, Cin from dim_labels against the lhs operand shape."""
    win = 1
    m = _WINDOW_RE.search(attrs)
    if m:
        for d in m.group(1).split("x"):
            win *= int(d)
    groups = 1
    m = _GROUPS_RE.search(attrs)
    if m:
        groups = max(int(m.group(1)), 1)
    lhs_dims = _shape_dims(args)
    if lhs_dims is None:
        first = _OPERAND_RE.search(args)
        if first:
            lhs_dims = symtab.get(first.group(1), (None,))[0]
    in_feat = None
    m = _DIMLABELS_RE.search(attrs)
    if m and lhs_dims:
        fpos = m.group(1).find("f")
        if 0 <= fpos < len(lhs_dims):
            in_feat = lhs_dims[fpos]
    if in_feat is None and lhs_dims:
        in_feat = lhs_dims[-1]
    return 2.0 * out_elems * win * (in_feat or 1) / groups


def _dot_flops(out_elems, args, attrs, symtab):
    """2 · out · k, k = product of the lhs contracting dims."""
    m = _CDIMS_RE.search(attrs)
    cdims = [int(d) for d in m.group(1).split(",") if d] if m else []
    lhs_dims = _shape_dims(args)
    if lhs_dims is None:
        first = _OPERAND_RE.search(args)
        if first:
            lhs_dims = symtab.get(first.group(1), (None,))[0]
    k = 1
    if lhs_dims:
        for d in cdims:
            if d < len(lhs_dims):
                k *= lhs_dims[d]
    return 2.0 * out_elems * max(k, 1)


_COMP_HEAD_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(")
_CALLS_REF_RE = re.compile(r"calls=%([\w.\-]+)")
_WHILE_REF_RE = re.compile(
    r"condition=%([\w.\-]+), body=%([\w.\-]+)")
_TO_APPLY_RE = re.compile(r"to_apply=%([\w.\-]+)")
_CONST_INT_RE = re.compile(r"constant\((\d+)\)")


def _comp_roles(hlo_text: str):
    """First pass over the HLO text: classify every computation by HOW
    it is called, since names alone lie (``region_*`` is both a
    scalar reduce applier — skip, its work is counted at the applying
    instruction — and a ``lax.scan`` while body — count, multiplied
    by the loop trip count).

    Returns ``{comp_name: execution-count multiplier}``: 0 for
    appliers/conditions, the trip count (times the parent's
    multiplier) for while bodies, the parent's multiplier for fusion
    interiors, 1 for ENTRY.  Trip counts come from the canonical cond
    pattern ``compare(counter, constant(N)), direction=LT``.

    Also computes per-fused-computation boundary bytes honestly:
    a parameter consumed only through ``dynamic-slice`` contributes
    the slice window, not the whole buffer (CPU scatter/sort loops
    index one row of a big table per trip), and a computation rooted
    at a ``dynamic-update-slice`` (in-placed by XLA) contributes the
    updated window instead of its full result."""
    parent: Dict[str, tuple] = {}   # comp -> (kind, parent_comp, trip)
    cond_trip: Dict[str, int] = {}
    body_cond: Dict[str, str] = {}  # while body -> its paired cond
    dus_root: Dict[str, float] = {}
    fusion_io: Dict[str, float] = {}  # comp -> touched parameter bytes
    entry = None
    current = None
    cur_const = None
    par_bytes: Dict[str, float] = {}
    par_slice: Dict[str, float] = {}
    par_full: set = set()

    def _finish_comp():
        if current is None:
            return
        touched = 0.0
        for pname, full in par_bytes.items():
            if pname in par_full or pname not in par_slice:
                touched += full
            else:
                touched += par_slice[pname]
        fusion_io[current] = touched

    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and ("->" in stripped
                                       or stripped.startswith("ENTRY")):
            _finish_comp()
            m = _COMP_HEAD_RE.match(stripped)
            current = m.group(1) if m else None
            if stripped.startswith("ENTRY"):
                entry = current
            cur_const = None
            par_bytes, par_slice, par_full = {}, {}, set()
            continue
        m = _CONST_INT_RE.search(stripped)
        if m:
            cur_const = int(m.group(1))
        if "direction=LT" in stripped and current is not None and \
                cur_const is not None:
            cond_trip[current] = cur_const
        m = _WHILE_REF_RE.search(stripped)
        if m:
            parent.setdefault(m.group(1), ("cond", current, 0))
            parent.setdefault(m.group(2), ("body", current, 0))
            body_cond.setdefault(m.group(2), m.group(1))
        for name in _CALLS_REF_RE.findall(stripped):
            parent.setdefault(name, ("fusion", current, 0))
        for name in _TO_APPLY_RE.findall(stripped):
            parent.setdefault(name, ("applier", current, 0))

        hm = _HEAD_RE.match(stripped)
        call = _CALL_RE.match(hm.group(2)) if hm else None
        if not call:
            continue
        rtype, opcode = call.group(1), call.group(2)
        argstr, _ = _split_call(hm.group(2)[call.end() - 1:])
        if opcode == "parameter":
            par_bytes[hm.group(1)] = float(_shape_cost(rtype)[1])
            continue
        operands = _OPERAND_RE.findall(argstr)
        if opcode == "dynamic-update-slice" and \
                stripped.startswith("ROOT") and current is not None:
            ub = _operand_bytes(argstr, {}, 1)
            if ub:
                dus_root[current] = 2.0 * ub
            # the in-placed buffer (operand 0) is not copied: its
            # traffic is the window, already in dus_root
            for opn in operands[1:]:
                if opn in par_bytes:
                    par_full.add(opn)
            if operands and operands[0] in par_bytes:
                par_slice.setdefault(operands[0], 0.0)
            continue
        out_b = float(_shape_cost(rtype)[1])
        for j, opn in enumerate(operands):
            if opn not in par_bytes:
                continue
            if opcode == "dynamic-slice" and j == 0:
                par_slice[opn] = par_slice.get(opn, 0.0) + out_b
            else:
                par_full.add(opn)
    _finish_comp()

    mult: Dict[str, float] = {}

    def resolve(comp, depth=0):
        if comp in mult:
            return mult[comp]
        if comp == entry or comp not in parent or depth > 16:
            mult[comp] = 1.0
            return 1.0
        kind, par, _ = parent[comp]
        if kind in ("cond", "applier"):
            m = 0.0
        elif kind == "body":
            # trip from this body's paired cond (same while line);
            # fall back to 1 when the cond isn't the canonical
            # counter < constant pattern
            trip = cond_trip.get(body_cond.get(comp, ""), 1)
            m = trip * resolve(par, depth + 1)
        else:
            m = resolve(par, depth + 1)
        mult[comp] = m
        return m

    return parent, cond_trip, entry, resolve, dus_root, fusion_io


def parse_hlo(hlo_text: str) -> Dict[str, _ScopeCost]:
    """Walk the optimized-HLO text and accumulate the analytic cost
    model per ``dl4j.<scope>`` (``_unattributed`` collects un-scoped
    instructions).  Fusion interiors contribute flops under their own
    per-instruction metadata; the fusion call site contributes the
    boundary bytes under the fusion's (root) metadata.  While bodies
    (``lax.scan`` layers) are weighted by their loop trip count;
    reduce/scatter appliers and loop conditions are skipped — their
    work is counted at the applying instruction."""
    (parent, cond_trip, entry, resolve, dus_root,
     fusion_io) = _comp_roles(hlo_text)
    out: Dict[str, _ScopeCost] = {}
    in_fused = False
    factor = 1.0
    # per-computation symbol table: name -> (dims, bytes) — names are
    # only unique within a computation (every fused computation has a
    # %param_0)
    symtab: Dict[str, tuple] = {}
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and ("->" in stripped
                                       or stripped.startswith("ENTRY")):
            m = _COMP_HEAD_RE.match(stripped)
            comp = m.group(1) if m else None
            kind = parent.get(comp, (None,))[0]
            in_fused = kind == "fusion"
            factor = resolve(comp) if comp is not None else 1.0
            symtab = {}
            continue
        if factor == 0.0:
            continue
        m = _HEAD_RE.match(line)
        if not m:
            continue
        name, rhs = m.group(1), m.group(2)
        call = _CALL_RE.match(rhs)
        if not call:
            continue
        result_type, opcode = call.group(1), call.group(2)
        args, attrs = _split_call(rhs[call.end() - 1:])
        out_elems, out_bytes = _shape_cost(result_type)
        symtab[name] = (_shape_dims(result_type), out_bytes)
        meta = _META_RE.search(attrs)
        op_name = meta.group(1) if meta else ""
        sm = _SCOPE_META_RE.search(op_name)
        scope_name = sm.group(1) if sm else "_unattributed"
        is_bwd = "transpose(" in op_name
        cost = out.get(scope_name)
        if cost is None:
            cost = out[scope_name] = _ScopeCost()

        flops = 0.0
        if opcode == "dot":
            flops = _dot_flops(out_elems, args, attrs, symtab)
        elif opcode == "convolution":
            flops = _conv_flops(out_elems, args, attrs, symtab)
        elif opcode in ("reduce", "reduce-window"):
            in_elems = _shape_cost(args)[0]
            if in_elems == 0:
                first = _OPERAND_RE.search(args)
                if first:
                    dims = symtab.get(first.group(1), (None,))[0]
                    if dims:
                        in_elems = 1
                        for d in dims:
                            in_elems *= d
            flops = float(max(in_elems, out_elems))
        elif opcode in _ELEMENTWISE_FLOP:
            flops = float(out_elems)
        elif opcode in _TRANSCENDENTAL:
            cost.transcendentals += float(out_elems) * factor
        if is_bwd:
            cost.flops_bwd += flops * factor
        else:
            cost.flops_fwd += flops * factor

        if in_fused or opcode in _FREE_BYTES:
            continue
        if opcode == "dynamic-update-slice":
            # only the updated window is touched (read update + write
            # region), not the full buffer — charging result+operands
            # would overcount scan carries by the carry size per step
            upd = _operand_bytes(args, symtab, index=1)
            op_bytes = 2.0 * (upd if upd else float(out_bytes))
        elif opcode == "dynamic-slice":
            op_bytes = 2.0 * float(out_bytes)   # read + write the slice
        elif opcode == "fusion":
            called = _CALLS_REF_RE.search(attrs)
            tgt = called.group(1) if called else None
            if tgt in fusion_io:
                # boundary bytes from the interior's actual access
                # pattern: dynamic-sliced params count their window,
                # a DUS root counts the updated window, not the full
                # in-placed buffer
                op_bytes = fusion_io[tgt] + (
                    dus_root[tgt] if tgt in dus_root
                    else float(out_bytes))
                if is_bwd:
                    cost.bytes_bwd += op_bytes * factor
                else:
                    cost.bytes_fwd += op_bytes * factor
                continue
            op_bytes = float(out_bytes)
            inline_b = _shape_cost(args)[1]
            if inline_b:
                op_bytes += inline_b
            else:
                for opn in _OPERAND_RE.findall(args):
                    ent = symtab.get(opn)
                    if ent:
                        op_bytes += ent[1]
        else:
            op_bytes = float(out_bytes)
            inline_b = _shape_cost(args)[1]
            if inline_b:
                op_bytes += inline_b
            else:
                for opn in _OPERAND_RE.findall(args):
                    ent = symtab.get(opn)
                    if ent:
                        op_bytes += ent[1]
        if is_bwd:
            cost.bytes_bwd += op_bytes * factor
        else:
            cost.bytes_fwd += op_bytes * factor
    return out


# ----------------------------------------------------------------------
# static attribution: partition cost_analysis totals by scope
def attribute_compiled(compiled, *, model_name: Optional[str] = None,
                       layer_types: Optional[dict] = None,
                       peak_tflops: Optional[float] = None,
                       peak_hbm_gbps: Optional[float] = None) -> dict:
    """Partition ``compiled.cost_analysis()`` flops/bytes by layer
    scope (see module docstring).  Returns the layer report and
    publishes it as the module's last report (``/api/layers``,
    ``top_layer``, the ``dl4j_layer_*`` gauges)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    total_flops = float(ca.get("flops", 0.0) or 0.0)
    total_bytes = float(ca.get("bytes accessed", 0.0) or 0.0)
    raw = parse_hlo(compiled.as_text())

    raw_flops = sum(c.flops_fwd + c.flops_bwd for c in raw.values())
    raw_bytes = sum(c.bytes_fwd + c.bytes_bwd for c in raw.values())
    sf = (total_flops / raw_flops) if raw_flops else 0.0
    sb = (total_bytes / raw_bytes) if raw_bytes else 0.0

    peak_tf = peak_tflops or DEFAULT_PEAK_TFLOPS
    peak_bw = peak_hbm_gbps or DEFAULT_HBM_GBPS
    ridge = peak_tf * 1e12 / (peak_bw * 1e9)

    layers = {}
    attr_flops = attr_bytes = 0.0
    for name, c in raw.items():
        f_fwd, f_bwd = c.flops_fwd * sf, c.flops_bwd * sf
        b_fwd, b_bwd = c.bytes_fwd * sb, c.bytes_bwd * sb
        flops, byts = f_fwd + f_bwd, b_fwd + b_bwd
        ai = flops / max(byts, 1.0)
        est_s = max(flops / (peak_tf * 1e12), byts / (peak_bw * 1e9))
        ent = {
            "flops": round(flops),
            "bytes": round(byts),
            "flops_fwd": round(f_fwd), "flops_bwd": round(f_bwd),
            "bytes_fwd": round(b_fwd), "bytes_bwd": round(b_bwd),
            "share_flops": round(flops / total_flops, 4)
            if total_flops else 0.0,
            "share_bytes": round(byts / total_bytes, 4)
            if total_bytes else 0.0,
            "arithmetic_intensity": round(ai, 2),
            "bound": "compute" if ai >= ridge else "hbm",
            "est_ms": round(est_s * 1e3, 7),
        }
        if layer_types and name in layer_types:
            ent["type"] = layer_types[name]
        kd = kernel_decisions(name)
        if kd:
            ent["kernel"] = kd
        if name != "_unattributed":
            attr_flops += flops
            attr_bytes += byts
        layers[name] = ent

    # display/report order: heaviest first (ISSUE: "top-k by time")
    layers = dict(sorted(
        layers.items(),
        key=lambda kv: kv[1]["est_ms"], reverse=True))

    report = {
        "model": model_name,
        "peaks": {"tflops": peak_tf, "hbm_gbps": peak_bw},
        "totals": {
            "flops": total_flops,
            "bytes": total_bytes,
            "transcendentals": float(
                ca.get("transcendentals", 0.0) or 0.0),
        },
        "raw_model": {
            "flops": round(raw_flops),
            "bytes": round(raw_bytes),
            "flops_err_pct": round(
                100.0 * (raw_flops - total_flops)
                / total_flops, 1) if total_flops else None,
            "bytes_err_pct": round(
                100.0 * (raw_bytes - total_bytes)
                / total_bytes, 1) if total_bytes else None,
            # positive err is expected on scan models: the analytic
            # model weighs while bodies by their trip count (executed
            # work), XLA's cost_analysis counts loop bodies once
            "loop_semantics": "executed-trips",
        },
        "coverage": {
            "flops": round(attr_flops / total_flops, 4)
            if total_flops else 0.0,
            "bytes": round(attr_bytes / total_bytes, 4)
            if total_bytes else 0.0,
        },
        "time_source": "static_roofline_model",
        "layers": layers,
    }
    _publish(report)
    return report


def reconcile_error_pct(report: dict) -> float:
    """Max relative error (percent) between the per-layer sums and the
    whole-model totals — the CI conformance gate's number.  ~0 by
    construction; a parser regression shows up here."""
    worst = 0.0
    for key in ("flops", "bytes"):
        total = report["totals"][key]
        if not total:
            continue
        got = sum(ent[key] for ent in report["layers"].values())
        worst = max(worst, abs(got - total) / total * 100.0)
    return worst


# ----------------------------------------------------------------------
# dynamic attribution: trace events -> per-layer fwd/bwd milliseconds
def attribute_trace(events) -> Dict[str, dict]:
    """Bucket chrome-trace complete events carrying ``dl4j.<scope>``
    metadata (event name or args) into per-scope
    ``{"fwd_ms", "bwd_ms"}``; observes ``dl4j_layer_seconds``."""
    out: Dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        hay = str(ev.get("name", ""))
        args = ev.get("args")
        if isinstance(args, dict):
            for v in args.values():
                if isinstance(v, str) and "dl4j." in v:
                    hay = hay + " " + v
        m = _SCOPE_META_RE.search(hay)
        if not m:
            continue
        p = "bwd" if "transpose(" in hay else "fwd"
        d = out.setdefault(m.group(1), {"fwd_ms": 0.0, "bwd_ms": 0.0})
        d[p + "_ms"] += float(ev.get("dur", 0) or 0) / 1e3
    for scope_name, d in out.items():
        for p in ("fwd", "bwd"):
            if d[p + "_ms"]:
                _layer_seconds.observe(
                    d[p + "_ms"] / 1e3,
                    **{"layer": scope_name, "pass": p})
    return out


def attribute_trace_file(path: str) -> Dict[str, dict]:
    """:func:`attribute_trace` over a chrome-trace file (a
    ``ProfileCapture`` artifact; ``.gz`` handled)."""
    from deeplearning4j_tpu.common.telemetry import _load_trace
    return attribute_trace(_load_trace(path).get("traceEvents", []))


def join_dynamic(report: dict, layer_ms: Dict[str, dict],
                 time_source: str = "trace") -> dict:
    """Merge measured per-layer milliseconds into a static report and
    re-run the roofline per layer against the measured time — the
    join that turns "kernel X fused" into "layer X moved from a% to
    b% of roof"."""
    from deeplearning4j_tpu.common import diagnostics
    peaks = report.get("peaks", {})
    for name, ent in report["layers"].items():
        ms = layer_ms.get(name)
        if not ms:
            continue
        ent["fwd_ms"] = round(ms.get("fwd_ms", 0.0), 4)
        ent["bwd_ms"] = round(ms.get("bwd_ms", 0.0), 4)
        total_s = (ent["fwd_ms"] + ent["bwd_ms"]) / 1e3
        if total_s > 0:
            rl = diagnostics.roofline(
                ent["flops"], ent["bytes"], total_s,
                peak_tflops=peaks.get("tflops"),
                peak_hbm_gbps=peaks.get("hbm_gbps"))
            ent["pct_of_roof"] = rl.get("pct_of_roof")
            ent["tflops"] = rl.get("tflops")
    report["time_source"] = time_source
    _publish(report)
    return report


def share_step_time(report: dict, step_ms: float,
                    time_source: str = "static_share_proxy"
                    ) -> Dict[str, dict]:
    """CPU-proxy fallback: split a measured whole-step wall time into
    per-layer fwd/bwd milliseconds by the static roofline-time
    weights.  Honest about what it is (``time_source`` marks it) —
    the chip path uses :func:`attribute_trace` on real device ops."""
    layers = report["layers"]
    est_total = sum(e["est_ms"] for e in layers.values()) or 1.0
    out = {}
    for name, ent in layers.items():
        ms = step_ms * ent["est_ms"] / est_total
        denom = max(ent["flops_fwd"] + ent["flops_bwd"]
                    + ent["bytes_fwd"] + ent["bytes_bwd"], 1.0)
        fwd_w = (ent["flops_fwd"] + ent["bytes_fwd"]) / denom
        out[name] = {"fwd_ms": ms * fwd_w, "bwd_ms": ms * (1 - fwd_w)}
    join_dynamic(report, out, time_source=time_source)
    return out


# ----------------------------------------------------------------------
# module report state (UIServer / flight recorder / CLI read this)
def _publish(report: dict) -> None:
    global _last_report, _top_layer
    top = None
    best = -1.0
    for name, ent in report["layers"].items():
        if name == "_unattributed":
            continue
        t = ent.get("fwd_ms", 0.0) + ent.get("bwd_ms", 0.0) \
            or ent.get("est_ms", 0.0)
        if t > best:
            best, top = t, name
        _layer_flops.set(ent["flops"], layer=name)
        _layer_bytes.set(ent["bytes"], layer=name)
    with _state_lock:
        _last_report = report
        _top_layer = top


def last_report() -> Optional[dict]:
    """The most recent layer report computed in this process."""
    with _state_lock:
        return _last_report


def top_layer() -> Optional[str]:
    """The heaviest layer of the last report (measured time when the
    dynamic join ran, else the static roofline-time estimate) — the
    flight recorder stamps this onto every step record."""
    return _top_layer


def reset() -> None:
    """Test hook: clear report state and the decision join."""
    global _last_report, _top_layer
    with _state_lock:
        _last_report = None
        _top_layer = None
        _decisions.clear()
