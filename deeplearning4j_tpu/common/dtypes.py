"""Data-type system.

Reference parity: nd4j's ``org.nd4j.linalg.api.buffer.DataType`` (the dtype
enum used across INDArray / ops / serialization). TPU-first notes: BFLOAT16
is a first-class training dtype here (the MXU's native input type), where the
reference treated HALF as the reduced-precision citizen.
"""
from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    """Mirrors the reference dtype enum, mapped onto jnp dtypes."""

    DOUBLE = "float64"
    FLOAT = "float32"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    LONG = "int64"
    INT = "int32"
    SHORT = "int16"
    BYTE = "int8"
    UBYTE = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    BOOL = "bool"
    UTF8 = "utf8"  # not a tensor dtype on TPU; kept for API parity

    # ------------------------------------------------------------------
    @property
    def jnp(self):
        if self is DataType.UTF8:
            raise TypeError("UTF8 is not a numeric dtype")
        return jnp.dtype(self.value)

    @property
    def np(self):
        if self is DataType.UTF8:
            return np.dtype(object)
        return np.dtype(self.value)

    def is_fp(self) -> bool:
        return self in (DataType.DOUBLE, DataType.FLOAT, DataType.HALF,
                        DataType.BFLOAT16)

    def is_int(self) -> bool:
        return self in (DataType.LONG, DataType.INT, DataType.SHORT,
                        DataType.BYTE, DataType.UBYTE, DataType.UINT16,
                        DataType.UINT32, DataType.UINT64)

    def width(self) -> int:
        """Bytes per element."""
        if self is DataType.UTF8:
            return 0
        return self.np.itemsize

    # ------------------------------------------------------------------
    @staticmethod
    def from_any(x) -> "DataType":
        if isinstance(x, DataType):
            return x
        if isinstance(x, str):
            try:
                return DataType[x.upper()]
            except KeyError:
                pass
            x = np.dtype(x)
        d = np.dtype(jnp.dtype(x).name) if not isinstance(x, np.dtype) else x
        for dt in DataType:
            if dt is not DataType.UTF8 and dt.np == d:
                return dt
        raise ValueError(f"No DataType for {x!r}")


def to_jnp_dtype(x):
    """Coerce DataType | str | np/jnp dtype to a jnp dtype."""
    if isinstance(x, DataType):
        return x.jnp
    return jnp.dtype(x)


def cast_floats(tree, dtype):
    """Cast every floating-point array leaf of a pytree to ``dtype``
    (ints/bools untouched) — the mixed-precision entry cast: master
    params stay float32, the forward runs in (usually) bfloat16, and
    the cast's transpose returns float32 gradients."""
    import jax

    def c(a):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype,
                                                  jnp.floating):
            return a.astype(dtype)
        return a
    return jax.tree_util.tree_map(c, tree)
