"""Shared scaffold for sqrt(N) activation checkpointing.

Three training walks use the same segmentation scheme — the
MultiLayerNetwork layer stack, the ComputationGraph topo walk, and
the SameDiff op walk: cut the walk into contiguous segments, wrap
every segment EXCEPT the last (it holds the loss head — nothing to
save past it) in ``jax.checkpoint``, so only segment-boundary values
are stored for backward. This module is the single source of truth
for the cut points and the wrap policy so the three walks cannot
drift."""
from __future__ import annotations

from typing import List, Tuple

import numpy as np


def segment_plan(n_items: int, n_segments: int
                 ) -> List[Tuple[int, int, bool]]:
    """``[(lo, hi, wrap), ...]`` covering ``range(n_items)`` in
    ``min(n_segments, n_items)`` contiguous segments; ``wrap`` is
    True for every segment but the last. ``n_segments`` above the
    item count clamps to per-item checkpointing."""
    n_seg = min(int(n_segments), int(n_items))
    bounds = np.linspace(0, n_items, n_seg + 1).astype(int)
    return [(int(bounds[i]), int(bounds[i + 1]), i + 1 < n_seg)
            for i in range(n_seg)]


def min_cut_segment_plan(n_items: int, n_segments: int,
                         cut_cost) -> List[Tuple[int, int, bool]]:
    """``segment_plan`` with boundary placement by liveness: instead
    of fixed even indices, each interior boundary lands on the
    LOWEST-``cut_cost`` index within a window around its even
    position (ties break toward the even cut). ``cut_cost[c]`` is
    the cost of cutting before walk item ``c`` — e.g. the number of
    live values that would have to be stored across that boundary.

    Why: a flat imported transformer has ~hundreds of ops per layer;
    even cuts land mid-attention where q/k/v/scores (O(t^2)) are all
    live and must be SAVED, which is precisely what checkpointing
    exists to avoid. Layer boundaries — where only the hidden state
    crosses — are liveness minima, and this plan finds them without
    knowing what a "layer" is."""
    base = segment_plan(n_items, n_segments)   # even skeleton + wrap
    n_seg = len(base)
    if n_seg <= 1:
        return base
    cost = np.asarray(cut_cost, dtype=np.float64)
    even = [lo for lo, _, _ in base] + [n_items]
    spacing = n_items / n_seg
    half = max(1, int(spacing // 2) - 1)
    bounds = [0]
    for k in range(1, n_seg):
        center = int(even[k])
        lo = max(bounds[-1] + 1, center - half)
        hi = min(n_items - (n_seg - k), center + half)
        cands = range(lo, hi + 1)
        best = min(cands,
                   key=lambda c: (cost[c], abs(c - center)))
        bounds.append(int(best))
    bounds.append(n_items)
    return [(bounds[i], bounds[i + 1], base[i][2])
            for i in range(n_seg)]
