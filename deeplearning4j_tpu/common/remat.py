"""Shared scaffold for sqrt(N) activation checkpointing.

Three training walks use the same segmentation scheme — the
MultiLayerNetwork layer stack, the ComputationGraph topo walk, and
the SameDiff op walk: cut the walk into contiguous segments, wrap
every segment EXCEPT the last (it holds the loss head — nothing to
save past it) in ``jax.checkpoint``, so only segment-boundary values
are stored for backward. This module is the single source of truth
for the cut points and the wrap policy so the three walks cannot
drift."""
from __future__ import annotations

from typing import List, Tuple

import numpy as np


def segment_plan(n_items: int, n_segments: int
                 ) -> List[Tuple[int, int, bool]]:
    """``[(lo, hi, wrap), ...]`` covering ``range(n_items)`` in
    ``min(n_segments, n_items)`` contiguous segments; ``wrap`` is
    True for every segment but the last. ``n_segments`` above the
    item count clamps to per-item checkpointing."""
    n_seg = min(int(n_segments), int(n_items))
    bounds = np.linspace(0, n_items, n_seg + 1).astype(int)
    return [(int(bounds[i]), int(bounds[i + 1]), i + 1 < n_seg)
            for i in range(n_seg)]
