"""Training diagnostics: device-level attribution on the telemetry spine.

PR 2's spine (``common.telemetry``) instruments host paths — queues,
steps, caches.  This layer pushes observability down to the device, in
the cost-attribution spirit of Xu et al. (PAPERS.md 2004.13336: the
XLA memory/collective accounting that steered the sharded-update work)
and TVM's measure-then-tune loop (PAPERS.md 1802.04799).  Four pieces:

- **HBM accounting** — :func:`update_hbm_gauges` reads jax device
  memory stats into ``dl4j_hbm_live_bytes`` / ``dl4j_hbm_peak_bytes``
  gauges; :func:`memory_report` adds per-buffer attribution (params /
  updater state / model states / prefetch staging / an activations+
  workspace residual) for every model the fit funnels have touched.
  Exported on ``/api/memory`` (UIServer), refreshed on every
  ``/metrics`` scrape (UIServer AND the serving ``InferenceServer``),
  and landed in ``bench.py`` JSON as the ``memory`` block.
- **Per-collective tracing** — :func:`collective_span` generalizes the
  ``dp.update_exchange`` span pattern: one context manager that emits
  a ``collective.<kind>`` chrome-trace span plus
  ``dl4j_collective_seconds{kind,axis}`` /
  ``dl4j_collective_bytes_total{kind,axis}``.  Used by
  ``parallel.wrapper`` (update exchange), ``parallel.zero`` (sharded
  state placement) and ``parallel.sharedtraining`` (global batch
  assembly).
- **Numerics watchdog** — opt-in (``DL4J_TPU_NUMERICS_WATCHDOG=1``),
  sampled (``DL4J_TPU_NUMERICS_SAMPLE=N``) non-finite check on the
  loss and the in-step global grad norm inside the fit funnels.  A
  trip raises a structured :class:`NumericsEvent` carrying the step,
  tensor group, and the first bad leaf — located by a cheap per-dtype
  flat-segment scan reusing ``learning.updaters.DpFlatSpec`` — instead
  of silently training on NaNs.
- **Flight recorder** — :class:`FlightRecorder`, a bounded ring of
  per-step records (step time, loss, grad norm, retrace count,
  collective bytes, HBM gauges) that dumps a JSONL artifact plus a
  chrome trace of the last window on crash (sys.excepthook), on
  SIGTERM (the preemption signal), or on a watchdog trip — the black
  box elastic training (ROADMAP item 5) debugs from.

Gates (``common.environment``): ``DL4J_TPU_FLIGHT_RECORDER`` (default
on), ``DL4J_TPU_FLIGHT_RECORDER_STEPS``/``_DIR``,
``DL4J_TPU_NUMERICS_WATCHDOG`` (default off),
``DL4J_TPU_HBM_SAMPLE_STEPS``.  The whole layer shares PR 2's <1%
step-overhead budget — ``benchmarks/bench_telemetry.py`` has the
diagnostics leg that measures it.
"""
from __future__ import annotations

import json
import logging
import math
import os
import signal
import sys
import threading
import time
import weakref
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.common import stepstats, telemetry
from deeplearning4j_tpu.common.environment import Environment

log = logging.getLogger("deeplearning4j_tpu")

#: flight-recorder / memory-report schema version, stamped into every
#: artifact and the bench.py ``meta`` block
SCHEMA_VERSION = 1


# ----------------------------------------------------------------------
# HBM accounting
def _tree_bytes(tree) -> int:
    """Total buffer bytes of a pytree (global logical bytes — a
    replicated array counts once, matching how dp_ravel sizes it)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            total += int(np.prod(leaf.shape, dtype=np.int64) *
                         np.dtype(leaf.dtype).itemsize)
    return total


def _leaf_resident_bytes(leaf) -> int:
    """Bytes of one leaf actually resident on a single device.  For a
    replicated array this equals the full logical bytes; for a
    dp-sharded flat (ZeRO-1 state, fsdp params) it is the 1/N shard
    the device really holds — which is what an HBM budget cares
    about."""
    if not (hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
        return 0
    itemsize = np.dtype(leaf.dtype).itemsize
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            shard_shape = sharding.shard_shape(leaf.shape)
            return int(np.prod(shard_shape, dtype=np.int64) * itemsize)
        except Exception:       # noqa: BLE001 — exotic sharding types
            pass
    return int(np.prod(leaf.shape, dtype=np.int64) * itemsize)


def _tree_resident_bytes(tree) -> int:
    """Per-device resident bytes of a pytree (sharding-aware: a
    dp-sharded leaf counts its shard, a replicated leaf its full
    size)."""
    import jax
    return sum(_leaf_resident_bytes(leaf)
               for leaf in jax.tree_util.tree_leaves(tree))


def device_memory_stats() -> List[dict]:
    """Per-device allocator stats from jax (``device.memory_stats()``).
    Empty on backends that expose none (CPU)."""
    import jax
    out = []
    for d in jax.devices():
        try:
            st = d.memory_stats()
        except Exception:           # noqa: BLE001 — backend-dependent
            st = None
        if not st:
            continue
        out.append({
            "id": int(d.id),
            "kind": str(getattr(d, "device_kind", d.platform)),
            "bytes_in_use": int(st.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(st.get("peak_bytes_in_use",
                                            st.get("bytes_in_use", 0))),
            "bytes_limit": int(st.get("bytes_limit", 0)),
        })
    return out


def update_hbm_gauges(stats: Optional[List[dict]] = None) -> List[dict]:
    """Refresh ``dl4j_hbm_live_bytes``/``dl4j_hbm_peak_bytes`` from the
    device allocator (``stats`` injectable for tests / CPU rigs where
    jax reports none).  Called per sampled step by the flight recorder
    and on every ``/metrics`` scrape."""
    if stats is None:
        stats = device_memory_stats()
    if stats and telemetry.enabled():
        live = telemetry.gauge(
            "dl4j_hbm_live_bytes",
            "device allocator bytes currently in use, per device")
        peak = telemetry.gauge(
            "dl4j_hbm_peak_bytes",
            "device allocator high-water mark, per device")
        for s in stats:
            live.set(s["bytes_in_use"], device=str(s["id"]))
            peak.set(s["peak_bytes_in_use"], device=str(s["id"]))
    return stats


#: models the fit funnels have stepped, for attribution — weak so a
#: dropped model does not leak through the diagnostics layer
_tracked_models: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()
_tracked_lock = threading.Lock()


def track_model(model, name: Optional[str] = None) -> None:
    """Register a model for :func:`memory_report` attribution (the fit
    funnels do this on every recorded step; idempotent and weak)."""
    key = f"{name or type(model).__name__}@{id(model):x}"
    if key not in _tracked_models:
        with _tracked_lock:
            try:
                _tracked_models[key] = model
            except TypeError:       # non-weakrefable exotic model
                pass


def _model_attribution(model) -> dict:
    """Bytes by buffer family for one model.  Works for MLN/graph
    (params/states/updater_states) and SameDiff (_arrays /
    _updater_state)."""
    params = getattr(model, "params", None)
    if params is None:
        params = getattr(model, "_arrays", {})
    upd = getattr(model, "updater_states", None)
    if upd is None:
        upd = getattr(model, "_updater_state", None) or {}
    states = getattr(model, "states", {}) or {}
    # resident = what one device actually holds (a ZeRO-1 sharded
    # state or fsdp param flat counts its 1/N shard, not the logical
    # size); equal to the plain bytes when everything is replicated
    return {
        "params_bytes": _tree_bytes(params),
        "updater_state_bytes": _tree_bytes(upd),
        "model_state_bytes": _tree_bytes(states),
        "params_resident_bytes": _tree_resident_bytes(params),
        "updater_state_resident_bytes": _tree_resident_bytes(upd),
    }


def memory_report(model=None) -> dict:
    """The per-buffer HBM attribution report: device allocator stats
    (live/peak/limit), per-model params / updater-state / model-state
    bytes, prefetch staging bytes, and the residual the allocator holds
    beyond what those account for (activations, XLA workspace,
    fragmentation).  ``model`` narrows attribution to one model;
    default covers every tracked model.  This is the instrument that
    makes the FSDP work (ROADMAP item 1) measurable: it shows where
    the 93.5%-of-peak HBM actually goes."""
    devices = update_hbm_gauges()
    if model is not None:
        items = [(type(model).__name__, model)]
    else:
        with _tracked_lock:
            items = [(k, m) for k, m in _tracked_models.items()]
    models = {name: _model_attribution(m) for name, m in items}
    staging = telemetry.gauge(
        "dl4j_prefetch_staged_bytes",
        "bytes of device-prefetched batches currently staged ahead of "
        "the step loop").value()
    # account per-device residency (shard-aware), not logical bytes —
    # under fsdp a model's params_bytes exceeds what any chip holds
    accounted = int(staging) + sum(
        v["params_resident_bytes"] + v["updater_state_resident_bytes"] +
        v["model_state_bytes"] for v in models.values())
    # paged KV-cache pools are their own resident class: preallocated
    # generation state, not params and not activations (sys.modules
    # lookup: near-free, and no import edge from diagnostics to
    # serving)
    kvc = sys.modules.get("deeplearning4j_tpu.serving.kvcache")
    kv_pools = kvc.pool_report() if kvc is not None else []
    kv_bytes = kvc.pool_resident_bytes() if kvc is not None else 0
    accounted += int(kv_bytes)
    report = {
        "schema_version": SCHEMA_VERSION,
        "devices": devices,
        "live_bytes_total": sum(d["bytes_in_use"] for d in devices),
        "peak_bytes_total": sum(d["peak_bytes_in_use"]
                                for d in devices),
        "models": models,
        "prefetch_staging_bytes": int(staging),
        "kv_pools": kv_pools,
        "kv_pool_bytes": int(kv_bytes),
        "accounted_bytes": accounted,
    }
    if devices:
        # what the allocator holds beyond the buffers we can name:
        # activations kept for backward, XLA scratch, fragmentation
        report["activations_and_workspace_bytes_est"] = max(
            report["live_bytes_total"] - accounted, 0)
    return report


def roofline(flops: float, bytes_moved: float, step_seconds: float,
             peak_tflops: Optional[float] = None,
             peak_hbm_gbps: Optional[float] = None) -> dict:
    """Automatic roofline classification from an XLA cost analysis
    (``benchmarks.cost_util``) plus a measured step time: achieved
    TFLOP/s and GB/s, arithmetic intensity vs the machine ridge point,
    which roof binds, and %-of-that-roof — the one number that says
    whether fused kernels (ROADMAP item 3) or more MXU work is the
    next lever."""
    tf = flops / step_seconds / 1e12
    gbps = bytes_moved / step_seconds / 1e9
    out = {
        "tflops": round(tf, 2),
        "hbm_gbps": round(gbps, 1),
        "arithmetic_intensity_flops_per_byte": round(
            flops / max(bytes_moved, 1.0), 2),
    }
    if peak_tflops and peak_hbm_gbps:
        ridge = peak_tflops * 1e12 / (peak_hbm_gbps * 1e9)
        ai = out["arithmetic_intensity_flops_per_byte"]
        out["ridge_flops_per_byte"] = round(ridge, 1)
        out["bound"] = "compute" if ai >= ridge else "hbm"
        out["pct_compute_peak"] = round(100 * tf / peak_tflops, 1)
        out["pct_hbm_peak"] = round(100 * gbps / peak_hbm_gbps, 1)
        out["pct_of_roof"] = (out["pct_compute_peak"]
                              if out["bound"] == "compute"
                              else out["pct_hbm_peak"])
    return out


def bench_meta() -> dict:
    """Provenance block stamped into every bench JSON so BENCH_r*.json
    trajectories are comparable run-to-run: schema version, git rev,
    jax version, device kind/count, and the ``DL4J_TPU_*`` env that
    shapes the run."""
    import jax
    meta = {
        "schema_version": SCHEMA_VERSION,
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "jax_version": jax.__version__,
        "python_version": sys.version.split()[0],
    }
    try:
        devs = jax.devices()
        meta["device_count"] = len(devs)
        meta["device_kind"] = str(getattr(devs[0], "device_kind",
                                          devs[0].platform))
        meta["platform"] = devs[0].platform
    except Exception as e:          # noqa: BLE001
        meta["device_error"] = repr(e)
    try:
        import subprocess
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
        if rev.returncode == 0:
            meta["git_rev"] = rev.stdout.strip()
    except Exception:               # noqa: BLE001 — no git, no rev
        pass
    meta["env"] = {k: v for k, v in sorted(os.environ.items())
                   if k.startswith("DL4J_TPU_") or k == "JAX_PLATFORMS"}
    return meta


# ----------------------------------------------------------------------
# per-collective tracing
_COLLECTIVE_SECONDS_HELP = (
    "host-observed wall time of one collective exchange — update "
    "exchange (AllReduce | ReduceScatter+AllGather), sharded-state "
    "placement, cross-process batch assembly (seconds)")
_COLLECTIVE_BYTES_HELP = (
    "estimated per-replica bytes moved by collective exchanges, by "
    "kind and mesh axis")


@contextmanager
def collective_span(kind: str, axis: str, nbytes: int = 0, **attrs):
    """The general form of the ``dp.update_exchange`` span pattern: a
    chrome-trace span ``collective.<kind>`` plus
    ``dl4j_collective_seconds{kind,axis}`` and
    ``dl4j_collective_bytes_total{kind,axis}``.  ``kind`` names the
    exchange (``update_exchange``, ``state_placement``,
    ``global_assembly``, ...), ``axis`` the mesh axis it rides.  Wraps
    host dispatch of the jitted program that CONTAINS the collective —
    on-device overlap means this bounds, not isolates, the wire time;
    the bytes counter is what makes a scaling-efficiency claim
    falsifiable per PR."""
    if not telemetry.enabled():
        yield
        return
    t0 = time.perf_counter()
    with telemetry.span(f"collective.{kind}", axis=axis,
                        bytes=int(nbytes), **attrs):
        yield
    dt = time.perf_counter() - t0
    telemetry.histogram(
        "dl4j_collective_seconds",
        _COLLECTIVE_SECONDS_HELP).observe(dt, kind=kind, axis=axis)
    if nbytes:
        telemetry.counter(
            "dl4j_collective_bytes_total",
            _COLLECTIVE_BYTES_HELP).inc(int(nbytes), kind=kind,
                                        axis=axis)
    # fold into the scaling observatory's step breakdown
    stepstats.note_collective(kind, dt)


# ----------------------------------------------------------------------
# numerics watchdog
class NumericsEvent(RuntimeError):
    """A non-finite value surfaced in training.  Structured: ``step``,
    ``model``, ``tensor_group`` (``loss``/``gradients``/``params``),
    ``value`` (the offending scalar when there is one), ``first_bad``
    ({leaf, dtype, flat_index} from the DpFlatSpec segment scan)."""

    def __init__(self, model: str, step: int, tensor_group: str,
                 first_bad: Optional[dict] = None, value=None):
        self.model = model
        self.step = int(step)
        self.tensor_group = tensor_group
        self.first_bad = first_bad
        self.value = value
        loc = f" first bad leaf: {first_bad}" if first_bad else ""
        super().__init__(
            f"non-finite {tensor_group} (={value}) in {model} at step "
            f"{step};{loc} — training halted by the numerics watchdog "
            f"(DL4J_TPU_NUMERICS_WATCHDOG=0 disables)")

    def to_dict(self) -> dict:
        return {"model": self.model, "step": self.step,
                "tensor_group": self.tensor_group,
                "first_bad": self.first_bad,
                "value": (None if self.value is None
                          else float(self.value))}


def first_nonfinite(tree) -> Optional[dict]:
    """Locate the first non-finite leaf element via the per-dtype flat
    segment layout (``learning.updaters.DpFlatSpec``): one fused
    ``isfinite``+``argmax`` reduction per float dtype bucket instead of
    a per-leaf host loop, then the flat index maps back through the
    spec's (dtype, offset, shape) segments to a named leaf.  Returns
    ``{leaf, dtype, flat_index}`` or None when every element is
    finite."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.learning.updaters import dp_ravel
    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    if not leaves_with_path:
        return None
    paths = [jax.tree_util.keystr(p) for p, _ in leaves_with_path]
    flats, spec = dp_ravel(tree, 1)
    for dt, flat in flats.items():
        if not jnp.issubdtype(flat.dtype, jnp.floating):
            continue
        bad = ~jnp.isfinite(flat)
        if not bool(jnp.any(bad)):
            continue
        idx = int(jnp.argmax(bad))
        for (d, off, shape), label in zip(spec.infos, paths):
            if d != dt:
                continue
            size = int(np.prod(shape)) if shape else 1
            if off <= idx < off + size:
                return {"leaf": label, "dtype": dt,
                        "flat_index": idx - off}
        return {"leaf": "<padding>", "dtype": dt, "flat_index": idx}
    return None


def check_numerics(model, model_name: str, step: int, loss,
                   grad_norm=None, grads=None, params=None,
                   recorded: bool = False) -> None:
    """The fit-funnel watchdog hook.  No-op unless
    ``DL4J_TPU_NUMERICS_WATCHDOG=1``; checks every
    ``DL4J_TPU_NUMERICS_SAMPLE``-th step.  ``loss`` (and ``grad_norm``
    when the step computes one) are device scalars — the check is the
    one host sync.  On a trip the first bad leaf is located in
    ``grads`` (preferred) or ``params``, the flight recorder dumps
    with ``reason="numerics"``, and a :class:`NumericsEvent` raises."""
    env = Environment.get()
    if not env.numerics_watchdog:
        return
    if env.numerics_sample > 1 and step % env.numerics_sample:
        return
    lf = float(loss)
    gf = None if grad_norm is None else float(grad_norm)
    if math.isfinite(lf) and (gf is None or math.isfinite(gf)):
        return
    if not math.isfinite(lf):
        group, value = "loss", lf
    else:
        group, value = "gradients", gf
    first_bad = None
    scan = grads if grads is not None else params
    if scan is not None:
        try:
            first_bad = first_nonfinite(scan)
        except Exception as e:      # noqa: BLE001 — diagnosis must not
            log.warning("numerics attribution scan failed: %r", e)
    telemetry.counter(
        "dl4j_numerics_trips_total",
        "numerics-watchdog trips (non-finite loss or grad norm), by "
        "model and tensor group").inc(model=model_name, group=group)
    telemetry.instant("numerics_trip", model=model_name, step=step,
                      group=group)
    event = NumericsEvent(model_name, step, group, first_bad, value)
    rec = FlightRecorder.get()
    if rec.enabled:
        if not recorded:
            # the poisoned step itself belongs in the black box
            rec.record(model, model_name, step, lf, None,
                       grad_norm=gf)
        rec.dump("numerics", event=event.to_dict())
    raise event


# ----------------------------------------------------------------------
# flight recorder
class FlightRecorder:
    """Bounded ring of per-step structured records, dumped to
    ``flightrec_<pid>_<reason>.jsonl`` (+ a chrome trace of the span
    buffer's last window) on crash, SIGTERM, or watchdog trip.

    Loss/grad-norm enter the ring as device scalars and are
    materialized only at dump time, so recording never forces a step
    sync.  HBM gauges refresh every ``DL4J_TPU_HBM_SAMPLE_STEPS``
    records.  Gate: ``DL4J_TPU_FLIGHT_RECORDER`` (default on)."""

    _instance: Optional["FlightRecorder"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        env = Environment.get()
        self.enabled = bool(env.flight_recorder)
        self.max_steps = max(int(env.flight_recorder_steps), 1)
        self.dir = env.flight_recorder_dir or "flightrec"
        self.keep = max(int(env.flight_recorder_keep), 1)
        self.hbm_sample = max(int(env.hbm_sample_steps), 1)
        self._ring: "deque[dict]" = deque()
        self._lock = threading.Lock()
        self._installed = False
        self._prev_excepthook = None
        self._prev_sigterm = None
        self._n_records = 0
        self._last_hbm: List[dict] = []
        self._dumped_reasons: set = set()

    @classmethod
    def get(cls) -> "FlightRecorder":
        inst = cls._instance
        if inst is not None:
            return inst
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def _reset_for_tests(cls):
        with cls._instance_lock:
            if cls._instance is not None:
                cls._instance.uninstall()
            cls._instance = None
        with _tracked_lock:
            _tracked_models.clear()

    # -- crash / preemption hooks --------------------------------------
    def install(self) -> None:
        """Wrap ``sys.excepthook`` (crash) and the SIGTERM handler
        (preemption).  Idempotent; called lazily on the first recorded
        step so importing the library never touches process-global
        handlers."""
        if self._installed:
            return
        self._installed = True
        self._prev_excepthook = sys.excepthook

        def _hook(tp, val, tb):
            try:
                self.dump("crash", event={"error": repr(val)})
            finally:
                (self._prev_excepthook or sys.__excepthook__)(
                    tp, val, tb)

        sys.excepthook = _hook
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
        except ValueError:
            # not the main thread — excepthook coverage only
            self._prev_sigterm = None

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                pass

    def _on_sigterm(self, signum, frame):
        self.dump("sigterm")
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        else:
            # re-deliver with the default disposition so the exit
            # status still says "terminated by SIGTERM"
            try:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
            except ValueError:
                pass
            os.kill(os.getpid(), signal.SIGTERM)

    # -- recording ------------------------------------------------------
    @staticmethod
    def _counter_total(name: str) -> float:
        reg = telemetry.MetricsRegistry.get()
        m = reg._metrics.get(name)
        if m is None:
            return 0.0
        return float(sum(m._series.values()))

    def record(self, model, model_name: str, step: int, loss,
               span=None, grad_norm=None, **extra) -> None:
        """Append one step record.  ``loss``/``grad_norm`` may be
        device scalars (kept lazy); ``span`` is the
        ``telemetry.step_span`` whose ``duration`` just closed."""
        if not self.enabled:
            return
        if not self._installed:
            self.install()
        track_model(model, model_name)
        self._n_records += 1
        if self._n_records % self.hbm_sample == 1:
            try:
                self._last_hbm = update_hbm_gauges()
            except Exception:       # noqa: BLE001
                self._last_hbm = []
        rec = {
            "step": int(step),
            "t": time.time(),
            "model": model_name,
            "step_seconds": getattr(span, "duration", None),
            "loss": loss,
            "grad_norm": grad_norm,
            "retraces": self._counter_total("dl4j_retrace_total"),
            "collective_bytes": (
                self._counter_total("dl4j_collective_bytes_total") +
                self._counter_total(
                    "dl4j_dp_update_exchange_bytes_total")),
            "hbm_live_bytes": sum(d["bytes_in_use"]
                                  for d in self._last_hbm),
            "hbm_peak_bytes": sum(d["peak_bytes_in_use"]
                                  for d in self._last_hbm),
        }
        # heaviest layer of the last layerprof report, when one was
        # computed (sys.modules lookup: near-free, and no import edge
        # from diagnostics to layerprof)
        lp = sys.modules.get("deeplearning4j_tpu.common.layerprof")
        if lp is not None:
            top = lp.top_layer()
            if top is not None:
                rec["top_layer"] = top
        if extra:
            rec.update(extra)
        with self._lock:
            self._ring.append(rec)
            while len(self._ring) > self.max_steps:
                self._ring.popleft()

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    # -- dumping --------------------------------------------------------
    @staticmethod
    def _materialize(v):
        if v is None:
            return None
        try:
            return float(v)
        except Exception as e:      # noqa: BLE001 — a dead buffer must
            return f"<unreadable: {e!r}>"   # not lose the record

    def dump(self, reason: str, event: Optional[dict] = None
             ) -> Optional[str]:
        """Write the ring as JSONL plus a chrome trace of the span
        buffer; returns the JSONL path.  One dump per reason per
        process (a crashing step must not stampede artifacts)."""
        if not self.enabled:
            return None
        with self._lock:
            if reason in self._dumped_reasons:
                return None
            self._dumped_reasons.add(reason)
            ring = list(self._ring)
        base = os.path.join(self.dir,
                            f"flightrec_{os.getpid()}_{reason}")
        path = base + ".jsonl"
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(path, "w") as f:
                f.write(json.dumps({
                    "record": "meta",
                    "schema_version": SCHEMA_VERSION,
                    "reason": reason,
                    "time": time.time(),
                    "pid": os.getpid(),
                    "n_steps": len(ring),
                    "ring_capacity": self.max_steps,
                    "event": event,
                }) + "\n")
                for rec in ring:
                    out = dict(rec)
                    out["loss"] = self._materialize(rec["loss"])
                    out["grad_norm"] = self._materialize(
                        rec["grad_norm"])
                    f.write(json.dumps(out) + "\n")
            trace = telemetry.export_chrome_trace(base + ".trace.json")
        except Exception as e:      # noqa: BLE001 — dumping is best-
            log.warning("flight recorder dump failed: %r", e)
            return None
        telemetry.counter(
            "dl4j_flightrec_dumps_total",
            "flight-recorder dumps, by trigger reason").inc(
                reason=reason)
        log.warning("flight recorder: dumped %d step records to %s "
                    "(+ %s) reason=%s", len(ring), path, trace, reason)
        self._prune()
        return path

    def _prune(self) -> None:
        """Bounded retention: keep the newest ``keep`` dump pairs in
        the dump directory, delete older ones (a week of preemptions
        must not fill the disk with black boxes)."""
        try:
            dumps = sorted(
                (p for p in os.listdir(self.dir)
                 if p.startswith("flightrec_")
                 and p.endswith(".jsonl")),
                key=lambda p: os.path.getmtime(
                    os.path.join(self.dir, p)))
        except OSError:
            return
        for p in dumps[:-self.keep]:
            for victim in (p, p[:-len(".jsonl")] + ".trace.json"):
                try:
                    os.remove(os.path.join(self.dir, victim))
                except OSError:
                    pass


# ----------------------------------------------------------------------
# the calls the fit funnels make per step
def _close_breakdown(model_name: str, step: int, span,
                     extra: dict) -> None:
    """Close the scaling-observatory breakdown for this step and embed
    its phase decomposition into the flight-recorder record."""
    try:
        bd = stepstats.close_step(model_name, step, span)
    except Exception as e:  # noqa: BLE001 — observability must never
        log.warning("stepstats close failed: %r", e)
        return
    if bd is not None:
        extra.setdefault("phases", bd["phases"])


def record_step(model, model_name: str, step: int, loss, span=None,
                grad_norm=None, **extra) -> None:
    """Flight-recorder append only — for funnels that already ran
    :func:`check_numerics` mid-step (the accumulation path must check
    grads BEFORE the apply step donates their buffers)."""
    from deeplearning4j_tpu.common import faults
    faults.chaos_step()
    _close_breakdown(model_name, step, span, extra)
    rec = FlightRecorder.get()
    if rec.enabled:
        rec.record(model, model_name, step, loss, span,
                   grad_norm=grad_norm, **extra)


def after_step(model, model_name: str, step: int, loss, span=None,
               grad_norm=None, grads=None, params=None,
               **extra) -> None:
    """Record the step into the flight recorder, then run the numerics
    watchdog (which may raise :class:`NumericsEvent`).  Near-free when
    both gates are off: two attribute checks."""
    from deeplearning4j_tpu.common import faults
    faults.chaos_step()
    _close_breakdown(model_name, step, span, extra)
    rec = FlightRecorder.get()
    if rec.enabled:
        rec.record(model, model_name, step, loss, span,
                   grad_norm=grad_norm, **extra)
    check_numerics(model, model_name, step, loss, grad_norm=grad_norm,
                   grads=grads, params=params, recorded=rec.enabled)


def watchdog_enabled() -> bool:
    return Environment.get().numerics_watchdog
