"""Request-scoped trace context for the serving path.

The serving observatory's propagation layer: a trace id is minted at
ingress (or adopted from an incoming ``X-Dl4j-Trace-Id`` header) and a
:class:`TraceContext` rides the request through
``ServingRouter`` → ``InferenceServer`` → ``AdmissionController`` →
``ServingBatcher``/``DecodeEngine``. Each hop stamps *phase* spans —
``req.admit``, ``req.queue``, ``req.batch_wait``, ``req.device``,
``req.serialize``, ``req.stream`` (plus ``req.ttft`` /
``req.inter_token`` instants for generate) — into the shared
chrome-trace ring with the trace id in ``args``, so one request's life
renders as a single connected timeline under its ``request`` root span
in Perfetto, next to the ``serving.flush`` / ``generate.*`` spans that
already existed.

Two propagation mechanisms, on purpose:

- **ambient** (:func:`bind` / :func:`current`): a ``contextvars``
  slot for code on the request's own handler thread (the access log
  reads it). Handler threads are reused across keep-alive requests, so
  ``bind`` always restores the previous value — the leakage hazard the
  test suite pins.
- **explicit**: cross-thread hops (the batcher's flush worker, the
  decode engine loop) carry the context object itself (on the Future /
  pending tuple) and use :meth:`TraceContext.phase_at` to attribute
  intervals they measured back onto the request's timeline.

Clocks: phase intervals are measured on ``time.monotonic`` and mapped
onto the unix-epoch microsecond axis chrome-trace uses via the
context's own (wall, mono) anchor pair, so spans from different
threads of one request line up without per-thread clock reads.

Gate: ``DL4J_TPU_REQUEST_TRACE`` (default ON, and also off whenever
the telemetry spine is off). When off, :func:`start` returns the
falsy :data:`NULL` context whose methods are no-ops — call sites stay
uniform and ``benchmarks/bench_serving.py``'s ``serving_observatory``
leg measures the ≤1% p50 overhead claim of leaving it on.
"""
from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from contextlib import contextmanager
from typing import List, Optional, Tuple

from deeplearning4j_tpu.common import telemetry

#: the end-to-end trace id header (request and response direction)
TRACE_HEADER = "X-Dl4j-Trace-Id"
#: stamped by the router: which replica actually served the request
REPLICA_HEADER = "X-Dl4j-Replica"

#: canonical per-request phase names (span name = "req.<phase>")
PHASES = ("admit", "queue", "batch_wait", "device", "serialize",
          "stream")

_MAX_ID_LEN = 64

_enabled_override: Optional[bool] = None

_current: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("dl4j_trace_ctx", default=None)


def request_trace_enabled() -> bool:
    """The ``DL4J_TPU_REQUEST_TRACE`` gate (AND the telemetry spine's
    own gate — a span with no ring to land in is pure cost)."""
    if not telemetry.enabled():
        return False
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get("DL4J_TPU_REQUEST_TRACE", "1") not in (
        "0", "false", "False", "no")


def set_enabled(on: Optional[bool]) -> None:
    """Override the env gate in-process (None restores it) — the bench
    leg's on/off A-B without re-execing."""
    global _enabled_override
    _enabled_override = None if on is None else bool(on)


def _reset_for_tests() -> None:
    set_enabled(None)


telemetry.on_reset(_reset_for_tests)


def mint_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _clean_id(header_value: Optional[str]) -> Optional[str]:
    """An adopted trace id, sanitized: printable, bounded, no
    whitespace — a hostile header must not pollute logs or traces."""
    if not header_value:
        return None
    tid = header_value.strip()
    if not tid or len(tid) > _MAX_ID_LEN:
        return None
    if not all(c.isalnum() or c in "-_." for c in tid):
        return None
    return tid


class TraceContext:
    """One request's identity + timeline. Truthy (the disabled path
    returns the falsy :data:`NULL` instead), thread-safe for the
    cross-thread ``phase_at``/``note`` calls."""

    __slots__ = ("trace_id", "model", "kind", "t0_wall", "t0_mono",
                 "phases", "attrs", "verdict", "closed", "_lock")

    def __init__(self, model: str, kind: str,
                 trace_id: Optional[str] = None):
        self.trace_id = trace_id or mint_trace_id()
        self.model = model
        self.kind = kind                    # "predict" | "generate"
        self.t0_wall = time.time()
        self.t0_mono = time.monotonic()
        #: (phase, start_mono, dur_s) — the recorder's phase breakdown
        self.phases: List[Tuple[str, float, float]] = []
        self.attrs: dict = {}
        self.verdict: Optional[str] = None
        self.closed = False
        self._lock = threading.Lock()

    def __bool__(self) -> bool:
        return True

    # -- clock mapping -------------------------------------------------
    def wall(self, mono_t: float) -> float:
        """A ``time.monotonic`` instant on this request's wall-clock
        axis (the anchor pair was read together at ingress)."""
        return self.t0_wall + (mono_t - self.t0_mono)

    # -- phases --------------------------------------------------------
    @contextmanager
    def phase(self, name: str):
        """Time the with-block as phase ``name`` of this request."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.phase_at(name, t0, time.monotonic())

    def phase_at(self, name: str, mono_t0: float,
                 mono_t1: float) -> None:
        """Attribute an already-measured ``[mono_t0, mono_t1]``
        interval to this request as phase ``name`` — the cross-thread
        spelling (batcher flush, decode engine)."""
        dur = max(0.0, mono_t1 - mono_t0)
        with self._lock:
            self.phases.append((name, mono_t0, dur))
        telemetry.span_at(f"req.{name}", self.wall(mono_t0), dur,
                          trace=self.trace_id, model=self.model)

    def instant(self, name: str, **attrs) -> None:
        telemetry.instant(f"req.{name}", trace=self.trace_id,
                          model=self.model, **attrs)

    def note(self, **attrs) -> None:
        """Attach request facts (queue depth, KV blocks, batch
        occupancy) — they land in the root span's args and the flight
        recorder's record."""
        with self._lock:
            self.attrs.update(attrs)

    # -- completion ----------------------------------------------------
    def elapsed_s(self) -> float:
        return time.monotonic() - self.t0_mono

    def finish(self, verdict) -> float:
        """Close the request: emit the ``request`` root span covering
        ingress→now with the verdict (HTTP status or reason) in args.
        Idempotent — error paths may race the normal path. Returns
        total seconds."""
        with self._lock:
            if self.closed:
                return 0.0
            self.closed = True
            self.verdict = str(verdict)
            attrs = dict(self.attrs)
        dur = self.elapsed_s()
        telemetry.span_at("request", self.t0_wall, dur,
                          trace=self.trace_id, model=self.model,
                          kind=self.kind, verdict=self.verdict,
                          **attrs)
        return dur

    def phase_ms(self) -> dict:
        """{phase: total milliseconds} — repeated phases (per-chunk
        device spans) sum."""
        out: dict = {}
        with self._lock:
            for name, _, dur in self.phases:
                out[name] = out.get(name, 0.0) + dur * 1e3
        return out


class _NullContext:
    """Falsy no-op stand-in when request tracing is off: call sites
    keep one shape, the disabled path costs one truthiness check."""

    __slots__ = ()
    trace_id = None
    model = None
    kind = None
    verdict = None
    closed = True

    def __bool__(self) -> bool:
        return False

    @contextmanager
    def phase(self, name: str):
        yield

    def phase_at(self, *a, **kw) -> None:
        pass

    def instant(self, *a, **kw) -> None:
        pass

    def note(self, **kw) -> None:
        pass

    def finish(self, verdict) -> float:
        return 0.0

    def phase_ms(self) -> dict:
        return {}

    def wall(self, mono_t: float) -> float:
        return mono_t

    def elapsed_s(self) -> float:
        return 0.0


NULL = _NullContext()


def start(model: str, kind: str,
          incoming_header: Optional[str] = None):
    """Mint (or adopt, when the ``X-Dl4j-Trace-Id`` request header
    carries a well-formed id) a request trace context — the ingress
    call. Returns :data:`NULL` when the gate is off."""
    if not request_trace_enabled():
        return NULL
    return TraceContext(model, kind,
                        trace_id=_clean_id(incoming_header))


def current():
    """The context bound to this thread of control (None outside a
    request)."""
    return _current.get()


@contextmanager
def bind(ctx):
    """Make ``ctx`` the ambient context for the with-block. ALWAYS
    restores the previous value — handler threads are reused across
    keep-alive requests, and a leaked binding is exactly the
    cross-request contamination the observatory exists to rule out."""
    token = _current.set(ctx if ctx else None)
    try:
        yield ctx
    finally:
        _current.reset(token)
