"""Updaters (optimizers) as pure functions over state pytrees.

Reference parity: ``org.nd4j.linalg.learning.config.IUpdater`` + the
``GradientUpdater`` implementations (Sgd, Adam, AdaMax, Nadam, AMSGrad,
AdaGrad, AdaDelta, RmsProp, Nesterovs, NoOp — SURVEY.md J7). The reference
mutates flat buffer views in place; here each updater is a pure transform
``(grads, state, iteration) -> (updates, new_state)`` over pytrees — the
whole update lives inside the jitted train step and XLA fuses it
(SURVEY.md section 7 design stance: "updaters are pure functions over
optimizer state pytrees").

Sign convention matches the reference: ``apply`` returns the quantity to be
**subtracted** from the parameters.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.learning.schedules import ISchedule

LrLike = Union[float, ISchedule]


def _tmap(fn, *trees):
    return jax.tree_util.tree_map(fn, *trees)


# ---------------------------------------------------------------------------
# ZeRO-1 flat layout: arbitrary param pytrees raveled into one padded 1-D
# vector per dtype, so optimizer state and the weight update can shard
# evenly along a data-parallel mesh axis (Xu et al., "Automatic
# Cross-Replica Sharding of Weight Update in Data-Parallel Training").
# The spec is pure shape metadata — building it under jit tracing is fine.

#: reserved key marking an updater-state dict as dp-sharded flat layout
DP_SHARDED_KEY = "__dp_sharded__"


def is_dp_sharded(state) -> bool:
    return isinstance(state, dict) and DP_SHARDED_KEY in state


#: reserved key marking a PARAM subtree as FSDP (ZeRO-3) flat layout:
#: ``{FSDP_KEY: {dtype key: padded flat vector}}`` resident 1/N per
#: replica along the dp axis (``parallel.zero`` owns the conversions)
FSDP_KEY = "__fsdp__"


def is_fsdp(tree) -> bool:
    return isinstance(tree, dict) and FSDP_KEY in tree


#: reserved key marking the tensor-parallel split of a param or
#: updater-state entry: ``{TP_KEY: {param name: array}}``. TP leaves
#: keep their full logical shape and live physically sharded along the
#: ``model`` mesh axis (``parallel.speclayout`` infers the specs); they
#: are never raveled into the dp flats — a data-axis ravel of a
#: model-sharded leaf would all-gather across the model axis inside the
#: step, which the 2D layouts forbid.
TP_KEY = "__tp__"


def has_tp(tree) -> bool:
    return isinstance(tree, dict) and TP_KEY in tree


#: reserved key marking the encoded update-exchange rung's per-replica
#: error-feedback state inside an updater-state entry:
#: ``{ENCODED_KEY: {"residual": {dtype key: padded flat}, "tau": f32,
#: "step": i32, "sparsity": f32}}``.  The residual flats shard
#: ``P(data)`` beside the DP_SHARDED slots; in the dense (checkpoint)
#: layout the residual unravels back into the param treedef so restore
#: works on any device count (``parallel.zero`` owns the conversions).
ENCODED_KEY = "__encoded__"


def is_encoded(state) -> bool:
    return isinstance(state, dict) and ENCODED_KEY in state


class DpFlatSpec:
    """How a pytree ravels into per-dtype padded flat vectors.

    ``infos``: per leaf (dtype key, offset into its dtype vector, shape);
    ``sizes``: dtype key -> (original length, padded length). The padded
    length is the original rounded up to a multiple of ``n_shards`` so a
    ``P(dp)`` NamedSharding divides it evenly. ``axis`` records WHICH
    mesh axis the flats shard over (always the data axis today — on a
    2D ``(data, model)`` mesh the dp collectives the flats imply must
    never cross the model axis, so per-axis wire accounting keys off
    it).
    """

    def __init__(self, treedef, infos, sizes, n_shards: int,
                 axis: str = "data"):
        self.treedef = treedef
        self.infos: List[Tuple[str, int, tuple]] = infos
        self.sizes: Dict[str, Tuple[int, int]] = sizes
        self.n_shards = n_shards
        self.axis = axis


def dp_flatten_spec(tree, n_shards: int,
                    axis: str = "data") -> DpFlatSpec:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    infos, offsets = [], {}
    for leaf in leaves:
        dt = str(jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype")
                 else leaf.dtype)
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        off = offsets.get(dt, 0)
        infos.append((dt, off, tuple(leaf.shape)))
        offsets[dt] = off + size
    sizes = {}
    for dt, orig in offsets.items():
        padded = -(-orig // n_shards) * n_shards
        sizes[dt] = (orig, padded)
    return DpFlatSpec(treedef, infos, sizes, n_shards, axis)


def dp_ravel(tree, n_shards: int, spec: DpFlatSpec = None):
    """Ravel ``tree`` to {dtype key: flat padded vector}; zero padding
    (harmless under every updater here: zero grad + zero state leaves
    the pad slot untouched, and pads are dropped by :func:`dp_unravel`).
    Returns (flats, spec)."""
    if spec is None:
        spec = dp_flatten_spec(tree, n_shards)
    leaves = jax.tree_util.tree_leaves(tree)
    parts: Dict[str, list] = {}
    for leaf, (dt, _, _) in zip(leaves, spec.infos):
        parts.setdefault(dt, []).append(jnp.reshape(leaf, (-1,)))
    flats = {}
    for dt, chunks in parts.items():
        flat = chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks)
        orig, padded = spec.sizes[dt]
        if padded != orig:
            flat = jnp.concatenate(
                [flat, jnp.zeros((padded - orig,), flat.dtype)])
        flats[dt] = flat
    return flats, spec


def dp_unravel(flats: Dict[str, jnp.ndarray], spec: DpFlatSpec):
    """Inverse of :func:`dp_ravel` (padding dropped). Only offsets and
    shapes are consulted, so vectors longer than the spec's padded
    length (e.g. padded for a different shard count) unravel fine."""
    leaves = []
    for dt, off, shape in spec.infos:
        size = int(np.prod(shape)) if shape else 1
        leaves.append(jnp.reshape(flats[dt][off:off + size], shape))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


class IUpdater:
    """Config + pure math for one optimizer."""

    learning_rate: LrLike = 1e-3

    # -- learning rate ---------------------------------------------------
    def lr_at(self, iteration, epoch=0):
        if isinstance(self.learning_rate, ISchedule):
            return self.learning_rate.value_at(iteration, epoch)
        return self.learning_rate

    def has_learning_rate(self) -> bool:
        return True

    # -- state / apply ---------------------------------------------------
    def init_state(self, params) -> Any:
        return ()

    def init_state_sharded(self, params, n_shards: int) -> Any:
        """State in the ZeRO-1 flat layout: each slot becomes per-dtype
        padded flat vectors (1/``n_shards`` of which lives on each
        replica once the caller places them — ``parallel.zero``). Works
        for every updater whose state is ``zeros_like(params)`` slots,
        i.e. all of them: ``init_state`` on the raveled params yields
        the slot structure directly. Stateless updaters return ``()``
        unchanged."""
        dense_shape = self.init_state(params)
        if not dense_shape:
            return dense_shape
        flats, _ = dp_ravel(params, n_shards)
        return {DP_SHARDED_KEY: self.init_state(flats)}

    def apply(self, grads, state, iteration, epoch=0):
        """-> (updates_to_subtract, new_state)."""
        raise NotImplementedError

    # -- serialization ---------------------------------------------------
    def to_map(self) -> dict:
        d = {"@class": type(self).__name__}
        for k, v in self.__dict__.items():
            d[k] = v.to_map() if isinstance(v, ISchedule) else v
        return d

    @staticmethod
    def from_map(d: dict) -> "IUpdater":
        d = dict(d)
        cls = _REGISTRY[d.pop("@class")]
        for k, v in d.items():
            if isinstance(v, dict) and "@class" in v:
                d[k] = ISchedule.from_map(v)
        return cls(**d)

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash(type(self).__name__)


def updater_from_config(x) -> IUpdater:
    if isinstance(x, IUpdater):
        return x
    if isinstance(x, dict):
        return IUpdater.from_map(x)
    raise TypeError(f"cannot build updater from {x!r}")


# ---------------------------------------------------------------------------
@dataclass(eq=False)
class NoOp(IUpdater):
    """No update (frozen parameters — reference ``NoOp``)."""

    def has_learning_rate(self) -> bool:
        return False

    def apply(self, grads, state, iteration, epoch=0):
        return _tmap(jnp.zeros_like, grads), state


@dataclass(eq=False)
class Sgd(IUpdater):
    learning_rate: LrLike = 1e-3

    def apply(self, grads, state, iteration, epoch=0):
        lr = self.lr_at(iteration, epoch)
        return _tmap(lambda g: lr * g, grads), state


@dataclass(eq=False)
class Nesterovs(IUpdater):
    """SGD with Nesterov momentum.

    v' = mu*v - lr*g ; update = -(mu*v' - lr*g)  (reference formulation:
    org.nd4j.linalg.learning.NesterovsUpdater applies
    params += mu*v' - lr*g, i.e. subtracts lr*g - mu*v').
    """
    learning_rate: LrLike = 0.1
    momentum: float = 0.9

    def init_state(self, params):
        return {"v": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, iteration, epoch=0):
        lr = self.lr_at(iteration, epoch)
        mu = self.momentum
        v_new = _tmap(lambda v, g: mu * v - lr * g, state["v"], grads)
        updates = _tmap(lambda vn, g: lr * g - mu * vn, v_new, grads)
        return updates, {"v": v_new}


@dataclass(eq=False)
class Adam(IUpdater):
    learning_rate: LrLike = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        # m/v must be distinct buffers: the train step donates its inputs,
        # and XLA rejects the same buffer donated twice
        return {"m": _tmap(jnp.zeros_like, params),
                "v": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, iteration, epoch=0):
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        lr = self.lr_at(iteration, epoch)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        m = _tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                  state["v"], grads)
        bc1 = 1.0 - jnp.power(b1, t)
        bc2 = 1.0 - jnp.power(b2, t)
        upd = _tmap(lambda m_, v_: lr * (m_ / bc1) /
                    (jnp.sqrt(v_ / bc2) + eps), m, v)
        return upd, {"m": m, "v": v}


@dataclass(eq=False)
class AdaMax(IUpdater):
    learning_rate: LrLike = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"m": _tmap(jnp.zeros_like, params),
                "u": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, iteration, epoch=0):
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        lr = self.lr_at(iteration, epoch)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        m = _tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        u = _tmap(lambda u_, g: jnp.maximum(b2 * u_, jnp.abs(g)),
                  state["u"], grads)
        bc1 = 1.0 - jnp.power(b1, t)
        upd = _tmap(lambda m_, u_: lr * m_ / (bc1 * (u_ + eps)), m, u)
        return upd, {"m": m, "u": u}


@dataclass(eq=False)
class Nadam(IUpdater):
    learning_rate: LrLike = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"m": _tmap(jnp.zeros_like, params),
                "v": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, iteration, epoch=0):
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        lr = self.lr_at(iteration, epoch)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        m = _tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                  state["v"], grads)
        bc1 = 1.0 - jnp.power(b1, t)
        bc2 = 1.0 - jnp.power(b2, t)
        upd = _tmap(
            lambda m_, v_, g: lr / (jnp.sqrt(v_ / bc2) + eps) *
            (b1 * m_ / bc1 + (1 - b1) * g / bc1),
            m, v, grads)
        return upd, {"m": m, "v": v}


@dataclass(eq=False)
class AMSGrad(IUpdater):
    learning_rate: LrLike = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_state(self, params):
        # distinct buffers required — donated arguments may not alias
        return {"m": _tmap(jnp.zeros_like, params),
                "v": _tmap(jnp.zeros_like, params),
                "vmax": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, iteration, epoch=0):
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        lr = self.lr_at(iteration, epoch)
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        m = _tmap(lambda m_, g: b1 * m_ + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                  state["v"], grads)
        vmax = _tmap(jnp.maximum, state["vmax"], v)
        # reference AMSGradUpdater: alpha_t = lr*sqrt(1-b2^t)/(1-b1^t)
        alpha_t = lr * jnp.sqrt(1.0 - jnp.power(b2, t)) / \
            (1.0 - jnp.power(b1, t))
        upd = _tmap(lambda m_, vm: alpha_t * m_ / (jnp.sqrt(vm) + eps),
                    m, vmax)
        return upd, {"m": m, "v": v, "vmax": vmax}


@dataclass(eq=False)
class AdaGrad(IUpdater):
    learning_rate: LrLike = 1e-1
    epsilon: float = 1e-6

    def init_state(self, params):
        return {"G": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, iteration, epoch=0):
        lr = self.lr_at(iteration, epoch)
        G = _tmap(lambda G_, g: G_ + g * g, state["G"], grads)
        upd = _tmap(lambda G_, g: lr * g / (jnp.sqrt(G_) + self.epsilon),
                    G, grads)
        return upd, {"G": G}


@dataclass(eq=False)
class AdaDelta(IUpdater):
    rho: float = 0.95
    epsilon: float = 1e-6
    learning_rate: LrLike = 1.0  # AdaDelta has no lr; kept for API shape

    def has_learning_rate(self) -> bool:
        return False

    def init_state(self, params):
        return {"Eg": _tmap(jnp.zeros_like, params),
                "Edx": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, iteration, epoch=0):
        rho, eps = self.rho, self.epsilon
        Eg = _tmap(lambda e, g: rho * e + (1 - rho) * g * g,
                   state["Eg"], grads)
        dx = _tmap(lambda e, edx, g:
                   jnp.sqrt(edx + eps) / jnp.sqrt(e + eps) * g,
                   Eg, state["Edx"], grads)
        Edx = _tmap(lambda edx, d: rho * edx + (1 - rho) * d * d,
                    state["Edx"], dx)
        return dx, {"Eg": Eg, "Edx": Edx}


@dataclass(eq=False)
class RmsProp(IUpdater):
    learning_rate: LrLike = 1e-3
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init_state(self, params):
        return {"Eg": _tmap(jnp.zeros_like, params)}

    def apply(self, grads, state, iteration, epoch=0):
        lr = self.lr_at(iteration, epoch)
        rho = self.rms_decay
        Eg = _tmap(lambda e, g: rho * e + (1 - rho) * g * g,
                   state["Eg"], grads)
        upd = _tmap(lambda e, g: lr * g / (jnp.sqrt(e) + self.epsilon),
                    Eg, grads)
        return upd, {"Eg": Eg}


_REGISTRY = {c.__name__: c for c in
             (NoOp, Sgd, Nesterovs, Adam, AdaMax, Nadam, AMSGrad, AdaGrad,
              AdaDelta, RmsProp)}
