from deeplearning4j_tpu.learning.schedules import (  # noqa: F401
    ScheduleType, ISchedule, FixedSchedule, StepSchedule,
    ExponentialSchedule, InverseSchedule, PolySchedule, SigmoidSchedule,
    MapSchedule, LinearSchedule, CycleSchedule, WarmupSchedule)
from deeplearning4j_tpu.learning.updaters import (  # noqa: F401
    IUpdater, Sgd, Adam, AdaMax, Nadam, AMSGrad, AdaGrad, AdaDelta,
    RmsProp, Nesterovs, NoOp, updater_from_config)
