"""Learning-rate schedules.

Reference parity: ``org.nd4j.linalg.schedule.ISchedule`` and its
implementations (SURVEY.md J7). All value computations use jnp so a traced
iteration counter works inside a jitted train step (the reference evaluates
schedules host-side per iteration; here the schedule is part of the compiled
step — the TPU-first design keeps the whole update on device).
"""
from __future__ import annotations

import enum
import math
from dataclasses import dataclass, field
from typing import Dict

import jax.numpy as jnp


class ScheduleType(enum.Enum):
    ITERATION = "iteration"
    EPOCH = "epoch"


class ISchedule:
    """value_at(iteration, epoch) -> lr (jnp scalar ok)."""

    schedule_type: ScheduleType = ScheduleType.ITERATION

    def _t(self, iteration, epoch):
        return iteration if self.schedule_type is ScheduleType.ITERATION \
            else epoch

    def value_at(self, iteration, epoch=0):
        raise NotImplementedError

    # -- JSON round-trip -------------------------------------------------
    def to_map(self) -> dict:
        d = {"@class": type(self).__name__}
        d.update({k: (v.value if isinstance(v, ScheduleType) else v)
                  for k, v in self.__dict__.items()})
        return d

    @staticmethod
    def from_map(d: dict) -> "ISchedule":
        d = dict(d)
        cls = _REGISTRY[d.pop("@class")]
        if not isinstance(cls, type):   # custom deserializer function
            return cls(d)
        if "schedule_type" in d:
            d["schedule_type"] = ScheduleType(d["schedule_type"])
        return cls(**d)


@dataclass
class FixedSchedule(ISchedule):
    value: float = 1e-3
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def value_at(self, iteration, epoch=0):
        return self.value


@dataclass
class StepSchedule(ISchedule):
    """lr = initial * decay_rate ^ floor(t / step)."""
    initial_value: float = 1e-3
    decay_rate: float = 0.5
    step: float = 1000.0
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def value_at(self, iteration, epoch=0):
        t = self._t(iteration, epoch)
        return self.initial_value * jnp.power(
            self.decay_rate, jnp.floor(t / self.step))


@dataclass
class ExponentialSchedule(ISchedule):
    """lr = initial * gamma ^ t."""
    initial_value: float = 1e-3
    gamma: float = 0.999
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def value_at(self, iteration, epoch=0):
        return self.initial_value * jnp.power(
            self.gamma, self._t(iteration, epoch))


@dataclass
class InverseSchedule(ISchedule):
    """lr = initial / (1 + gamma * t) ^ power."""
    initial_value: float = 1e-3
    gamma: float = 0.001
    power: float = 1.0
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def value_at(self, iteration, epoch=0):
        t = self._t(iteration, epoch)
        return self.initial_value / jnp.power(1.0 + self.gamma * t,
                                              self.power)


@dataclass
class PolySchedule(ISchedule):
    """lr = initial * (1 - t/max_iter) ^ power."""
    initial_value: float = 1e-3
    power: float = 1.0
    max_iter: int = 10000
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def value_at(self, iteration, epoch=0):
        t = self._t(iteration, epoch)
        frac = jnp.clip(t / self.max_iter, 0.0, 1.0)
        return self.initial_value * jnp.power(1.0 - frac, self.power)


@dataclass
class SigmoidSchedule(ISchedule):
    """lr = initial / (1 + exp(-gamma * (t - step_size)))."""
    initial_value: float = 1e-3
    gamma: float = 0.01
    step_size: int = 1000
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def value_at(self, iteration, epoch=0):
        t = self._t(iteration, epoch)
        return self.initial_value / (
            1.0 + jnp.exp(-self.gamma * (t - self.step_size)))


@dataclass
class MapSchedule(ISchedule):
    """Piecewise-constant: explicit t -> lr breakpoints.

    Reference: ``MapSchedule`` (builder with .add(position, value)). Values
    hold from their breakpoint until the next one.
    """
    values: Dict[int, float] = field(default_factory=dict)
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def __post_init__(self):
        self.values = {int(k): float(v) for k, v in self.values.items()}
        if 0 not in self.values:
            raise ValueError("MapSchedule requires a value for t=0")

    def value_at(self, iteration, epoch=0):
        t = self._t(iteration, epoch)
        keys = sorted(self.values)
        out = jnp.asarray(self.values[keys[0]], jnp.float32)
        for k in keys[1:]:
            out = jnp.where(t >= k, self.values[k], out)
        return out


@dataclass
class LinearSchedule(ISchedule):
    """Linear from initial to final over max_iter steps (then flat)."""
    initial_value: float = 1e-3
    final_value: float = 0.0
    max_iter: int = 10000
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def value_at(self, iteration, epoch=0):
        t = self._t(iteration, epoch)
        frac = jnp.clip(t / self.max_iter, 0.0, 1.0)
        return self.initial_value + frac * (self.final_value -
                                            self.initial_value)


@dataclass
class CycleSchedule(ISchedule):
    """1cycle: warmup to max, anneal down, final short decay.

    Reference: ``CycleSchedule`` (super-convergence style).
    """
    initial_value: float = 1e-4
    max_value: float = 1e-2
    final_value: float = 1e-5
    cycle_length: int = 1000
    annealing_length: int = 100
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def value_at(self, iteration, epoch=0):
        t = self._t(iteration, epoch)
        up = self.cycle_length // 2
        down_end = self.cycle_length
        tf = jnp.asarray(t, jnp.float32)
        lr_up = self.initial_value + (self.max_value - self.initial_value) \
            * (tf / max(up, 1))
        lr_down = self.max_value + (self.initial_value - self.max_value) \
            * ((tf - up) / max(down_end - up, 1))
        lr_anneal = self.initial_value + (self.final_value -
                                          self.initial_value) * jnp.clip(
            (tf - down_end) / max(self.annealing_length, 1), 0.0, 1.0)
        out = jnp.where(tf < up, lr_up,
                        jnp.where(tf < down_end, lr_down, lr_anneal))
        return out


@dataclass
class WarmupSchedule(ISchedule):
    """Linear warmup into an inner schedule (transformer-style; extension —
    the reference composes this manually)."""
    warmup_steps: int = 1000
    inner: ISchedule = field(default_factory=lambda: FixedSchedule(1e-3))
    schedule_type: ScheduleType = ScheduleType.ITERATION

    def value_at(self, iteration, epoch=0):
        t = jnp.asarray(self._t(iteration, epoch), jnp.float32)
        peak = self.inner.value_at(iteration, epoch)
        return jnp.where(t < self.warmup_steps,
                         peak * t / max(self.warmup_steps, 1), peak)

    def to_map(self) -> dict:
        return {"@class": "WarmupSchedule",
                "warmup_steps": self.warmup_steps,
                "inner": self.inner.to_map(),
                "schedule_type": self.schedule_type.value}


_REGISTRY = {c.__name__: c for c in
             (FixedSchedule, StepSchedule, ExponentialSchedule,
              InverseSchedule, PolySchedule, SigmoidSchedule, MapSchedule,
              LinearSchedule, CycleSchedule)}


def _from_map_warmup(d):
    return WarmupSchedule(warmup_steps=d["warmup_steps"],
                          inner=ISchedule.from_map(d["inner"]),
                          schedule_type=ScheduleType(d["schedule_type"]))


_REGISTRY["WarmupSchedule"] = _from_map_warmup
