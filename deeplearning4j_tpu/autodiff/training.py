"""SameDiff training config + history (SURVEY.md S4).

Reference parity: ``org.nd4j.autodiff.samediff.TrainingConfig`` (updater,
regularization, dataset feature/label -> placeholder mappings) and
``History`` (per-epoch loss curves returned by ``fit``).
"""
from __future__ import annotations

import queue as _queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np


class _FeederError:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def device_prefetch_placeholders(iterator, make_ph: Callable,
                                 depth: int = 2):
    """Device-side staging for the SameDiff fit loop (the placeholder
    analogue of ``datasets.prefetch.DevicePrefetcher``): a feeder
    thread maps each batch through ``make_ph`` (DataSet ->
    ``{name: array}`` via the TrainingConfig mappings) and the arrays
    are ``jax.device_put`` ahead of the step that consumes them,
    double-buffered, so the H2D copy of batch n+1 overlaps the device
    step on batch n. As in DevicePrefetcher, the put is issued
    feeder-side on accelerator backends and consumer-side (after the
    async step dispatch of the previous batch) on CPU. Feeder
    exceptions re-raise on the consumer; the generator yields dicts
    of device-resident arrays in iterator order."""
    import time

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common import telemetry
    thread_put = jax.default_backend() != "cpu"
    q: _queue.Queue = _queue.Queue(max(1, int(depth)))
    sentinel = object()

    def to_dev(ph):
        return {k: jax.device_put(jnp.asarray(v))
                for k, v in ph.items()}

    def feeder():
        try:
            for batch in iterator:
                with telemetry.span("prefetch.stage",
                                    source="samediff"):
                    ph = make_ph(batch)
                    item = to_dev(ph) if thread_put else ph
                q.put(item)
                if telemetry.enabled():
                    telemetry.counter(
                        "dl4j_prefetch_batches_staged_total",
                        "batches staged by the device prefetcher"
                    ).inc()
            q.put(sentinel)
        except BaseException as e:       # noqa: BLE001 — re-raised below
            q.put(_FeederError(e))

    threading.Thread(target=feeder, daemon=True,
                     name="dl4j-tpu-samediff-prefetch").start()
    while True:
        if telemetry.enabled():
            t0 = time.perf_counter()
            item = q.get()
            telemetry.observe_feed_stall(time.perf_counter() - t0,
                                         source="samediff_prefetch")
        else:
            item = q.get()
        if item is sentinel:
            return
        if isinstance(item, _FeederError):
            raise item.exc
        yield item if thread_put else to_dev(item)


@dataclass
class TrainingConfig:
    updater: object = None                 # learning.updaters.IUpdater
    l1: float = 0.0
    l2: float = 0.0
    # placeholder names fed from DataSet features/labels, in order
    data_set_feature_mapping: List[str] = field(default_factory=list)
    data_set_label_mapping: List[str] = field(default_factory=list)
    data_set_feature_mask_mapping: List[str] = field(default_factory=list)
    data_set_label_mask_mapping: List[str] = field(default_factory=list)

    class Builder:
        def __init__(self):
            self._c = TrainingConfig()

        def updater(self, u):
            self._c.updater = u
            return self

        def l1(self, v):
            self._c.l1 = v
            return self

        def l2(self, v):
            self._c.l2 = v
            return self

        def data_set_feature_mapping(self, *names):
            self._c.data_set_feature_mapping = list(names)
            return self

        def data_set_label_mapping(self, *names):
            self._c.data_set_label_mapping = list(names)
            return self

        def data_set_feature_mask_mapping(self, *names):
            self._c.data_set_feature_mask_mapping = list(names)
            return self

        def data_set_label_mask_mapping(self, *names):
            self._c.data_set_label_mask_mapping = list(names)
            return self

        def build(self):
            if self._c.updater is None:
                raise ValueError("TrainingConfig needs an updater")
            return self._c

    # ------------------------------------------------------------------
    def placeholders_from(self, batch) -> Dict[str, np.ndarray]:
        """DataSet/MultiDataSet -> placeholder dict via the mappings."""
        ph = {}

        def as_list(x):
            return x if isinstance(x, (list, tuple)) else [x]

        feats = as_list(batch.features)
        for name, arr in zip(self.data_set_feature_mapping, feats):
            ph[name] = arr
        labs = as_list(batch.labels)
        for name, arr in zip(self.data_set_label_mapping, labs):
            ph[name] = arr
        fm = getattr(batch, "features_masks",
                     getattr(batch, "features_mask", None))
        if fm is not None and self.data_set_feature_mask_mapping:
            for name, arr in zip(self.data_set_feature_mask_mapping,
                                 as_list(fm)):
                if arr is not None:
                    ph[name] = arr
        lm = getattr(batch, "labels_masks",
                     getattr(batch, "labels_mask", None))
        if lm is not None and self.data_set_label_mask_mapping:
            for name, arr in zip(self.data_set_label_mask_mapping,
                                 as_list(lm)):
                if arr is not None:
                    ph[name] = arr
        return ph

    # -- serde ---------------------------------------------------------
    def to_map(self) -> dict:
        return {
            "updater": self.updater.to_map() if self.updater else None,
            "l1": self.l1, "l2": self.l2,
            "data_set_feature_mapping": self.data_set_feature_mapping,
            "data_set_label_mapping": self.data_set_label_mapping,
            "data_set_feature_mask_mapping":
                self.data_set_feature_mask_mapping,
            "data_set_label_mask_mapping":
                self.data_set_label_mask_mapping,
        }

    @staticmethod
    def from_map(m: dict) -> "TrainingConfig":
        from deeplearning4j_tpu.learning.updaters import IUpdater
        c = TrainingConfig()
        if m.get("updater"):
            c.updater = IUpdater.from_map(m["updater"])
        c.l1 = m.get("l1", 0.0)
        c.l2 = m.get("l2", 0.0)
        c.data_set_feature_mapping = m.get("data_set_feature_mapping", [])
        c.data_set_label_mapping = m.get("data_set_label_mapping", [])
        c.data_set_feature_mask_mapping = m.get(
            "data_set_feature_mask_mapping", [])
        c.data_set_label_mask_mapping = m.get(
            "data_set_label_mask_mapping", [])
        return c


class History:
    """Per-epoch training history (reference:
    org.nd4j.autodiff.listeners.records.History — loss curves PLUS
    the evaluation records ``fit`` collects on the validation iterator
    each epoch)."""

    def __init__(self):
        self.epoch_losses: List[List[float]] = []
        #: one dict per epoch: output-var name -> Evaluation-like
        #: object (empty dict for epochs without validation)
        self.epoch_evaluations: List[Dict[str, object]] = []
        #: mean validation loss per epoch (nan when not measured)
        self.validation_losses: List[float] = []

    def add_epoch(self, epoch: int, losses: List[float],
                  evaluations: Optional[Dict[str, object]] = None,
                  validation_loss: float = float("nan")):
        self.epoch_losses.append(losses)
        self.epoch_evaluations.append(dict(evaluations or {}))
        self.validation_losses.append(validation_loss)

    def final_loss(self) -> float:
        if not self.epoch_losses or not self.epoch_losses[-1]:
            return float("nan")
        return self.epoch_losses[-1][-1]

    def loss_curve(self) -> List[float]:
        return [l for ep in self.epoch_losses for l in ep]

    # -- evaluation records (reference: History.finalTrainingEvaluations
    # / getEvaluations) ------------------------------------------------
    def evaluations(self, name: str) -> List[object]:
        """Every recorded evaluation for output var ``name``, in epoch
        order (epochs without one are skipped)."""
        return [d[name] for d in self.epoch_evaluations if name in d]

    def final_evaluation(self, name: str):
        ev = self.evaluations(name)
        if not ev:
            raise KeyError(
                f"no evaluation recorded for {name!r} — pass "
                f"validation_iter/validation_evaluations to fit")
        return ev[-1]

    def validation_loss_curve(self) -> List[float]:
        return list(self.validation_losses)

    def __len__(self):
        return len(self.epoch_losses)
