"""Op validation framework (SURVEY.md §4.3:
`org.nd4j.autodiff.opvalidation.OpValidation` — declarative per-op
cases checking forward output AND analytic-vs-numeric gradients, plus
coverage accounting that FAILS when registered ops have no
validation).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set

import jax
import jax.numpy as jnp
import numpy as np

from .registry import OP_REGISTRY, get_op

#: ops validated so far (coverage accounting)
_VALIDATED: Set[str] = set()


@dataclass
class TestCase:
    """One op validation case (reference: OpValidation TestCase)."""
    op: str
    inputs: Sequence[np.ndarray]
    attrs: Optional[dict] = None
    expected: Optional[Sequence[np.ndarray]] = None
    #: reference fn computing expected outputs from inputs (numpy)
    expected_fn: Optional[Callable] = None
    gradient_check: bool = True
    #: which inputs get gradient-checked (default: all float inputs)
    grad_inputs: Optional[Sequence[int]] = None
    fwd_tol: float = 1e-5
    grad_tol: float = 2e-2
    #: float32 loss values quantize at ~scale*1e-7; a larger step
    #: keeps the difference above that noise (truncation error is
    #: O(eps^2) and stays far smaller for these smooth ops)
    epsilon: float = 1e-2
    max_entries: int = 8
    seed: int = 0


def validate(tc: TestCase) -> None:
    """Run one case; raises AssertionError with op context on any
    mismatch. Records the op as covered.

    Runs under ``default_matmul_precision('highest')``: validation is
    about op SEMANTICS, so the TPU's default bf16 matmul passes must
    not show up as forward mismatches."""
    with jax.default_matmul_precision("highest"):
        _validate_inner(tc)
    _VALIDATED.add(tc.op)


def _validate_inner(tc: TestCase) -> None:
    impl = get_op(tc.op)
    attrs = tc.attrs or {}
    ins = [jnp.asarray(a) for a in tc.inputs]

    out = impl(list(ins), attrs)
    outs = list(out) if isinstance(out, (list, tuple)) else [out]

    expected = tc.expected
    if expected is None and tc.expected_fn is not None:
        e = tc.expected_fn(*[np.asarray(a) for a in tc.inputs])
        expected = list(e) if isinstance(e, (list, tuple)) else [e]
    if expected is not None:
        assert len(expected) == len(outs), \
            f"{tc.op}: {len(outs)} outputs, expected {len(expected)}"
        for i, (got, want) in enumerate(zip(outs, expected)):
            np.testing.assert_allclose(
                np.asarray(got, np.float64),
                np.asarray(want, np.float64),
                atol=tc.fwd_tol, rtol=tc.fwd_tol,
                err_msg=f"{tc.op}: forward output {i} mismatch")

    if tc.gradient_check:
        _check_grads(tc, impl, attrs, ins)


def _check_grads(tc: TestCase, impl, attrs, ins):
    grad_idx = tc.grad_inputs
    if grad_idx is None:
        grad_idx = [i for i, a in enumerate(ins)
                    if jnp.issubdtype(a.dtype, jnp.floating)]
    if not grad_idx:
        return

    def scalar_loss(*wrt):
        full = list(ins)
        for j, i in enumerate(grad_idx):
            full[i] = wrt[j]
        out = impl(full, attrs)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        return sum(jnp.sum(o * o) for o in outs
                   if jnp.issubdtype(o.dtype, jnp.floating))

    wrt = [ins[i] for i in grad_idx]
    analytic = jax.grad(scalar_loss, argnums=tuple(range(len(wrt))))(
        *wrt)
    rng = np.random.RandomState(tc.seed)
    for j, (a, g) in enumerate(zip(wrt, analytic)):
        a64 = np.asarray(a, np.float64)
        g64 = np.asarray(g, np.float64)
        n = a64.size
        idxs = (range(n) if n <= tc.max_entries else
                rng.choice(n, tc.max_entries, replace=False))
        for fi in idxs:
            d = np.zeros(n)
            d[fi] = tc.epsilon
            d = d.reshape(a64.shape)

            def at(off):
                pert = [jnp.asarray((a64 + off).astype(np.float32))
                        if k == j else w for k, w in enumerate(wrt)]
                return float(scalar_loss(*pert))

            numeric = (at(d) - at(-d)) / (2 * tc.epsilon)
            ana = g64.reshape(-1)[fi]
            err = abs(numeric - ana)
            denom = max(abs(numeric), abs(ana))
            # absolute floor absorbs f32 loss quantization
            assert err <= 1e-3 or (denom > 0
                                   and err / denom <= tc.grad_tol), (
                f"{tc.op}: grad mismatch input {grad_idx[j]} "
                f"idx {fi}: analytic {ana:.6g} numeric {numeric:.6g}")


# -- coverage accounting ----------------------------------------------------
def validated_ops() -> Set[str]:
    return set(_VALIDATED)


def coverage_report(exclusions: Optional[Set[str]] = None) -> Dict:
    """reference: OpValidation coverage accounting — which registered
    ops have at least one validation case."""
    exclusions = exclusions or set()
    all_ops = set(OP_REGISTRY)
    covered = _VALIDATED & all_ops
    missing = all_ops - covered - exclusions
    return {"total": len(all_ops), "covered": len(covered),
            "missing": sorted(missing),
            "fraction": len(covered) / max(1, len(all_ops))}
