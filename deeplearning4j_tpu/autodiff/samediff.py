"""SameDiff-equivalent graph builder + executor.

Reference parity: ``org.nd4j.autodiff.samediff.SameDiff`` / ``SDVariable``
(SURVEY.md S1), autodiff (S2), sessions (S3), fit (S4), save/load (S5).
Call-stack parity: `SameDiff.output()` / `.fit()` (SURVEY.md §3.3).

TPU-first: the op DAG is evaluated by ONE traced-and-jitted function per
(outputs, placeholder-signature) — XLA sees the whole graph and fuses
it; `jax.value_and_grad` over that trace replaces the reference's
reverse-topo `doDiff` backward-graph construction; sessions/dependency
tracking/memory managers are unnecessary (XLA owns scheduling+memory).
"""
from __future__ import annotations

import enum
import io
import json
import logging
import zipfile
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("deeplearning4j_tpu")

from deeplearning4j_tpu.autodiff.registry import get_op
from deeplearning4j_tpu.common import layerprof

# ops that consume a PRNG key at execution time; the executor folds a
# per-op key out of the step rng (deterministic per op position)
RNG_OPS = {"dropout", "random_normal", "random_uniform",
           "random_bernoulli"}


class VariableType(enum.Enum):
    """Reference: org.nd4j.autodiff.samediff.VariableType."""
    VARIABLE = "VARIABLE"          # trainable
    CONSTANT = "CONSTANT"
    PLACEHOLDER = "PLACEHOLDER"
    ARRAY = "ARRAY"                # op output


class SDVariable:
    """Symbolic handle into a SameDiff graph (reference: SDVariable).
    Operator overloads build graph nodes; `.eval()` executes."""

    def __init__(self, sd: "SameDiff", name: str, var_type: VariableType,
                 shape=None, dtype=None):
        self.sd = sd
        self.name = name
        self.var_type = var_type
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype

    # -- graph-building sugar ------------------------------------------
    def _bin(self, other, op):
        other = self.sd._as_var(other)
        return self.sd._op(op, [self, other])

    def __add__(self, o):
        return self._bin(o, "add")

    def __radd__(self, o):
        return self.sd._as_var(o)._bin(self, "add")

    def __sub__(self, o):
        return self._bin(o, "sub")

    def __rsub__(self, o):
        return self.sd._as_var(o)._bin(self, "sub")

    def __mul__(self, o):
        return self._bin(o, "mul")

    def __rmul__(self, o):
        return self.sd._as_var(o)._bin(self, "mul")

    def __truediv__(self, o):
        return self._bin(o, "div")

    def __rtruediv__(self, o):
        return self.sd._as_var(o)._bin(self, "div")

    def __pow__(self, o):
        return self._bin(o, "pow")

    def __matmul__(self, o):
        return self._bin(o, "matmul")

    def __neg__(self):
        return self.sd._op("neg", [self])

    def __gt__(self, o):
        return self._bin(o, "gt")

    def __ge__(self, o):
        return self._bin(o, "gte")

    def __lt__(self, o):
        return self._bin(o, "lt")

    def __le__(self, o):
        return self._bin(o, "lte")

    # -- named methods (reference SDVariable surface) ------------------
    def add(self, o):
        return self.__add__(o)

    def sub(self, o):
        return self.__sub__(o)

    def mul(self, o):
        return self.__mul__(o)

    def div(self, o):
        return self.__truediv__(o)

    def rdiv(self, o):
        return self._bin(o, "rdiv")

    def mmul(self, o):
        return self._bin(o, "matmul")

    def dot(self, o):
        return self._bin(o, "dot")

    def sum(self, axis=None, keep_dims=False):
        return self.sd._op("reduce_sum", [self],
                           {"axis": axis, "keep_dims": keep_dims})

    def mean(self, axis=None, keep_dims=False):
        return self.sd._op("reduce_mean", [self],
                           {"axis": axis, "keep_dims": keep_dims})

    def max(self, axis=None, keep_dims=False):
        return self.sd._op("reduce_max", [self],
                           {"axis": axis, "keep_dims": keep_dims})

    def min(self, axis=None, keep_dims=False):
        return self.sd._op("reduce_min", [self],
                           {"axis": axis, "keep_dims": keep_dims})

    def std(self, axis=None, keep_dims=False):
        return self.sd._op("reduce_std", [self],
                           {"axis": axis, "keep_dims": keep_dims})

    def norm2(self, axis=None):
        return self.sd._op("reduce_norm2", [self], {"axis": axis})

    def argmax(self, axis=-1):
        return self.sd._op("argmax", [self], {"axis": axis})

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return self.sd._op("reshape", [self], {"shape": list(shape)})

    def permute(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return self.sd._op("permute", [self], {"axes": list(axes)})

    def transpose(self):
        return self.sd._op("permute", [self], {"axes": [1, 0]})

    def cast(self, dtype):
        return self.sd._op("cast", [self], {"dtype": str(dtype)})

    def rename(self, new_name: str) -> "SDVariable":
        self.sd._rename(self.name, new_name)
        self.name = new_name
        return self

    # -- execution -----------------------------------------------------
    def eval(self, placeholders: Optional[dict] = None) -> np.ndarray:
        return self.sd.output(placeholders or {}, [self.name])[self.name]

    def get_arr(self) -> Optional[np.ndarray]:
        a = self.sd._arrays.get(self.name)
        return np.asarray(a) if a is not None else None

    def set_arr(self, value):
        self.sd._arrays[self.name] = jnp.asarray(value)
        # constant values are baked into cached executors; invalidate
        if self.var_type is VariableType.CONSTANT:
            self.sd._exec_cache.clear()

    def __repr__(self):
        return (f"SDVariable(name='{self.name}', "
                f"type={self.var_type.value}, shape={self.shape})")


class OpNode:
    __slots__ = ("op_name", "inputs", "outputs", "attrs")

    def __init__(self, op_name, inputs, outputs, attrs):
        self.op_name = op_name
        self.inputs = inputs       # list of variable names
        self.outputs = outputs     # list of variable names
        self.attrs = attrs or {}


def _shard_placeholders(mesh, ph_vals: Dict, batch_names=None,
                        specs=None):
    """Shared DP placeholder contract of ``output(mesh=)`` and
    ``fit_steps(mesh=)``: batch dims shard over the mesh's ``data``
    axis, scalars replicate (``shard_batch`` passes them through),
    indivisible batches are rejected loudly. ``specs`` maps
    placeholder names to explicit ``PartitionSpec``s (or axis-name
    tuples) — those placeholders skip inference entirely and are
    device_put at the requested sharding, the escape hatch when the
    batch-dim vote below would guess wrong. Returns
    ``(ph_vals, mesh_sig)``; ``mesh_sig`` keys compiled-program
    caches (None when no mesh) and folds the explicit specs in."""
    if mesh is None:
        return ph_vals, None
    from jax.sharding import NamedSharding, PartitionSpec
    from deeplearning4j_tpu.parallel import replicate_tree, shard_batch
    if "data" not in mesh.axis_names:
        raise ValueError(
            f"mesh must have a 'data' axis, got {mesh.axis_names}")
    ndev = mesh.shape["data"]
    specs = dict(specs or {})
    for k in specs:
        if k not in ph_vals:
            raise ValueError(
                f"placeholder spec for unknown placeholder {k!r} "
                f"(have {sorted(ph_vals)})")
        if not isinstance(specs[k], PartitionSpec):
            sp = specs[k]
            specs[k] = PartitionSpec(*sp) if isinstance(
                sp, (tuple, list)) else PartitionSpec(sp)
    # batch placeholders shard; everything else replicates (GSPMD
    # semantics are identical either way; only batch tensors gain from
    # sharding). "Batch" = the leading dim of the feature/label-mapped
    # placeholders when the caller knows them (fit_steps passes the
    # TrainingConfig mappings); otherwise inferred as the most common
    # leading dim among non-scalar placeholders — ties break toward
    # dims that divide the data axis, then higher rank ([B,T] batch
    # outranks a [T] aux), then size
    batch = None
    inferred = False
    if batch_names:
        for k in batch_names:
            v = ph_vals.get(k)
            if v is not None and v.ndim > 0:
                batch = int(v.shape[0])
                break
    if batch is None:
        leads: dict = {}
        ranks: dict = {}
        for k, v in ph_vals.items():
            if k not in specs and v.ndim > 0:
                d = int(v.shape[0])
                leads[d] = leads.get(d, 0) + 1
                ranks[d] = max(ranks.get(d, 0), v.ndim)
        if leads:
            inferred = True
            batch = max(leads, key=lambda d: (
                leads[d], d % ndev == 0, ranks[d], d))
    if inferred:
        # the vote can be outvoted by aux placeholders that merely
        # share a leading dim: every loser gets REPLICATED, silently
        # giving up DP batch sharding for it (and bypassing the
        # divisibility check it would have hit as a batch tensor) —
        # warn about ANY excluded candidate, not just exact ties
        excluded = sorted(
            k for k, v in ph_vals.items()
            if k not in specs and v.ndim > 0
            and int(v.shape[0]) != batch)
        if excluded:
            log.warning(
                "batch-dim inference chose leading dim %d — "
                "placeholders %s (other leading dims) will be "
                "replicated, not batch-sharded. Pass explicit "
                "data_set_feature_mapping/label_mapping (or "
                "batch_names), or per-placeholder specs "
                "(ph_specs=...), to disambiguate.",
                batch, excluded)
    out = {}
    for k, v in ph_vals.items():
        if k in specs:
            out[k] = jax.device_put(v, NamedSharding(mesh, specs[k]))
        elif v.ndim > 0 and int(v.shape[0]) == batch:
            if v.shape[0] % ndev:
                raise ValueError(
                    f"placeholder {k!r} batch dim {v.shape} not "
                    f"divisible by data axis size {ndev}")
            out[k] = shard_batch(mesh, v)
        else:
            out[k] = replicate_tree(mesh, v)
    return out, (
        tuple(mesh.axis_names),
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        tuple(sorted((k, tuple(sp)) for k, sp in specs.items())))


def _write_samediff_zip(path, graph: dict, arrays: dict,
                        cf_arrays: dict, upd_leaves):
    """Write the SameDiff zip from already-host-resident state (shared
    by ``save`` and the async checkpoint snapshot's background
    ``write``)."""
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("graph.json", json.dumps(graph, indent=1))
        buf = io.BytesIO()
        np.savez(buf, **arrays, **cf_arrays)
        z.writestr("arrays.npz", buf.getvalue())
        if upd_leaves is not None:
            buf2 = io.BytesIO()
            np.savez(buf2, **{f"leaf_{i}": l
                              for i, l in enumerate(upd_leaves)})
            z.writestr("updater.npz", buf2.getvalue())


class SameDiff:
    """The graph. Build with var/constant/placeholder + op namespaces
    (sd.math, sd.nn, sd.cnn, sd.rnn, sd.loss, sd.image, sd.bitwise,
    sd.linalg, sd.random); run with output()/fit()."""

    def __init__(self):
        self.vars: Dict[str, SDVariable] = {}
        self.ops: List[OpNode] = []
        self._arrays: Dict[str, jnp.ndarray] = {}   # VARIABLE/CONSTANT
        self._producer: Dict[str, int] = {}          # var name -> op idx
        self._name_counter: Dict[str, int] = {}
        self._exec_cache: Dict = {}
        self._rng = jax.random.PRNGKey(0)
        self.loss_variables: List[str] = []
        self.training_config = None
        self._updater_state = None
        #: DpFlatSpec of the fsdp fit_steps window (parallel.zero);
        #: set by _build_raw_train_step(fsdp=True)
        self._fsdp_spec = None
        #: updater iteration, persisted across fit()/fit_steps() calls
        #: (Adam bias correction must not restart per call)
        self.iteration_count: int = 0
        self.epoch_count: int = 0
        #: TrainingListener bus (reference: SameDiff.setListeners /
        #: ListenerList — the SAME listener impls MLN/graph use:
        #: Score/Performance/Evaluative/Checkpoint attach unchanged)
        self.listeners: list = []
        self._retrace_guard = None
        self._score: float = float("nan")
        self.last_batch_size: int = 0
        #: sqrt(N) activation checkpointing for TRAINING programs:
        #: the op walk is cut into this many jax.checkpoint segments
        #: (only segment-boundary values are stored for backward).
        #: The memory lever for FLAT imported graphs, which have no
        #: layer structure to remat (see set_remat_segments)
        self.remat_segments: int = 0
        #: foreign-var captures (control-flow bodies closing over a
        #: parent graph): local name -> (owner SameDiff, owner name)
        self._captures: Dict[str, tuple] = {}
        #: names of this graph's VARIABLEs frozen into the closures of
        #: subgraphs owned by UNRELATED graphs — baked per compile, so
        #: fit() drops compiled programs after updating one of them.
        #: (Captures within one tracing chain — direct or nested — are
        #: live op inputs and never land here.)
        self._frozen_captured_vars: set = set()
        #: set while this graph is being traced as a control-flow
        #: subgraph (enables foreign-var capture in _op)
        self._tracing_parent = None
        from deeplearning4j_tpu.autodiff.opsets import (SDBitwise, SDCNN,
                                                        SDImage, SDLinalg,
                                                        SDLoss, SDMath,
                                                        SDNN, SDRandom,
                                                        SDRNN)
        self.math = SDMath(self)
        self.nn = SDNN(self)
        self.cnn = SDCNN(self)
        self.rnn = SDRNN(self)
        self.loss = SDLoss(self)
        self.image = SDImage(self)
        self.bitwise = SDBitwise(self)
        self.linalg = SDLinalg(self)
        self.random = SDRandom(self)

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    # -- naming --------------------------------------------------------
    def _unique(self, base: str) -> str:
        if base not in self.vars and base not in self._name_counter:
            self._name_counter[base] = 0
            return base
        n = self._name_counter.get(base, 0)
        while True:                      # skip user-taken suffixed names
            n += 1
            cand = f"{base}_{n}"
            if cand not in self.vars:
                self._name_counter[base] = n
                return cand

    def _rename(self, old: str, new: str):
        if new in self.vars:
            raise ValueError(f"variable '{new}' already exists")
        v = self.vars.pop(old)
        v.name = new
        self.vars[new] = v
        if old in self._arrays:
            self._arrays[new] = self._arrays.pop(old)
        if old in self._producer:
            self._producer[new] = self._producer.pop(old)
        for op_node in self.ops:
            op_node.inputs = [new if i == old else i
                              for i in op_node.inputs]
            op_node.outputs = [new if o == old else o
                               for o in op_node.outputs]
        self.loss_variables = [new if n == old else n
                               for n in self.loss_variables]
        self._exec_cache.clear()

    # -- variable creation (reference: sd.var/constant/placeHolder) ----
    def var(self, name: Optional[str] = None, shape=None,
            dtype=jnp.float32, *, init=None, array=None) -> SDVariable:
        """Trainable variable. Provide ``array`` or (``shape`` +
        optional weight-init ``init`` (WeightInit or callable))."""
        name = self._unique(name or "var")
        if array is not None:
            arr = jnp.asarray(array)
        else:
            if shape is None:
                raise ValueError("var needs shape or array")
            self._rng, k = jax.random.split(self._rng)
            if init is None:
                arr = jnp.zeros(shape, dtype)
            elif callable(getattr(init, "init", None)):
                fan_in = shape[0] if len(shape) >= 1 else 1
                fan_out = shape[-1] if len(shape) >= 2 else 1
                arr = init.init(k, tuple(shape), fan_in, fan_out, dtype)
            else:
                arr = init(k, tuple(shape), dtype)
        v = SDVariable(self, name, VariableType.VARIABLE, arr.shape,
                       arr.dtype)
        self.vars[name] = v
        self._arrays[name] = arr
        return v

    def constant(self, name_or_array, array=None) -> SDVariable:
        if array is None:
            name, array = None, name_or_array
        else:
            name = name_or_array
        arr = jnp.asarray(array)
        name = self._unique(name or "const")
        v = SDVariable(self, name, VariableType.CONSTANT, arr.shape,
                       arr.dtype)
        self.vars[name] = v
        self._arrays[name] = arr
        return v

    def placeholder(self, name: str, shape=None,
                    dtype=jnp.float32) -> SDVariable:
        name = self._unique(name)
        v = SDVariable(self, name, VariableType.PLACEHOLDER, shape, dtype)
        self.vars[name] = v
        return v

    place_holder = placeholder     # reference spelling

    def convert_to_variables(self, names: Sequence,
                             values: Optional[dict] = None):
        """Promote placeholders/constants to trainable VARIABLEs
        (reference: SameDiff.convertToVariable(s) — used after import
        to make trained tensors differentiable/trainable). ``values``
        supplies initial arrays for converted placeholders."""
        for n in names:
            name = n.name if isinstance(n, SDVariable) else n
            v = self.vars[name]
            if values and name in values:
                arr = jnp.asarray(values[name])
                self._arrays[name] = arr
                v.shape, v.dtype = arr.shape, arr.dtype
            if name not in self._arrays:
                raise ValueError(
                    f"convert_to_variables('{name}'): no stored value "
                    f"— pass one via values={{'{name}': array}}")
            v.var_type = VariableType.VARIABLE
        self._exec_cache.clear()

    convertToVariables = convert_to_variables

    def _as_var(self, x) -> SDVariable:
        if isinstance(x, SDVariable):
            return x
        return self.constant(jnp.asarray(x))

    # -- op creation ---------------------------------------------------
    def _op(self, op_name: str, inputs: Sequence[SDVariable],
            attrs: Optional[dict] = None, name: Optional[str] = None,
            n_out: int = 1) -> Union[SDVariable, Tuple[SDVariable, ...]]:
        get_op(op_name)               # validate early
        for v in inputs:
            if isinstance(v, SDVariable) and v.sd is not self and \
                    self._tracing_parent is None:
                raise ValueError(
                    f"variable '{v.name}' belongs to another SameDiff "
                    f"graph (cross-graph references are only valid "
                    f"inside control-flow bodies)")
        inputs = [self._import_foreign(v) if isinstance(v, SDVariable)
                  and v.sd is not self else v for v in inputs]
        in_names = [v.name for v in inputs]
        if n_out == 1:
            out_names = [self._unique(name or op_name)]
        else:
            base = name or op_name
            out_names = [self._unique(f"{base}:{i}")
                         for i in range(n_out)]
        node = OpNode(op_name, in_names, out_names, attrs)
        idx = len(self.ops)
        self.ops.append(node)
        outs = []
        for on in out_names:
            v = SDVariable(self, on, VariableType.ARRAY)
            self.vars[on] = v
            self._producer[on] = idx
            outs.append(v)
        self._exec_cache.clear()
        return outs[0] if n_out == 1 else tuple(outs)

    def invoke(self, op_name, inputs, attrs=None, name=None, n_out=1):
        """Public escape hatch: call any registered op by name."""
        return self._op(op_name, [self._as_var(i) for i in inputs],
                        attrs, name, n_out)

    def _import_foreign(self, v: "SDVariable") -> "SDVariable":
        """A var of ANOTHER SameDiff used here (control-flow bodies
        closing over parent vars): register it under a local capture
        name so it can never collide with this graph's own names —
        the subgraph runner resolves captures from the owner at call
        time."""
        for local, (sd, pname) in self._captures.items():
            if sd is v.sd and pname == v.name:
                return self.vars[local]
        local = self._unique(f"_cap_{v.name}")
        proxy = SDVariable(self, local, VariableType.PLACEHOLDER,
                           v.shape, v.dtype)
        self.vars[local] = proxy
        self._captures[local] = (v.sd, v.name)
        return proxy


    # -- execution -----------------------------------------------------
    def _ancestors(self, targets: Sequence[str]) -> List[int]:
        """Op indices needed to compute ``targets``, in execution order."""
        needed: set = set()
        stack = list(targets)
        seen_vars = set()
        while stack:
            vn = stack.pop()
            if vn in seen_vars:
                continue
            seen_vars.add(vn)
            if vn in self._producer:
                idx = self._producer[vn]
                if idx not in needed:
                    needed.add(idx)
                    stack.extend(self.ops[idx].inputs)
        return sorted(needed)

    def _execute(self, values: dict, op_indices: List[int], rng,
                 training: bool):
        for idx in op_indices:
            node = self.ops[idx]
            attrs = node.attrs
            if node.op_name in RNG_OPS:
                attrs = dict(attrs)
                attrs["rng"] = (jax.random.fold_in(rng, idx)
                                if rng is not None else None)
                if node.op_name == "dropout":
                    attrs["training"] = training
            ins = [values[i] for i in node.inputs]
            # layer-attribution scope (common.layerprof): tag the op's
            # trace — fwd and its autodiff transpose — with the first
            # output's name, so imported-graph HLO carries op identity
            with layerprof.scope("sd." + node.outputs[0]):
                out = get_op(node.op_name)(ins, attrs)
            if len(node.outputs) == 1:
                values[node.outputs[0]] = out
            else:
                for on, o in zip(node.outputs, out):
                    values[on] = o
        return values

    def _required_placeholders(self, op_indices, out_names):
        needed = set(out_names)
        for idx in op_indices:
            needed.update(self.ops[idx].inputs)
        return {n for n in needed
                if n in self.vars and
                self.vars[n].var_type is VariableType.PLACEHOLDER}

    def _build_fn(self, out_names: Tuple[str, ...], ph_names: Tuple[str,
                  ...], training: bool):
        op_indices = self._ancestors(list(out_names))
        missing = self._required_placeholders(op_indices, out_names) \
            - set(ph_names)
        if missing:
            raise ValueError(
                f"missing placeholder values for {sorted(missing)} "
                f"(required to compute {list(out_names)}; "
                f"provided: {sorted(ph_names)})")
        # restrict to the requested subgraph: variables/constants outside
        # it must not be shipped per call nor receive l1/l2 gradients
        needed = set(out_names)
        for idx in op_indices:
            needed.update(self.ops[idx].inputs)
        const_vals = {n: a for n, a in self._arrays.items()
                      if n in needed and
                      self.vars[n].var_type is VariableType.CONSTANT}
        var_names = [n for n, v in self.vars.items()
                     if n in needed and
                     v.var_type is VariableType.VARIABLE]

        def fn(var_vals: dict, ph_vals: dict, rng):
            values = dict(const_vals)
            values.update(var_vals)
            values.update(ph_vals)
            if training and self.remat_segments > 1 \
                    and len(op_indices) > 1:
                self._execute_segmented(values, op_indices, rng,
                                        training, out_names)
            else:
                self._execute(values, op_indices, rng, training)
            return [values[n] for n in out_names]

        return fn, var_names

    def _segment_cut_costs(self, op_indices: List[int],
                           out_names: Tuple[str, ...],
                           sizes: Optional[dict] = None):
        """``cost[c]`` = BYTES of intermediate values live across a
        cut placed before walk position ``c`` (produced earlier,
        consumed at/after ``c`` or a requested output) — the storage
        ``min_cut_segment_plan`` minimizes. ``sizes`` maps value name
        -> bytes (from the abstract shape pass); a missing entry
        counts 1, so with no size info this degrades to live-value
        counting."""
        n = len(op_indices)
        first_prod = {}
        last_read = {}
        for j, i in enumerate(op_indices):
            for name in self.ops[i].inputs:
                last_read[name] = j
            for name in self.ops[i].outputs:
                first_prod.setdefault(name, j)
        sizes = sizes or {}
        diff = np.zeros(n + 2)
        for name, j in first_prod.items():
            k = n if name in out_names else last_read.get(name, j)
            if k > j:
                w = float(sizes.get(name, 1.0))
                # crosses every cut c with j < c <= k
                diff[j + 1] += w
                diff[k + 1] -= w
        return np.cumsum(diff)[:n + 1]

    def _value_sizes(self, values: dict, op_indices: List[int], rng,
                     training: bool) -> dict:
        """Byte size of every intermediate value, via ONE abstract
        (shape-only) pass over the walk — jax.eval_shape runs no
        FLOPs and allocates nothing. Empty on failure (the cut costs
        then fall back to live-value counts)."""
        in_structs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                      for k, v in values.items()
                      if hasattr(v, "shape") and hasattr(v, "dtype")}

        def walk(vals_in):
            vals = dict(values)
            vals.update(vals_in)
            self._execute(vals, op_indices, rng, training)
            return vals

        try:
            out = jax.eval_shape(walk, in_structs)
            return {k: int(np.prod(v.shape)) * v.dtype.itemsize
                    for k, v in out.items()
                    if hasattr(v, "shape") and v.shape is not None}
        except Exception as e:                    # noqa: BLE001
            log.debug("abstract size pass failed (%s); min-cut falls "
                      "back to live-value counts", e)
            return {}

    def fuse_attention_patterns(self) -> int:
        """Attention-fusion pass (reference role: SameDiff's
        GraphOptimizer/OptimizationConfig): recognize the exporter's
        op-by-op attention and rewrite each occurrence to ONE fused
        ``sdpa_core`` op — now one pass of the full pipeline in
        autodiff.passes (see :meth:`optimize`). Kept as a standalone
        entry point for API compatibility: returns the number of
        sites fused; compiled-program caches are dropped when > 0."""
        from deeplearning4j_tpu.autodiff.passes import attention_fuse
        fused = attention_fuse(self)
        if fused:
            self._exec_cache.clear()
        return fused

    def optimize(self, passes=None) -> Dict[str, int]:
        """Run the full GraphOptimizer pass pipeline (autodiff.passes):
        cast folding, mask strength reduction, LayerNorm/GELU
        re-fusion, attention fusion — ordered, iterated to fixpoint.
        Importers invoke this automatically post-import unless
        DL4J_TPU_GRAPHOPT=0. Returns per-pass rewrite counts."""
        from deeplearning4j_tpu.autodiff.passes import GraphOptimizer
        return GraphOptimizer(self, passes=passes).run()

    def set_remat_segments(self, n: int):
        """Cut TRAINING forward programs into ``n`` ``jax.checkpoint``
        segments of the op walk (sqrt(N) activation checkpointing):
        only segment-boundary values are stored for backward,
        interiors are recomputed. This is the memory lever for flat
        IMPORTED graphs, which have no layer boundaries to remat —
        e.g. imported BERT-base OOMs at batch 1024 without it
        (BENCH_notes_r04.md). 0 disables. Compiled training programs
        bake the setting, so the caches are dropped."""
        self.remat_segments = int(n)
        self._exec_cache.clear()
        return self

    def _execute_segmented(self, values: dict, op_indices: List[int],
                           rng, training: bool,
                           out_names: Tuple[str, ...]):
        """The op walk in ``remat_segments`` contiguous
        ``jax.checkpoint`` segments, with liveness analysis so only
        values consumed later (or requested outputs) cross segment
        boundaries. Boundaries are MIN-CUT placed (fewest live values
        stored — on a flat imported transformer that finds the layer
        boundaries, where only the hidden state crosses, instead of
        cutting mid-attention where the O(t^2) scores are live). The
        per-op RNG is ``fold_in(rng, op idx)`` (same as the plain
        walk), so segmentation does not change the stream."""
        from deeplearning4j_tpu.common.remat import min_cut_segment_plan
        read_at = [set(self.ops[i].inputs) for i in op_indices]
        sizes = self._value_sizes(values, op_indices, rng, training)
        plan = min_cut_segment_plan(
            len(op_indices), self.remat_segments,
            self._segment_cut_costs(op_indices, out_names, sizes))
        for lo, hi, wrap in plan:
            seg = op_indices[lo:hi]
            produced = set()
            for i in seg:
                produced.update(self.ops[i].outputs)
            read = set()
            for j in range(lo, hi):
                read.update(read_at[j])
            needed_after = set(out_names)
            for j in range(hi, len(op_indices)):
                needed_after.update(read_at[j])
            seg_in = sorted((read - produced) & set(values))
            seg_out = sorted(produced & needed_after)

            def seg_fn(in_vals, seg=seg, seg_out=seg_out):
                vals = dict(in_vals)
                self._execute(vals, seg, rng, training)
                return {k: vals[k] for k in seg_out}

            if wrap:
                seg_fn = jax.checkpoint(seg_fn)
            outs = seg_fn({k: values[k] for k in seg_in})
            # prune: drop values dead past this boundary, keep the
            # rest (constants/vars/placeholders live in `values` too
            # and are needed by later segments' seg_in gathers)
            for k in list(values):
                if k not in needed_after:
                    del values[k]
            values.update(outs)

    def output(self, placeholders: dict, outputs: Sequence[str],
               *, training: bool = False,
               mesh=None, ph_specs=None) -> Dict[str, np.ndarray]:
        """Execute the graph (reference: SameDiff.output). The whole
        requested subgraph compiles to one XLA program, cached per
        (outputs, placeholder signature).

        ``mesh``: a ``jax.sharding.Mesh`` with a ``data`` axis runs
        inference DATA-PARALLEL — placeholder batch dims shard over
        ``data``, variables replicate (the batched-inference half of
        ``fit_steps(mesh=...)``). ``ph_specs`` maps placeholder names
        to explicit ``PartitionSpec``s when the batch-dim inference
        would guess wrong (see ``_shard_placeholders``)."""
        outputs = [o.name if isinstance(o, SDVariable) else o
                   for o in outputs]
        ph_vals = {k: jnp.asarray(v) for k, v in placeholders.items()}
        cfg = self.training_config
        ph_vals, mesh_sig = _shard_placeholders(
            mesh, ph_vals,
            batch_names=(cfg.data_set_feature_mapping +
                         cfg.data_set_label_mapping) if cfg else None,
            specs=ph_specs)
        sig = (tuple(outputs), training, mesh_sig,
               tuple(sorted((k, v.shape, str(v.dtype))
                            for k, v in ph_vals.items())))
        if sig not in self._exec_cache:
            _, _, fn, var_vals = self._prepare(placeholders, outputs,
                                               training)
            self._exec_cache[sig] = (jax.jit(fn), list(var_vals))
        jfn, var_names = self._exec_cache[sig]
        var_vals = {n: self._arrays[n] for n in var_names}
        if mesh is not None:
            from deeplearning4j_tpu.parallel import replicate_tree
            var_vals = replicate_tree(mesh, var_vals)
        self._rng, rng = jax.random.split(self._rng)
        res = jfn(var_vals, ph_vals, rng)
        return {n: np.asarray(r) for n, r in zip(outputs, res)}

    def _prepare(self, placeholders: dict, outputs: Sequence[str],
                 training: bool):
        """Shared preamble of output/to_stablehlo/export_serialized:
        name normalization, placeholder coercion, subgraph build,
        variable-value gather."""
        outputs = tuple(o.name if isinstance(o, SDVariable) else o
                        for o in outputs)
        ph_vals = {k: (v if isinstance(v, jax.ShapeDtypeStruct)
                       else jnp.asarray(v))
                   for k, v in placeholders.items()}
        fn, var_names = self._build_fn(outputs, tuple(ph_vals),
                                       training)
        var_vals = {n: self._arrays[n] for n in var_names}
        return outputs, ph_vals, fn, var_vals

    def to_stablehlo(self, placeholders: dict,
                     outputs: Sequence[str],
                     *, training: bool = False) -> str:
        """StableHLO text of the ONE compiled program this subgraph
        lowers to (SURVEY.md §2.7 item 1: the "StableHLO graph
        emitter" role of the reference's native graph runtime —
        here the emitter is the jax lowering of the already-built
        program; this is the portable, inspectable artifact).
        ``placeholders`` supply shapes/dtypes (arrays or
        ShapeDtypeStruct)."""
        _, ph_vals, fn, var_vals = self._prepare(placeholders,
                                                 outputs, training)
        lowered = jax.jit(fn).lower(var_vals, ph_vals,
                                    jax.random.PRNGKey(0))
        return lowered.as_text()

    def export_serialized(self, placeholders: dict,
                          outputs: Sequence[str],
                          *, training: bool = False) -> bytes:
        """Portable serialized program (``jax.export`` bytes: versioned
        StableHLO + calling convention) — the AOT hand-off artifact
        for serving runtimes.  The RNG key stays a program INPUT so
        stochastic graphs (dropout, random ops) are reseedable per
        call.  Round-trips with :func:`deserialize_and_call`."""
        from jax import export as jax_export
        _, ph_vals, fn, var_vals = self._prepare(placeholders,
                                                 outputs, training)

        def closed(ph, rng):
            return fn(var_vals, ph, rng)

        args = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                for k, v in ph_vals.items()}
        key_spec = jax.ShapeDtypeStruct(
            jax.random.PRNGKey(0).shape,
            jax.random.PRNGKey(0).dtype)
        exported = jax_export.export(jax.jit(closed))(args, key_spec)
        return bytes(exported.serialize())

    @staticmethod
    def deserialize_and_call(blob: bytes, placeholders: dict,
                             seed: int = 0):
        """Run a program serialized by :meth:`export_serialized`."""
        from jax import export as jax_export
        exported = jax_export.deserialize(bytearray(blob))
        return exported.call({k: jnp.asarray(v)
                              for k, v in placeholders.items()},
                             jax.random.PRNGKey(seed))

    # -- control flow (SURVEY.md S3 / Appendix A) ----------------------
    def _trace_subgraph(self, fn, n_args: int):
        """Trace a python-function subgraph into a CHILD SameDiff and
        return (callable, n_outputs). The callable replays the child
        graph on traced values — so while/cond/scan bodies lower into
        the parent's XLA program as lax control flow.

        Child-graph VARIABLES are frozen into the closure as constants
        (loop bodies can't own trainable state; thread it through the
        carry instead)."""
        child = SameDiff()
        child._tracing_parent = self
        proxies = [child.placeholder(f"_arg{i}", shape=None)
                   for i in range(n_args)]
        try:
            # zero-arg bodies have no proxy to learn the child graph
            # from; publish it on the callable (the TF importer's
            # function bodies read this to emit into the right graph)
            fn._trace_child_sd = child
        except (AttributeError, TypeError):
            pass
        res = fn(*proxies) if n_args else fn()
        outs = list(res) if isinstance(res, (list, tuple)) else [res]
        outs = [(o if o.sd is child else child._import_foreign(o))
                if isinstance(o, SDVariable) else child._as_var(o)
                for o in outs]
        out_names = [o.name for o in outs]
        proxy_names = [p.name for p in proxies]
        idxs = child._ancestors(out_names)
        # Closure capture: foreign vars the body referenced were
        # registered under collision-proof local names
        # (_import_foreign) mapping back to their owner graph.
        # Captures owned by THIS graph become extra op INPUTS — live,
        # differentiable values at runtime (a captured trainable
        # receives gradients through cond/scan and through
        # while_loop(max_iterations=N); an UNBOUNDED while_loop
        # raises on any gradient request through its outputs — XLA
        # while has no reverse rule, and silence would train wrong).
        # Captures owned by a graph FURTHER UP the tracing chain
        # (nested subgraphs) re-capture level-by-level, so they stay
        # live op inputs at every level and gradients flow the same
        # way.  Only captures of a genuinely UNRELATED graph are
        # frozen at trace time; their owner drops compiled programs
        # when such a variable trains.
        parent_caps = []     # (local_name, parent_name)
        frozen_caps = []     # (local_name, owner, owner_name)
        for local, (owner, pname) in child._captures.items():
            if owner is self:
                parent_caps.append((local, pname))
                continue
            anc = self._tracing_parent
            while anc is not None and anc is not owner:
                anc = anc._tracing_parent
            if anc is owner:
                # thread LIVE through this intermediate graph: the
                # re-captured proxy becomes a real op input here and
                # resolves one level up on the next trace
                proxy = self._import_foreign(owner.vars[pname])
                parent_caps.append((local, proxy.name))
                continue
            if pname not in owner._arrays:
                raise ValueError(
                    f"control-flow body captured '{pname}' from an "
                    f"outer subgraph where it has no stored value — "
                    f"thread it through the loop/branch arguments")
            if owner.vars[pname].var_type is VariableType.VARIABLE:
                owner._frozen_captured_vars.add(pname)
            frozen_caps.append((local, owner, pname))

        def call(*args):
            values = dict(child._arrays)
            for local, owner, pname in frozen_caps:
                values[local] = owner._arrays[pname]
            values.update(zip(proxy_names, args[:n_args]))
            values.update({local: v for (local, _), v in
                           zip(parent_caps, args[n_args:])})
            child._execute(values, idxs, None, False)
            return [values[n] for n in out_names]

        cap_vars = [self.vars[pname] for _, pname in parent_caps]
        # serializable description of the subgraph (sd.save writes it;
        # load rebuilds the call closure from it) — the live refs
        # (child, frozen owners) are resolved to arrays at save time
        spec = {"child": child, "frozen_caps": frozen_caps,
                "proxies": proxy_names, "outs": out_names,
                "parent_cap_locals": [l for l, _ in parent_caps]}
        return call, len(out_names), cap_vars, spec

    def while_loop(self, loop_vars: Sequence, cond_fn, body_fn,
                   name: Optional[str] = None,
                   max_iterations: Optional[int] = None):
        """Dynamic loop over the graph (reference: SameDiff whileLoop /
        TF-import Enter..Exit frames). ``cond_fn`` maps the loop vars
        to a scalar boolean; ``body_fn`` returns updated loop vars
        (same count/shapes).

        With ``max_iterations=N`` (TF ``maximum_iterations``
        semantics) the loop lowers to a bounded masked ``lax.scan`` —
        fully reverse-differentiable through loop vars and captures,
        truncating after N trips. Without it, the loop lowers to
        ``lax.while_loop``: unbounded, but forward-only — a gradient
        request through it raises loudly (never silently zeros)."""
        loop_vars = [self._as_var(v) for v in loop_vars]
        n = len(loop_vars)
        cond_call, _, cond_caps, cond_spec = self._trace_subgraph(
            cond_fn, n)
        body_call, n_body, body_caps, body_spec = self._trace_subgraph(
            body_fn, n)
        if n_body != n:
            raise ValueError(f"while_loop body returned {n_body} vars "
                             f"for {n} loop vars")
        return self._op("while_loop",
                        loop_vars + cond_caps + body_caps,
                        {"_cond_call": cond_call,
                         "_body_call": body_call,
                         "_cond_spec": cond_spec,
                         "_body_spec": body_spec,
                         "n_loop": n,
                         "n_cond_caps": len(cond_caps),
                         "n_body_caps": len(body_caps),
                         "max_iterations": max_iterations},
                        name=name, n_out=n)

    def cond(self, pred, true_fn, false_fn, operands: Sequence = (),
             name: Optional[str] = None):
        """``lax.cond`` (reference: TF-import Switch/Merge pairs).
        Both branches take ``operands`` and must return the same
        number of outputs. Differentiable."""
        operands = [self._as_var(v) for v in operands]
        t_call, nt, t_caps, t_spec = self._trace_subgraph(true_fn,
                                                          len(operands))
        f_call, nf, f_caps, f_spec = self._trace_subgraph(false_fn,
                                                          len(operands))
        if nt != nf:
            raise ValueError(f"cond branches disagree: {nt} vs {nf} "
                             f"outputs")
        return self._op("cond",
                        [self._as_var(pred)] + operands
                        + t_caps + f_caps,
                        {"_true_call": t_call, "_false_call": f_call,
                         "_true_spec": t_spec, "_false_spec": f_spec,
                         "n_operands": len(operands),
                         "n_true_caps": len(t_caps),
                         "n_false_caps": len(f_caps)},
                        name=name, n_out=nt)

    def scan(self, body_fn, init: Sequence, xs: Sequence = (),
             length: Optional[int] = None,
             name: Optional[str] = None):
        """``lax.scan``: ``body_fn(*carry, *x_slices) -> (new_carry...,
        y_outputs...)``. Returns final carries followed by stacked
        per-step outputs. Differentiable — the trainable-loop form
        (reference tBPTT-style loops compile to this)."""
        init = [self._as_var(v) for v in init]
        xs = [self._as_var(v) for v in xs]
        body_call, n_total, caps, body_spec = self._trace_subgraph(
            body_fn, len(init) + len(xs))
        if n_total < len(init):
            raise ValueError("scan body must return at least the "
                             "carry")
        return self._op("scan", init + xs + caps,
                        {"_body_call": body_call,
                         "_body_spec": body_spec,
                         "n_carry": len(init), "n_xs": len(xs),
                         "length": length},
                        name=name, n_out=n_total)

    def batch_output(self):
        """Fluent executor (reference: sd.batchOutput())."""
        sd = self

        class _Builder:
            def __init__(self):
                self._ph = {}
                self._outs = []

            def input(self, name, arr):
                self._ph[name if isinstance(name, str) else name.name] \
                    = arr
                return self

            def output(self, *names):
                self._outs.extend(n if isinstance(n, str) else n.name
                                  for n in names)
                return self

            def output_all(self):
                self._outs = [n for n, v in sd.vars.items()
                              if v.var_type is VariableType.ARRAY]
                return self

            def exec(self):
                return sd.output(self._ph, self._outs)

        return _Builder()

    # -- gradients (S2) ------------------------------------------------
    def set_loss_variables(self, *names):
        # accept varargs or a single list/tuple (reference overloads)
        if len(names) == 1 and isinstance(names[0], (list, tuple)):
            names = names[0]
        self.loss_variables = [n.name if isinstance(n, SDVariable) else n
                               for n in names]

    def calculate_gradients(self, placeholders: dict,
                            wrt: Sequence[str]) -> Dict[str, np.ndarray]:
        """Analytic gradients of the summed loss variables wrt the given
        VARIABLEs (reference: sd.calculateGradients)."""
        if not self.loss_variables:
            raise ValueError("call set_loss_variables first")
        wrt = [w.name if isinstance(w, SDVariable) else w for w in wrt]
        ph_vals = {k: jnp.asarray(v) for k, v in placeholders.items()}
        fn, var_names = self._build_fn(tuple(self.loss_variables),
                                       tuple(ph_vals), True)

        def loss_fn(wrt_vals):
            var_vals = {n: self._arrays[n] for n in var_names
                        if n not in wrt_vals}
            var_vals.update(wrt_vals)
            # deterministic key so random ops in the loss subgraph work
            outs = fn(var_vals, ph_vals, jax.random.PRNGKey(0))
            return sum(jnp.sum(o) for o in outs)

        grads = jax.grad(loss_fn)({n: self._arrays[n] for n in wrt})
        return {n: np.asarray(g) for n, g in grads.items()}

    # -- training (S4) -------------------------------------------------
    def set_training_config(self, config):
        self.training_config = config
        # compiled train steps bake the updater/regularization in
        self._exec_cache = {
            k: v for k, v in self._exec_cache.items()
            if not (isinstance(k, tuple) and k
                    and k[0] in ("train", "train_multi"))}

    def _build_raw_train_step(self, ph_names: Tuple[str, ...],
                              mesh=None, axis: str = "data",
                              fsdp: bool = False, tp_specs=None,
                              dense_tail: bool = False,
                              encoding=None):
        cfg = self.training_config
        fn, var_names = self._build_fn(tuple(self.loss_variables),
                                       ph_names, True)
        trainable = [n for n in var_names]
        updater = cfg.updater
        tp_specs = ({n: s for n, s in (tp_specs or {}).items()
                     if n in trainable} if mesh is not None else {})

        def dense_loss(tv, ph_vals, rng):
            if tp_specs:
                # 2D mode: pin tp variables to their compute spec; the
                # custom-vjp pin sends the cotangent to the resident
                # spec, so dp grad collectives stay on the data axis
                from deeplearning4j_tpu.parallel.zero import \
                    pin_tp_entry
                tv = pin_tp_entry(tv, mesh, tp_specs)
            outs = fn(tv, ph_vals, rng)
            total = sum(jnp.sum(o) for o in outs)
            if cfg.l2:
                total = total + 0.5 * cfg.l2 * sum(
                    jnp.sum(v * v) for v in tv.values())
            if cfg.l1:
                total = total + cfg.l1 * sum(
                    jnp.sum(jnp.abs(v)) for v in tv.values())
            return total

        if fsdp:
            # ZeRO-3: var_vals travel as the single flat shard dict
            # ({FSDP_KEY: {dtype: flat}}, resident 1/N along the data
            # axis); the forward gathers them through the custom-vjp
            # gather, so the grad cotangent is born reduce-scattered
            # and the tail never all-gathers the new variables.
            # Tensor-parallel variables (tp_specs) never enter the
            # flats: they ride under TP_KEY at full logical shape,
            # resident-sharded over model(×data) via their specs
            from deeplearning4j_tpu.learning.updaters import (
                FSDP_KEY, TP_KEY, dp_flatten_spec)
            from deeplearning4j_tpu.parallel.zero import (
                apply_update_fsdp, apply_update_tp, fsdp_gather,
                merge_tp_state, split_tp_state)
            spec = dp_flatten_spec(
                {n: self._arrays[n] for n in trainable
                 if n not in tp_specs},
                mesh.shape[axis])
            self._fsdp_spec = spec

            def fsdp_step(var_vals, upd_state, ph_vals, iteration, rng):
                def loss_fn(fv):
                    tv = fsdp_gather(fv[FSDP_KEY], spec, mesh, axis)
                    if tp_specs:
                        # dense_loss pins these to the compute spec
                        tv = {**tv, **fv[TP_KEY]}
                    return dense_loss(tv, ph_vals, rng)

                loss, grads = jax.value_and_grad(loss_fn)(var_vals)
                st_rest, st_tp = split_tp_state(upd_state)
                new_flat, new_state = apply_update_fsdp(
                    updater, grads[FSDP_KEY], var_vals[FSDP_KEY],
                    st_rest, iteration, mesh, axis)
                new_vars = {FSDP_KEY: new_flat}
                if tp_specs:
                    new_tp, us_tp = apply_update_tp(
                        updater, grads[TP_KEY], var_vals[TP_KEY],
                        st_tp, iteration, mesh, tp_specs,
                        gather_params=False)
                    new_vars[TP_KEY] = new_tp
                    new_state = merge_tp_state(new_state, us_tp)
                return new_vars, new_state, loss

            return fsdp_step, trainable

        def step(var_vals, upd_state, ph_vals, iteration, rng):
            loss, grads = jax.value_and_grad(
                lambda tv: dense_loss(tv, ph_vals, rng))(var_vals)
            if mesh is not None and not dense_tail:
                # ZeRO-1 sharded tail (parallel.zero): updater + state
                # on 1/N shards; new_vars come back replicated and in
                # each variable's own dtype. Tensor-parallel variables
                # get their own elementwise tail (apply_update_tp)
                # pinned to the model-axis layout
                from deeplearning4j_tpu.parallel.zero import (
                    apply_update_encoded, apply_update_sharded,
                    apply_update_tp, merge_tp_state, split_tp_entry,
                    split_tp_state)
                if encoding is not None:
                    # encoded rung: compress the flat dp gradient
                    # before the collective (error-feedback state under
                    # ENCODED_KEY); tp leaves below keep the
                    # uncompressed elementwise tail
                    import functools as _ft
                    apply_dp = _ft.partial(apply_update_encoded,
                                           encoding=encoding)
                else:
                    apply_dp = apply_update_sharded
                if tp_specs:
                    g_rest, g_tp = split_tp_entry(grads, tp_specs)
                    p_rest, p_tp = split_tp_entry(var_vals, tp_specs)
                    st_rest, st_tp = split_tp_state(upd_state)
                    if g_rest:
                        new_rest, new_state = apply_dp(
                            updater, g_rest, p_rest, st_rest,
                            iteration, mesh, axis)
                    else:
                        new_rest, new_state = p_rest, st_rest
                    new_tp, us_tp = apply_update_tp(
                        updater, g_tp, p_tp, st_tp, iteration, mesh,
                        tp_specs, gather_params=True)
                    return ({**new_rest, **new_tp},
                            merge_tp_state(new_state, us_tp), loss)
                new_vars, new_state = apply_dp(
                    updater, grads, var_vals, upd_state, iteration,
                    mesh, axis)
                return new_vars, new_state, loss
            updates, new_state = updater.apply(grads, upd_state,
                                               iteration)
            # updater math (bias corrections etc.) may run in f32;
            # apply it at full precision, then keep each variable's
            # own dtype — without the cast, bf16 variables silently
            # promote to f32 after one step (and recompile the step)
            new_vars = jax.tree_util.tree_map(
                lambda p, u: (p - u).astype(p.dtype),
                var_vals, updates)
            return new_vars, new_state, loss

        return step, trainable

    def _build_train_step(self, ph_names: Tuple[str, ...]):
        from deeplearning4j_tpu.common.compilecache import \
            enable_persistent_cache
        enable_persistent_cache()    # second process loads, not compiles
        step, trainable = self._build_raw_train_step(ph_names)
        return jax.jit(step, donate_argnums=(0, 1)), trainable

    def fit_steps(self, placeholders: Dict, n_steps: int,
                  mesh=None, update_exchange="auto", tp_specs=None,
                  ph_specs=None, encoding=None) -> float:
        """``n_steps`` train-step updates on ONE fixed placeholder
        batch inside a single ``lax.fori_loop`` dispatch, syncing on
        the final loss once. The benchmark-grade loop (same recipe as
        ``MultiLayerNetwork.fit_steps``): per-step dispatch + loss
        sync through a TPU tunnel is a fixed tax that the fori-loop
        amortizes. Per-step RNG is ``fold_in(rng, i)``; the updater
        iteration continues from ``self.iteration_count`` (shared with
        ``fit``), so chained calls don't re-apply Adam bias-correction
        warmup: ``fit_steps(b, 5)`` twice == ``fit_steps(b, 10)``.

        ``mesh``: a ``jax.sharding.Mesh`` with a ``data`` axis trains
        the program DATA-PARALLEL — every placeholder's leading axis
        is sharded over ``data``, variables/updater state are
        replicated, and GSPMD inserts the gradient all-reduce inside
        the compiled step (the ParallelWrapper recipe applied to an
        imported/authored SameDiff program; no reference equivalent —
        SameDiff in the reference is single-device).

        A 2D ``(data, model)`` mesh trains TENSOR-PARALLEL on top:
        eligible variables (``parallel.speclayout`` inference, or an
        explicit ``tp_specs`` name→``TpLeafSpec`` dict) are physically
        sharded over ``model`` and updated through ``apply_update_tp``
        — they never enter the dp flat ravels, so dp collectives stay
        on the ``data`` axis. ``ph_specs`` maps placeholder names to
        explicit ``PartitionSpec``s (see ``_shard_placeholders``).

        ``update_exchange="encoded"`` selects the compressed-collective
        rung: the flat dp gradient is quantized/sparsified before the
        data-axis exchange with per-replica error-feedback residuals
        (``parallel.encoding``); ``encoding=`` takes an
        ``EncodingSpec`` or scheme string (``"threshold"``/``"int8"``/
        ``"1bit"``)."""
        cfg = self.training_config
        if cfg is None:
            raise ValueError("call set_training_config first")
        if not self.loss_variables:
            raise ValueError("call set_loss_variables first")
        ph_vals = {k: jnp.asarray(v) for k, v in placeholders.items()}
        ph_vals, mesh_sig = _shard_placeholders(
            mesh, ph_vals, batch_names=(cfg.data_set_feature_mapping +
                                        cfg.data_set_label_mapping),
            specs=ph_specs)
        from deeplearning4j_tpu.parallel.zero import (
            UpdateExchange, resolve_update_exchange)
        mode = resolve_update_exchange(mesh, requested=update_exchange)
        sharded = mode is UpdateExchange.SHARDED
        fsdp = mode is UpdateExchange.FSDP
        encoded = mode is UpdateExchange.ENCODED
        if encoded:
            from deeplearning4j_tpu.parallel.encoding import \
                resolve_encoding
            encoding = resolve_encoding(encoding)
        else:
            encoding = None
        tp = (int(mesh.shape.get("model", 1)) if mesh is not None
              else 1)
        if mesh is None or tp <= 1:
            tp_specs = {}
        elif tp_specs is None:
            from deeplearning4j_tpu.parallel.speclayout import \
                SpecLayout
            tp_specs = SpecLayout(mesh).infer_entry(
                {n: v for n, v in self._arrays.items()
                 if self.vars[n].var_type is VariableType.VARIABLE},
                shard_over_data=sharded or fsdp or encoded)
        tp_sig = tuple(sorted(
            (n, tuple(s.compute), tuple(s.resident))
            for n, s in tp_specs.items())) or None
        enc_sig = encoding.signature() if encoding is not None else None
        key = (tuple(sorted(ph_vals)), mesh_sig, mode.value, tp_sig,
               enc_sig)
        cached = self._exec_cache.get(("train_multi", key))
        if cached is None:
            from deeplearning4j_tpu.common.compilecache import \
                enable_persistent_cache
            enable_persistent_cache()
            raw, trainable = self._build_raw_train_step(
                tuple(ph_vals),
                mesh if (sharded or fsdp or encoded or tp_specs)
                else None,
                fsdp=fsdp, tp_specs=tp_specs,
                dense_tail=not (sharded or fsdp or encoded),
                encoding=encoding)

            def multi(var_vals, upd_state, ph, rng, it0, n):
                def body(i, carry):
                    vv, us, _ = carry
                    vv, us, loss = raw(vv, us, ph, it0 + i,
                                       jax.random.fold_in(rng, i))
                    return vv, us, jnp.float32(loss)

                return jax.lax.fori_loop(
                    0, n, body,
                    (var_vals, upd_state, jnp.float32(0)))

            cached = (jax.jit(multi, static_argnums=(5,),
                              donate_argnums=(0, 1)), trainable)
            self._exec_cache[("train_multi", key)] = cached
        multi_fn, trainable = cached
        # checked on EVERY call (not just compile): a subgraph traced
        # after the first fit_steps can freeze a trainable into a
        # closure, and the cached fori program would keep reusing the
        # stale baked capture while training the variable
        if self._frozen_captured_vars \
                and self._frozen_captured_vars & set(trainable):
            raise ValueError(
                "fit_steps cannot train variables frozen into "
                "nested-subgraph closures (their values are baked "
                "per compile; the fori-loop would keep reusing "
                "stale captures) — use fit(), which retraces per "
                "step in that case")
        if self._updater_state is None:
            self._updater_state = cfg.updater.init_state(
                {n: self._arrays[n] for n in trainable})
            self._restore_updater_leaves()
        self._updater_trainable = list(trainable)
        var_vals = {n: self._arrays[n] for n in trainable}
        tp_specs = {n: s for n, s in tp_specs.items() if n in var_vals}
        # layout sync: the sharded/fsdp steps consume/produce the
        # ZeRO-1 flat state (tp variables split out under TP_KEY); the
        # dense step the per-variable slot trees
        flat_state = sharded or fsdp or encoded
        from deeplearning4j_tpu.learning.updaters import (has_tp,
                                                          is_dp_sharded,
                                                          is_encoded)
        if encoded and self._updater_state is not None:
            # encoded flats + error-feedback residual injected when
            # absent (first fit, or a dense/sharded checkpoint
            # restored into an encoded run on any device count)
            from deeplearning4j_tpu.parallel.zero import \
                ensure_encoded_state
            self._updater_state = ensure_encoded_state(
                var_vals, self._updater_state, mesh.shape["data"],
                encoding, tp_names=tuple(tp_specs))
        elif flat_state and self._updater_state:
            # idempotent: a state already raveled for this world size
            # and tp split passes through untouched (a residual left by
            # an encoded run is stripped — it belongs to that exchange)
            from deeplearning4j_tpu.parallel.zero import (
                strip_encoded_state, to_sharded_state)
            self._updater_state = to_sharded_state(
                var_vals, strip_encoded_state(self._updater_state),
                mesh.shape["data"], tp_names=tuple(tp_specs))
        elif not flat_state and (is_dp_sharded(self._updater_state)
                                 or has_tp(self._updater_state)
                                 or is_encoded(self._updater_state)):
            from deeplearning4j_tpu.parallel.zero import (
                strip_encoded_state, to_dense_state)
            self._updater_state = strip_encoded_state(
                to_dense_state(var_vals, self._updater_state))
        self._rng, rng = jax.random.split(self._rng)
        if mesh is not None:
            from deeplearning4j_tpu.parallel import replicate_tree
            if fsdp:
                # variables enter the flat resident layout: 1/N per
                # replica along the data axis for the whole fori window
                # (tp variables resident at their model(×data) spec)
                from deeplearning4j_tpu.learning.updaters import (
                    FSDP_KEY, TP_KEY, dp_ravel)
                from deeplearning4j_tpu.parallel.mesh import flat_sharding
                rest = {n: v for n, v in var_vals.items()
                        if n not in tp_specs}
                flats, _ = dp_ravel(rest, mesh.shape["data"],
                                    self._fsdp_spec)
                shard = flat_sharding(mesh, "data")
                vv = {FSDP_KEY: {dt: jax.device_put(v, shard)
                                 for dt, v in flats.items()}}
                if tp_specs:
                    from deeplearning4j_tpu.parallel.zero import \
                        place_tp_params
                    vv[TP_KEY] = place_tp_params(
                        mesh, {"v": {n: var_vals[n] for n in tp_specs}},
                        {"v": tp_specs}, resident=True)["v"]
                var_vals = vv
            elif tp_specs:
                # dense×tp / sharded×tp: tp variables live at their
                # compute sharding, the rest replicate
                from deeplearning4j_tpu.parallel.zero import \
                    place_tp_params
                var_vals = place_tp_params(
                    mesh, {"v": var_vals}, {"v": tp_specs})["v"]
            else:
                var_vals = replicate_tree(mesh, var_vals)
            if flat_state:
                # 1/N of the optimizer state per replica — the HBM win
                from deeplearning4j_tpu.parallel.zero import \
                    place_updater_states
                self._updater_state = place_updater_states(
                    mesh, {"state": self._updater_state},
                    tp_specs={"state": tp_specs})["state"]
            else:
                self._updater_state = replicate_tree(
                    mesh, self._updater_state)
            rng = replicate_tree(mesh, rng)
        from deeplearning4j_tpu.common import diagnostics, telemetry
        with telemetry.step_span("SameDiff", steps=n_steps) as sp:
            new_vars, self._updater_state, loss = multi_fn(
                var_vals, self._updater_state, ph_vals, rng,
                jnp.asarray(self.iteration_count), n_steps)
        if fsdp:
            # _arrays stay dense between calls (output()/getters read
            # them directly); the densify gather is timed into
            # dl4j_fsdp_gather_seconds
            from deeplearning4j_tpu.parallel.zero import params_to_dense
            new_vars = params_to_dense(
                {"vars": new_vars}, {"vars": self._fsdp_spec})["vars"]
        self._arrays.update(new_vars)
        self.iteration_count += n_steps
        diagnostics.after_step(self, "SameDiff",
                               self.iteration_count - 1, loss, sp,
                               params=new_vars, steps=n_steps)
        self._score = float(loss)
        first = next(iter(ph_vals.values()), None)
        if first is not None and first.ndim:
            self.last_batch_size = int(first.shape[0])
        # one listener round per fori group with the final loss (the
        # MLN fit_steps contract): checkpoints/score logging still
        # attach to the benchmark-grade loop
        for lis in self.listeners:
            lis.iteration_done(self, self.iteration_count - 1,
                               self.epoch_count)
        return self._score

    # -- listener bus (reference: SameDiff.setListeners; SURVEY S4/S8:
    # the same TrainingListener impls as MLN/graph) ---------------------
    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def score(self) -> float:
        """Loss of the most recent train step (TrainingListener
        surface: ScoreIterationListener calls ``model.score()``)."""
        return float(self._score)

    def _run_validation(self, iterator, evaluations, placeholders_fn):
        """One pass over the validation iterator: mean loss + the
        requested per-output-var evaluations (reference: SameDiff.fit's
        validation ``History`` records)."""
        cfg = self.training_config
        evals = {}
        for name, spec in (evaluations or {}).items():
            factory, label_idx = (spec if isinstance(spec, tuple)
                                  else (spec, 0))
            evals[name] = (factory(), label_idx)
        if hasattr(iterator, "reset"):
            iterator.reset()
        data = iterator
        if hasattr(data, "features"):
            data = [data]
        losses, n = [], 0
        want = list(evals) + [v for v in self.loss_variables
                              if v not in evals]
        for batch in data:
            ph = (placeholders_fn(batch) if placeholders_fn
                  else cfg.placeholders_from(batch))
            out = self.output(ph, want)
            bl = sum(float(jnp.sum(out[v]))
                     for v in self.loss_variables)
            losses.append(bl)
            n += 1
            if not evals:
                continue     # loss-only validation: batches need not
            # labels come from the label-mapped placeholders when the
            # mapping names them (covers placeholders_fn dict batches),
            # else from the DataSet protocol       carry .labels at all
            if cfg.data_set_label_mapping and all(
                    n in ph for n in cfg.data_set_label_mapping):
                labels = [ph[n] for n in cfg.data_set_label_mapping]
            else:
                labels = getattr(batch, "labels", None)
                if labels is None:
                    raise ValueError(
                        "validation evaluation needs labels: map them "
                        "via data_set_label_mapping or provide "
                        "batches with a .labels attribute")
                labels = (labels if isinstance(labels, (list, tuple))
                          else [labels])
            for name, (e, li) in evals.items():
                e.eval(np.asarray(labels[li]), np.asarray(out[name]))
        val_loss = float(np.mean(losses)) if n else float("nan")
        return {k: e for k, (e, _) in evals.items()}, val_loss

    def fit(self, iterator=None, *, n_epochs: int = 1,
            placeholders_fn=None, listeners=None, validation_iter=None,
            validation_evaluations=None, validation_frequency: int = 1):
        """fit(MultiDataSetIterator-like). Each element must provide the
        placeholder dict via training_config's feature/label mappings
        (reference: TrainingConfig dataSetFeatureMapping), or supply
        ``placeholders_fn(batch) -> dict``.

        ``listeners``: extra TrainingListeners for this call (on top of
        ``set_listeners``'s) — Score/Performance/Evaluative/Checkpoint
        impls attach unchanged (the r4 verdict's S4 gap: imported
        models used to train blind).
        ``validation_iter`` + ``validation_evaluations``
        ({output_var: Evaluation-factory or (factory, label_index)}):
        evaluated every ``validation_frequency`` epochs; results land
        in the returned History's evaluation records."""
        from deeplearning4j_tpu.autodiff.training import (
            History, device_prefetch_placeholders)
        from deeplearning4j_tpu.common.environment import Environment
        cfg = self.training_config
        if cfg is None:
            raise ValueError("call set_training_config first")
        if not self.loss_variables:
            raise ValueError("call set_loss_variables first")
        all_listeners = self.listeners + list(listeners or [])
        history = History()
        step_fn = None
        trainable = None
        iteration = self.iteration_count
        env = Environment.get()

        def make_ph(batch):
            # host-side mapping only; the staging generator (or the
            # sync fallback below) owns the device conversion
            return (placeholders_fn(batch) if placeholders_fn
                    else cfg.placeholders_from(batch))

        for epoch in range(n_epochs):
            for lis in all_listeners:
                lis.on_epoch_start(self)
            if hasattr(iterator, "reset"):
                iterator.reset()
            epoch_losses = []
            # device-prefetch: make_ph + the H2D copies run on a feeder
            # thread a batch ahead of the step loop
            staged = (device_prefetch_placeholders(
                          iterator, make_ph,
                          depth=env.device_prefetch_depth)
                      if env.device_prefetch
                      else ({k: jnp.asarray(v)
                             for k, v in make_ph(b).items()}
                            for b in iterator))
            for ph_vals in staged:
                if self._retrace_guard is None:
                    from deeplearning4j_tpu.common.compilecache import \
                        RetraceGuard
                    self._retrace_guard = RetraceGuard(
                        "SameDiff train step")
                self._retrace_guard.record(
                    *(ph_vals[k] for k in sorted(ph_vals)))
                if step_fn is None:
                    # cache the COMPILED step across fit() calls: a
                    # fresh jax.jit wrapper per fit would recompile
                    # the whole program every call (measured 110x on
                    # imported BERT-base — BENCH_notes_r04.md)
                    key = tuple(sorted(ph_vals))
                    cached = self._exec_cache.get(("train", key))
                    if cached is None:
                        cached = self._build_train_step(
                            tuple(ph_vals))
                        self._exec_cache[("train", key)] = cached
                    step_fn, trainable = cached
                    if self._updater_state is None:
                        self._updater_state = cfg.updater.init_state(
                            {n: self._arrays[n] for n in trainable})
                        self._restore_updater_leaves()
                    self._updater_trainable = list(trainable)
                var_vals = {n: self._arrays[n] for n in trainable}
                from deeplearning4j_tpu.learning.updaters import \
                    is_dp_sharded
                if is_dp_sharded(self._updater_state):
                    # left over from a ZeRO-1 fit_steps(mesh=...) run;
                    # this dense step needs the slot-tree layout
                    from deeplearning4j_tpu.parallel.zero import \
                        to_dense_state
                    self._updater_state = to_dense_state(
                        var_vals, self._updater_state)
                self._rng, rng = jax.random.split(self._rng)
                from deeplearning4j_tpu.common import (diagnostics,
                                                       telemetry)
                with telemetry.step_span("SameDiff") as sp:
                    new_vars, self._updater_state, loss = step_fn(
                        var_vals, self._updater_state, ph_vals,
                        jnp.asarray(iteration), rng)
                self._arrays.update(new_vars)
                # loss-only watchdog (grads stay fused in the step);
                # a trip scans the just-updated variables for the
                # first poisoned leaf
                diagnostics.after_step(self, "SameDiff", iteration,
                                       loss, sp, params=new_vars)
                if self._frozen_captured_vars \
                        and self._frozen_captured_vars & set(new_vars):
                    # a NESTED subgraph froze one of the variables we
                    # just trained — its value is baked per compile,
                    # so drop BOTH compiled-program caches (output()
                    # programs and this loop's step_fn). Retrace per
                    # step is the price of freezing trainables into
                    # nested closures; thread them through loop args
                    # to avoid it.
                    self._exec_cache.clear()
                    step_fn = None
                epoch_losses.append(float(loss))
                self._score = epoch_losses[-1]
                first = next(iter(ph_vals.values()))
                self.last_batch_size = (int(first.shape[0])
                                        if first.ndim else 0)
                # advance the counter BEFORE listeners fire (the
                # MLN/fit_steps convention): an iteration-triggered
                # checkpoint must serialize the post-step count, so a
                # resumed job does not re-apply the consumed updater
                # index. Listeners get the just-consumed index and the
                # MODEL-lifetime epoch count, like MLN's bus.
                iteration += 1
                self.iteration_count = iteration
                for lis in all_listeners:
                    lis.iteration_done(self, iteration - 1,
                                       self.epoch_count)
            evals, val_loss = {}, float("nan")
            if validation_iter is not None and \
                    (epoch + 1) % max(1, validation_frequency) == 0:
                evals, val_loss = self._run_validation(
                    validation_iter, validation_evaluations,
                    placeholders_fn)
            history.add_epoch(epoch, epoch_losses, evals, val_loss)
            # epoch count advances BEFORE listeners fire (an epoch-end
            # checkpoint must serialize the true count — MLN contract)
            self.epoch_count += 1
            for lis in all_listeners:
                lis.on_epoch_end(self)
        return history

    def _restore_updater_leaves(self):
        """Graft updater leaves saved by ``save`` onto the freshly-built
        state tree (same graph + updater -> same treedef), so a loaded
        model resumes with its optimizer moments intact."""
        loaded = getattr(self, "_loaded_updater_leaves", None)
        if loaded is None:
            return
        leaves, treedef = jax.tree_util.tree_flatten(self._updater_state)
        if len(leaves) != len(loaded):
            raise ValueError(
                f"saved updater state has {len(loaded)} leaves, current "
                f"updater expects {len(leaves)} — updater/graph changed "
                f"since save")
        self._updater_state = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(l) for l in loaded])
        self._loaded_updater_leaves = None

    # -- serialization (S5) --------------------------------------------
    def save(self, path: str, save_updater_state: bool = True):
        """Zip: graph.json + arrays.npz (+ updater npz) — the same
        contract as the reference .fb (graph + params + updater state +
        training config)."""
        _write_samediff_zip(path,
                            *self._serialized_state(save_updater_state))

    def checkpoint_snapshot(self):
        """Host-side snapshot for the async CheckpointListener: every
        array is copied device->host NOW; ``write(path)`` can then run
        on a background thread while training keeps mutating this
        graph (the same contract as utils.checkpoint._ModelSnapshot
        for MLN/graph models)."""
        graph, arrays, cf_arrays, upd = self._serialized_state(True)

        class _Snap:
            def write(s, path):
                _write_samediff_zip(path, graph, arrays, cf_arrays, upd)
        return _Snap()

    def _serialized_state(self, save_updater_state: bool):
        cf_arrays: dict = {}   # control-flow subgraph constants/captures
        graph = {
            "variables": [
                {"name": v.name, "type": v.var_type.value,
                 "shape": list(v.shape) if v.shape else None,
                 "dtype": str(v.dtype) if v.dtype else None}
                for v in self.vars.values()],
            "ops": [{"op": o.op_name, "inputs": o.inputs,
                     "outputs": o.outputs,
                     "attrs": _json_attrs(o.attrs, cf_arrays,
                                          f"__cf.op{i}")}
                    for i, o in enumerate(self.ops)],
            "loss_variables": self.loss_variables,
            "training_config": (self.training_config.to_map()
                                if self.training_config else None),
            # resuming training must continue the updater iteration
            # (Adam bias correction) and the epoch schedule, not
            # restart either at 0
            "iteration_count": self.iteration_count,
            "epoch_count": self.epoch_count,
        }
        # np.array (copy), not np.asarray: on CPU the conversion is a
        # zero-copy VIEW of the XLA buffer, and fit donates var/updater
        # buffers — an executable honoring the donation would mutate a
        # checkpoint_snapshot while its background write is in flight
        arrays = {k: np.array(v) for k, v in self._arrays.items()}
        upd_leaves = None
        if save_updater_state and self._updater_state is not None:
            state = self._updater_state
            from deeplearning4j_tpu.learning.updaters import \
                is_dp_sharded
            if is_dp_sharded(state):
                # serialize the dense per-variable layout so the saved
                # leaf order/count is independent of mesh/shard count
                from deeplearning4j_tpu.parallel.zero import \
                    to_dense_state
                names = getattr(self, "_updater_trainable", ())
                state = to_dense_state(
                    {n: self._arrays[n] for n in names}, state)
            leaves, _ = jax.tree_util.tree_flatten(state)
            upd_leaves = [np.array(l) for l in leaves]
        return graph, arrays, cf_arrays, upd_leaves

    @staticmethod
    def load(path: str) -> "SameDiff":
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        sd = SameDiff()
        with zipfile.ZipFile(path) as z:
            graph = json.loads(z.read("graph.json"))
            arrays = np.load(io.BytesIO(z.read("arrays.npz")))
            arr_map = {k: jnp.asarray(arrays[k]) for k in arrays.files}
        for vd in graph["variables"]:
            v = SDVariable(sd, vd["name"], VariableType(vd["type"]),
                           vd["shape"], vd["dtype"])
            sd.vars[v.name] = v
            if v.name in arr_map:
                sd._arrays[v.name] = arr_map[v.name]
        for i, od in enumerate(graph["ops"]):
            node = OpNode(od["op"], od["inputs"], od["outputs"],
                          _rebuild_cf_attrs(od["op"], od["attrs"],
                                            arr_map))
            sd.ops.append(node)
            for on in node.outputs:
                sd._producer[on] = i
        sd.loss_variables = graph.get("loss_variables", [])
        sd.iteration_count = graph.get("iteration_count", 0)
        sd.epoch_count = graph.get("epoch_count", 0)
        tc = graph.get("training_config")
        if tc:
            sd.training_config = TrainingConfig.from_map(tc)
        with zipfile.ZipFile(path) as z:
            if "updater.npz" in z.namelist():
                upd = np.load(io.BytesIO(z.read("updater.npz")))
                sd._loaded_updater_leaves = [
                    upd[f"leaf_{i}"] for i in range(len(upd.files))]
        return sd

    # -- introspection -------------------------------------------------
    def variables(self) -> List[SDVariable]:
        return list(self.vars.values())

    def get_variable(self, name: str) -> SDVariable:
        return self.vars[name]

    def has_variable(self, name: str) -> bool:
        return name in self.vars

    def summary(self) -> str:
        lines = [f"{'var':<28}{'type':<14}{'shape':<18}producer op"]
        for v in self.vars.values():
            prod = ""
            if v.name in self._producer:
                prod = self.ops[self._producer[v.name]].op_name
            lines.append(f"{v.name:<28}{v.var_type.value:<14}"
                         f"{str(v.shape):<18}{prod}")
        lines.append(f"{len(self.ops)} ops, {len(self.vars)} variables")
        return "\n".join(lines)


def _json_attrs(attrs: dict, array_sink: Optional[dict] = None,
                prefix: str = "") -> dict:
    out = {}
    for k, v in (attrs or {}).items():
        if k == "rng" or callable(v):
            continue    # call closures are rebuilt from *_spec on load
        if k.endswith("_spec") and isinstance(v, dict) and "child" in v:
            v = _spec_to_json(v, array_sink, f"{prefix}.{k}")
        elif isinstance(v, (np.integer,)):
            v = int(v)
        elif isinstance(v, (np.floating,)):
            v = float(v)
        elif isinstance(v, tuple):
            v = list(v)
        elif hasattr(v, "dtype") and hasattr(v, "tolist"):
            v = v.tolist()
        out[k] = v
    return out


def _spec_to_json(spec: dict, array_sink: Optional[dict] = None,
                  prefix: str = "") -> dict:
    """Serialize a control-flow subgraph (see _trace_subgraph): child
    graph structure + constants, with frozen outer-graph captures baked
    to their save-time values (matching the runtime freeze semantics).
    Arrays go into ``array_sink`` (written to the zip's arrays.npz
    under ``prefix``) — large captured weights stay binary; without a
    sink they inline into the JSON (small graphs / tests)."""
    child = spec["child"]
    arrays = {n: np.asarray(a) for n, a in child._arrays.items()}
    for local, owner, pname in spec["frozen_caps"]:
        arrays[local] = np.asarray(owner._arrays[pname])
    out = {
        "vars": [{"name": v.name, "type": v.var_type.value,
                  "shape": list(v.shape) if v.shape else None,
                  "dtype": str(v.dtype) if v.dtype else None}
                 for v in child.vars.values()],
        "ops": [{"op": o.op_name, "inputs": o.inputs,
                 "outputs": o.outputs,
                 "attrs": _json_attrs(o.attrs, array_sink,
                                      f"{prefix}.op{i}")}
                for i, o in enumerate(child.ops)],
        "proxies": spec["proxies"],
        "outs": spec["outs"],
        "parent_cap_locals": spec["parent_cap_locals"],
    }
    if array_sink is not None:
        out["arrays_prefix"] = prefix
        out["array_names"] = sorted(arrays)
        for n, a in arrays.items():
            array_sink[f"{prefix}/{n}"] = a
    else:
        out["arrays"] = {n: {"dtype": str(a.dtype), "data": a.tolist()}
                         for n, a in arrays.items()}
    return out


def _call_from_json_spec(spec: dict, arr_map: Optional[dict] = None):
    """Rebuild a subgraph call closure from its serialized form (the
    load-side twin of _trace_subgraph's `call`). ``arr_map`` holds the
    zip's arrays.npz entries for npz-referenced specs."""
    child = SameDiff()
    for vd in spec["vars"]:
        v = SDVariable(child, vd["name"], VariableType(vd["type"]),
                       tuple(vd["shape"]) if vd["shape"] else None,
                       vd["dtype"])
        child.vars[v.name] = v
    if "arrays_prefix" in spec:
        pre = spec["arrays_prefix"]
        for n in spec["array_names"]:
            child._arrays[n] = jnp.asarray(arr_map[f"{pre}/{n}"])
    else:
        for n, rec in spec.get("arrays", {}).items():
            child._arrays[n] = jnp.asarray(
                np.asarray(rec["data"], dtype=rec["dtype"]))
    for i, od in enumerate(spec["ops"]):
        attrs = _rebuild_cf_attrs(od["op"], od["attrs"], arr_map)
        node = OpNode(od["op"], od["inputs"], od["outputs"], attrs)
        child.ops.append(node)
        for on in node.outputs:
            child._producer[on] = i
    idxs = child._ancestors(list(spec["outs"]))
    proxies = list(spec["proxies"])
    cap_locals = list(spec["parent_cap_locals"])
    outs = list(spec["outs"])
    n_args = len(proxies)

    def call(*args):
        values = dict(child._arrays)
        values.update(zip(proxies, args[:n_args]))
        values.update(zip(cap_locals, args[n_args:]))
        child._execute(values, idxs, None, False)
        return [values[n] for n in outs]

    return call


#: control-flow attrs: call-closure key -> serialized-spec key
_CF_CALL_SPECS = {"_cond_call": "_cond_spec", "_body_call": "_body_spec",
                  "_true_call": "_true_spec",
                  "_false_call": "_false_spec"}


def _rebuild_cf_attrs(op_name: str, attrs: dict,
                      arr_map: Optional[dict] = None) -> dict:
    """Recreate call closures for a (possibly nested) control-flow op
    loaded from JSON; no-op for ordinary ops."""
    if op_name not in ("while_loop", "cond", "scan"):
        return attrs
    attrs = dict(attrs)
    for call_key, spec_key in _CF_CALL_SPECS.items():
        spec = attrs.get(spec_key)
        if spec is not None and call_key not in attrs:
            attrs[call_key] = _call_from_json_spec(spec, arr_map)
    return attrs
