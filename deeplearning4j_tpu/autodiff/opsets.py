"""Op factory namespaces (reference: SDMath/SDNN/SDCNN/SDRNN/SDLoss/
SDImage/SDBitwise — SURVEY.md S1 "op factories"). Thin builders over
``SameDiff._op``; the math lives in ``registry``."""
from __future__ import annotations

from typing import Optional, Sequence


class _Namespace:
    def __init__(self, sd):
        self.sd = sd

    def _v(self, x):
        return self.sd._as_var(x)

    def _call(self, op, inputs, attrs=None, name=None, n_out=1):
        return self.sd._op(op, [self._v(i) for i in inputs], attrs,
                           name, n_out)


class SDMath(_Namespace):
    def __getattr__(self, op_name):
        """Any registered unary/binary op is reachable directly:
        sd.math.tanh(x), sd.math.atan2(a, b), ..."""
        from deeplearning4j_tpu.autodiff.registry import OP_REGISTRY
        if op_name.startswith("_") or op_name not in OP_REGISTRY:
            raise AttributeError(op_name)

        def fn(*inputs, name=None, **attrs):
            return self._call(op_name, list(inputs), attrs or None, name)

        return fn

    def add(self, a, b, name=None):
        return self._call("add", [a, b], name=name)

    def square(self, x, name=None):
        return self._call("square", [x], name=name)

    def standardize(self, x, axis=-1, name=None):
        return self._call("standardize", [x], {"axis": axis}, name)

    def moments(self, x, axis=None, name=None):
        return self._call("moments", [x], {"axis": axis}, name, n_out=2)

    def clip_by_value(self, x, lo, hi, name=None):
        return self._call("clip_by_value", [x],
                          {"clip_value_min": lo, "clip_value_max": hi},
                          name)

    def cumsum(self, x, axis=-1, name=None):
        return self._call("cumsum", [x], {"axis": axis}, name)

    def concat(self, inputs, axis=0, name=None):
        return self._call("concat", list(inputs), {"axis": axis}, name)

    def stack(self, inputs, axis=0, name=None):
        return self._call("stack", list(inputs), {"axis": axis}, name)

    def unstack(self, x, axis=0, num=None, name=None):
        if num is None:
            shape = self._v(x).shape
            if shape is None:     # op outputs carry no static shape
                raise ValueError(
                    "unstack of a computed tensor needs explicit num=")
            num = shape[axis]
        return self._call("unstack", [x], {"axis": axis}, name,
                          n_out=num)

    def split(self, x, num_splits, axis=0, name=None):
        return self._call("split", [x],
                          {"num_splits": num_splits, "axis": axis},
                          name, n_out=num_splits)

    def one_hot(self, idx, depth, name=None):
        return self._call("one_hot", [idx], {"depth": depth}, name)

    def segment_sum(self, data, segment_ids, num_segments=None,
                    name=None):
        return self._call("segment_sum", [data, segment_ids],
                          {"num_segments": num_segments}, name)

    def segment_mean(self, data, segment_ids, num_segments=None,
                     name=None):
        return self._call("segment_mean", [data, segment_ids],
                          {"num_segments": num_segments}, name)


class SDNN(_Namespace):
    def linear(self, x, w, b=None, name=None):
        y = self._call("matmul", [x, w], name=name)
        return y + b if b is not None else y

    def relu(self, x, name=None):
        return self._call("relu", [x], name=name)

    def gelu(self, x, name=None):
        return self._call("gelu", [x], name=name)

    def sigmoid(self, x, name=None):
        return self._call("sigmoid", [x], name=name)

    def tanh(self, x, name=None):
        return self._call("tanh", [x], name=name)

    def swish(self, x, name=None):
        return self._call("swish", [x], name=name)

    def elu(self, x, name=None):
        return self._call("elu", [x], name=name)

    def leaky_relu(self, x, alpha=0.01, name=None):
        return self._call("leaky_relu", [x], {"alpha": alpha}, name)

    def softmax(self, x, axis=-1, name=None):
        return self._call("softmax", [x], {"axis": axis}, name)

    def log_softmax(self, x, axis=-1, name=None):
        return self._call("log_softmax", [x], {"axis": axis}, name)

    def dropout(self, x, rate, name=None):
        return self._call("dropout", [x], {"rate": rate}, name)

    def layer_norm(self, x, gain=None, bias=None, axis=-1,
                   epsilon=1e-5, name=None):
        ins = [x] + ([gain] if gain is not None else []) + \
            ([bias] if bias is not None else [])
        return self._call("layer_norm", ins,
                          {"axis": axis, "epsilon": epsilon}, name)

    def batch_norm(self, x, mean, var, gamma, beta, epsilon=1e-5,
                   name=None):
        return self._call("batch_norm", [x, mean, var, gamma, beta],
                          {"epsilon": epsilon}, name)

    def dot_product_attention(self, q, k, v, mask=None, scale=None,
                              name=None):
        ins = [q, k, v] + ([mask] if mask is not None else [])
        attrs = {}
        if scale is not None:
            attrs["scale"] = scale
        return self._call("dot_product_attention", ins, attrs or None,
                          name)

    def multi_head_dot_product_attention(self, x, wq, wk, wv, wo,
                                         num_heads, mask=None,
                                         name=None):
        ins = [x, wq, wk, wv, wo] + ([mask] if mask is not None else [])
        return self._call("multi_head_dot_product_attention", ins,
                          {"num_heads": num_heads}, name)

    def embedding_lookup(self, table, ids, name=None):
        return self._call("gather", [table, ids], {"axis": 0}, name)

    def pad(self, x, paddings, constant=0.0, name=None):
        return self._call("pad", [x],
                          {"paddings": paddings, "constant": constant},
                          name)


class SDCNN(_Namespace):
    def conv2d(self, x, w, b=None, stride=(1, 1), padding="SAME",
               dilation=(1, 1), name=None):
        ins = [x, w] + ([b] if b is not None else [])
        return self._call("conv2d", ins,
                          {"stride": tuple(stride), "padding": padding,
                           "dilation": tuple(dilation)}, name)

    def conv1d(self, x, w, b=None, stride=1, padding="SAME", name=None):
        ins = [x, w] + ([b] if b is not None else [])
        return self._call("conv1d", ins,
                          {"stride": stride, "padding": padding}, name)

    def depthwise_conv2d(self, x, w, b=None, stride=(1, 1),
                         padding="SAME", name=None):
        ins = [x, w] + ([b] if b is not None else [])
        return self._call("depthwise_conv2d", ins,
                          {"stride": tuple(stride), "padding": padding},
                          name)

    def separable_conv2d(self, x, dw, pw, b=None, stride=(1, 1),
                         padding="SAME", name=None):
        ins = [x, dw, pw] + ([b] if b is not None else [])
        return self._call("separable_conv2d", ins,
                          {"stride": tuple(stride), "padding": padding},
                          name)

    def deconv2d(self, x, w, b=None, stride=(1, 1), padding="SAME",
                 name=None):
        ins = [x, w] + ([b] if b is not None else [])
        return self._call("deconv2d", ins,
                          {"stride": tuple(stride), "padding": padding},
                          name)

    def max_pooling2d(self, x, kernel=(2, 2), stride=(2, 2),
                      padding="VALID", name=None):
        return self._call("max_pool2d", [x],
                          {"kernel": tuple(kernel),
                           "stride": tuple(stride), "padding": padding},
                          name)

    def avg_pooling2d(self, x, kernel=(2, 2), stride=(2, 2),
                      padding="VALID", name=None):
        return self._call("avg_pool2d", [x],
                          {"kernel": tuple(kernel),
                           "stride": tuple(stride), "padding": padding},
                          name)

    def upsampling2d(self, x, scale=2, name=None):
        return self._call("upsampling2d", [x], {"scale": scale}, name)

    def im2col(self, x, kernel, stride=(1, 1), name=None):
        return self._call("im2col", [x],
                          {"kernel": tuple(kernel),
                           "stride": tuple(stride)}, name)


class SDRNN(_Namespace):
    def lstm_cell(self, x, h_prev, c_prev, w, rw, b, name=None):
        return self._call("lstm_cell", [x, h_prev, c_prev, w, rw, b],
                          None, name, n_out=2)

    def gru_cell(self, x, h_prev, w, rw, b, name=None):
        return self._call("gru_cell", [x, h_prev, w, rw, b], None, name)

    def sru_cell(self, x, c_prev, w, b, name=None):
        return self._call("sru_cell", [x, c_prev, w, b], None, name,
                          n_out=2)


class SDLoss(_Namespace):
    def softmax_cross_entropy(self, labels, logits, weights=None,
                              label_smoothing=0.0, name=None):
        ins = [labels, logits] + ([weights] if weights is not None
                                  else [])
        return self._call("softmax_cross_entropy", ins,
                          {"label_smoothing": label_smoothing}, name)

    def sparse_softmax_cross_entropy(self, labels, logits, name=None):
        return self._call("sparse_softmax_cross_entropy",
                          [labels, logits], None, name)

    def sigmoid_cross_entropy(self, labels, logits, weights=None,
                              name=None):
        ins = [labels, logits] + ([weights] if weights is not None
                                  else [])
        return self._call("sigmoid_cross_entropy", ins, None, name)

    def mean_squared_error(self, labels, preds, weights=None, name=None):
        ins = [labels, preds] + ([weights] if weights is not None
                                 else [])
        return self._call("mean_squared_error", ins, None, name)

    def absolute_difference(self, labels, preds, weights=None,
                            name=None):
        ins = [labels, preds] + ([weights] if weights is not None
                                 else [])
        return self._call("absolute_difference", ins, None, name)

    def huber_loss(self, labels, preds, delta=1.0, name=None):
        return self._call("huber_loss", [labels, preds],
                          {"delta": delta}, name)

    def log_loss(self, labels, preds, name=None):
        return self._call("log_loss", [labels, preds], None, name)

    def hinge_loss(self, labels, logits, name=None):
        return self._call("hinge_loss", [labels, logits], None, name)

    def cosine_distance(self, a, b, axis=-1, name=None):
        return self._call("cosine_distance", [a, b], {"axis": axis},
                          name)


class SDImage(_Namespace):
    def resize_bilinear(self, x, size, name=None):
        return self._call("resize_bilinear", [x], {"size": tuple(size)},
                          name)

    def resize_nearest(self, x, size, name=None):
        return self._call("resize_nearest", [x], {"size": tuple(size)},
                          name)

    def crop_and_resize(self, img, boxes, box_idx, crop_size,
                        name=None):
        return self._call("crop_and_resize", [img, boxes, box_idx],
                          {"crop_size": tuple(crop_size)}, name)

    def non_max_suppression(self, boxes, scores, max_output_size,
                            iou_threshold=0.5, name=None):
        return self._call("non_max_suppression", [boxes, scores],
                          {"max_output_size": max_output_size,
                           "iou_threshold": iou_threshold}, name)

    def extract_image_patches(self, x, kernel, stride=(1, 1),
                              name=None):
        return self._call("extract_image_patches", [x],
                          {"kernel": tuple(kernel),
                           "stride": tuple(stride)}, name)


class SDBitwise(_Namespace):
    def and_(self, a, b, name=None):
        return self._call("bitwise_and", [a, b], None, name)

    def or_(self, a, b, name=None):
        return self._call("bitwise_or", [a, b], None, name)

    def xor(self, a, b, name=None):
        return self._call("bitwise_xor", [a, b], None, name)

    def left_shift(self, a, b, name=None):
        return self._call("left_shift", [a, b], None, name)

    def right_shift(self, a, b, name=None):
        return self._call("right_shift", [a, b], None, name)


class SDLinalg(_Namespace):
    def matmul(self, a, b, transpose_a=False, transpose_b=False,
               name=None):
        return self._call("matmul", [a, b],
                          {"transpose_a": transpose_a,
                           "transpose_b": transpose_b}, name)

    def cholesky(self, x, name=None):
        return self._call("cholesky", [x], None, name)

    def qr(self, x, name=None):
        return self._call("qr", [x], None, name, n_out=2)

    def svd(self, x, full_matrices=False, name=None):
        return self._call("svd", [x],
                          {"full_matrices": full_matrices}, name,
                          n_out=3)

    def lu(self, x, name=None):
        return self._call("lu", [x], None, name, n_out=3)

    def solve(self, a, b, name=None):
        return self._call("solve", [a, b], None, name)

    def triangular_solve(self, a, b, lower=True, name=None):
        return self._call("triangular_solve", [a, b], {"lower": lower},
                          name)

    def inverse(self, x, name=None):
        return self._call("matrix_inverse", [x], None, name)

    def det(self, x, name=None):
        return self._call("matrix_determinant", [x], None, name)


class SDRandom(_Namespace):
    def normal(self, mean, stddev, shape, name=None):
        return self._call("random_normal", [],
                          {"mean": mean, "stddev": stddev,
                           "shape": tuple(shape)}, name)

    def uniform(self, low, high, shape, name=None):
        return self._call("random_uniform", [],
                          {"min": low, "max": high,
                           "shape": tuple(shape)}, name)

    def bernoulli(self, prob, shape, name=None):
        return self._call("random_bernoulli", [],
                          {"prob": prob, "shape": tuple(shape)}, name)
