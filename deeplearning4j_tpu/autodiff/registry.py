"""Op registry: named graph ops -> jax implementations.

Reference parity: libnd4j's ~500 declarable ops (SURVEY.md N5,
Appendix A domain checklist) carried in Java by the
``DynamicCustomOp`` hierarchy (J2). Here an op is a pure function
``fn(inputs: list[Array], attrs: dict) -> Array | tuple`` registered
under its reference/TF-compatible name; the SameDiff layer dispatches
through this table and XLA fuses the result (so an "op" needs no
hand-written kernel or gradient — jax.grad differentiates the trace).

Coverage accounting (§4.3 OpValidation pattern): every op declares a
domain; ``op_coverage()`` reports per-domain counts and tests assert
domains are populated.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

OP_REGISTRY: Dict[str, Callable] = {}
OP_DOMAINS: Dict[str, str] = {}


def op(name, domain):
    def deco(fn):
        OP_REGISTRY[name] = fn
        OP_DOMAINS[name] = domain
        return fn
    return deco


def alias(new, existing):
    OP_REGISTRY[new] = OP_REGISTRY[existing]
    OP_DOMAINS[new] = OP_DOMAINS[existing]


def op_coverage() -> Dict[str, int]:
    out: Dict[str, int] = {}
    for _, d in OP_DOMAINS.items():
        out[d] = out.get(d, 0) + 1
    return out


def get_op(name: str) -> Callable:
    if name not in OP_REGISTRY:
        raise KeyError(f"unknown op '{name}'; known domains: "
                       f"{sorted(set(OP_DOMAINS.values()))}")
    return OP_REGISTRY[name]


# -- helpers ----------------------------------------------------------------

def _unary(name, domain, fn):
    OP_REGISTRY[name] = lambda ins, attrs: fn(ins[0])
    OP_DOMAINS[name] = domain


def _binary(name, domain, fn):
    OP_REGISTRY[name] = lambda ins, attrs: fn(ins[0], ins[1])
    OP_DOMAINS[name] = domain


def _reduce(name, fn):
    def impl(ins, attrs):
        axis = attrs.get("axis")
        if isinstance(axis, (list, tuple)):
            axis = tuple(axis)
        return fn(ins[0], axis=axis,
                  keepdims=bool(attrs.get("keep_dims", False)))
    OP_REGISTRY[name] = impl
    OP_DOMAINS[name] = "reduce"


# -- arithmetic / broadcastable (Appendix A: broadcastable) -----------------
_binary("add", "arithmetic", jnp.add)
_binary("sub", "arithmetic", jnp.subtract)
_binary("mul", "arithmetic", jnp.multiply)
_binary("div", "arithmetic", jnp.divide)
_binary("rdiv", "arithmetic", lambda a, b: b / a)
_binary("rsub", "arithmetic", lambda a, b: b - a)
_binary("pow", "arithmetic", jnp.power)
_binary("floordiv", "arithmetic", jnp.floor_divide)
_binary("mod", "arithmetic", jnp.mod)
_binary("fmod", "arithmetic", jnp.fmod)   # C-style sign-of-dividend
_binary("maximum", "arithmetic", jnp.maximum)
_binary("minimum", "arithmetic", jnp.minimum)
_binary("squared_difference", "arithmetic", lambda a, b: (a - b) ** 2)
_unary("neg", "arithmetic", jnp.negative)
_unary("abs", "arithmetic", jnp.abs)
_unary("sign", "arithmetic", jnp.sign)
_unary("reciprocal", "arithmetic", jnp.reciprocal)

# -- transforms (same/strict/float) -----------------------------------------
_unary("exp", "transform", jnp.exp)
_unary("log", "transform", jnp.log)
_unary("log1p", "transform", jnp.log1p)
_unary("expm1", "transform", jnp.expm1)
_unary("sqrt", "transform", jnp.sqrt)
_unary("rsqrt", "transform", lambda x: lax.rsqrt(x))
_unary("square", "transform", jnp.square)
_unary("cube", "transform", lambda x: x ** 3)
_unary("floor", "transform", jnp.floor)
_unary("ceil", "transform", jnp.ceil)
_unary("round", "transform", jnp.round)
_unary("sin", "transform", jnp.sin)
_unary("cos", "transform", jnp.cos)
_unary("tan", "transform", jnp.tan)
_unary("asin", "transform", jnp.arcsin)
_unary("acos", "transform", jnp.arccos)
_unary("atan", "transform", jnp.arctan)
_unary("sinh", "transform", jnp.sinh)
_unary("cosh", "transform", jnp.cosh)
_unary("tanh", "transform", jnp.tanh)
_unary("asinh", "transform", jnp.arcsinh)
_unary("acosh", "transform", jnp.arccosh)
_unary("atanh", "transform", jnp.arctanh)
_unary("erf", "transform", jax.scipy.special.erf)
_unary("erfc", "transform", jax.scipy.special.erfc)
_binary("atan2", "transform", jnp.arctan2)


@op("clip_by_value", "transform")
def _clip(ins, attrs):
    return jnp.clip(ins[0], attrs["clip_value_min"],
                    attrs["clip_value_max"])


@op("clip_by_norm", "transform")
def _clip_norm(ins, attrs):
    n = jnp.linalg.norm(ins[0])
    c = attrs["clip_norm"]
    return jnp.where(n > c, ins[0] * (c / n), ins[0])


@op("cast", "transform")
def _cast(ins, attrs):
    return ins[0].astype(jnp.dtype(attrs["dtype"]))


# -- activations ------------------------------------------------------------
_unary("relu", "activation", jax.nn.relu)
_unary("relu6", "activation", jax.nn.relu6)
_unary("sigmoid", "activation", jax.nn.sigmoid)
_unary("softplus", "activation", jax.nn.softplus)
_unary("softsign", "activation", jax.nn.soft_sign)
_unary("elu", "activation", jax.nn.elu)
_unary("selu", "activation", jax.nn.selu)
_unary("gelu", "activation", partial(jax.nn.gelu, approximate=False))
_unary("gelu_tanh", "activation", partial(jax.nn.gelu, approximate=True))
_unary("swish", "activation", jax.nn.silu)
_unary("mish", "activation", jax.nn.mish)
_unary("hard_sigmoid", "activation", jax.nn.hard_sigmoid)
_unary("hard_tanh", "activation", lambda x: jnp.clip(x, -1.0, 1.0))


@op("leaky_relu", "activation")
def _leaky(ins, attrs):
    return jax.nn.leaky_relu(ins[0], attrs.get("alpha", 0.01))


@op("softmax", "activation")
def _softmax(ins, attrs):
    return jax.nn.softmax(ins[0], axis=attrs.get("axis", -1))


@op("log_softmax", "activation")
def _log_softmax(ins, attrs):
    return jax.nn.log_softmax(ins[0], axis=attrs.get("axis", -1))


@op("prelu", "activation")
def _prelu(ins, attrs):
    x, a = ins
    return jnp.where(x >= 0, x, a * x)


# -- blas / linalg ----------------------------------------------------------
@op("matmul", "blas")
def _matmul(ins, attrs):
    a, b = ins
    if attrs.get("transpose_a"):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("transpose_b"):
        b = jnp.swapaxes(b, -1, -2)
    return a @ b


alias("mmul", "matmul")
alias("batch_matmul", "matmul")
_binary("tensordot_last", "blas", lambda a, b: jnp.tensordot(a, b, 1))
_binary("outer", "blas", jnp.outer)
_binary("dot", "blas", jnp.dot)


@op("lu", "linalg")
def _lu(ins, attrs):
    return jax.scipy.linalg.lu(ins[0])


@op("qr", "linalg")
def _qr(ins, attrs):
    return jnp.linalg.qr(ins[0])


@op("cholesky", "linalg")
def _chol(ins, attrs):
    return jnp.linalg.cholesky(ins[0])


@op("svd", "linalg")
def _svd(ins, attrs):
    return jnp.linalg.svd(ins[0],
                          full_matrices=attrs.get("full_matrices", False))


@op("matrix_inverse", "linalg")
def _inv(ins, attrs):
    return jnp.linalg.inv(ins[0])


@op("matrix_determinant", "linalg")
def _det(ins, attrs):
    return jnp.linalg.det(ins[0])


@op("triangular_solve", "linalg")
def _trisolve(ins, attrs):
    return jax.scipy.linalg.solve_triangular(
        ins[0], ins[1], lower=attrs.get("lower", True))


@op("solve", "linalg")
def _solve(ins, attrs):
    return jnp.linalg.solve(ins[0], ins[1])


@op("trace", "linalg")
def _trace(ins, attrs):
    return jnp.trace(ins[0], axis1=-2, axis2=-1)


@op("diag", "linalg")
def _diag(ins, attrs):
    return jnp.diag(ins[0])


@op("diag_part", "linalg")
def _diag_part(ins, attrs):
    return jnp.diagonal(ins[0], axis1=-2, axis2=-1)


@op("eye", "linalg")
def _eye(ins, attrs):
    return jnp.eye(attrs["rows"], attrs.get("cols"),
                   dtype=attrs.get("dtype", jnp.float32))


# -- reductions -------------------------------------------------------------
_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_std", jnp.std)
_reduce("reduce_var", jnp.var)
alias("sum", "reduce_sum")
alias("mean", "reduce_mean")
alias("amax", "reduce_max")
alias("amin", "reduce_min")


@op("reduce_norm1", "reduce")
def _norm1(ins, attrs):
    axis = attrs.get("axis")
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sum(jnp.abs(ins[0]), axis=axis,
                   keepdims=bool(attrs.get("keep_dims", False)))


@op("reduce_norm2", "reduce")
def _norm2(ins, attrs):
    axis = attrs.get("axis")
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.sqrt(jnp.sum(ins[0] ** 2, axis=axis,
                            keepdims=bool(attrs.get("keep_dims", False))))


@op("reduce_logsumexp", "reduce")
def _lse(ins, attrs):
    return jax.scipy.special.logsumexp(ins[0], axis=attrs.get("axis"))


@op("cumsum", "reduce")
def _cumsum(ins, attrs):
    """TF Cumsum / ONNX CumSum semantics: ``exclusive`` shifts the
    scan by one (first element 0), ``reverse`` scans from the end."""
    x = ins[0]
    ax = attrs.get("axis", -1) % x.ndim
    if attrs.get("reverse", False):
        x = jnp.flip(x, ax)
    y = jnp.cumsum(x, axis=ax)
    if attrs.get("exclusive", False):
        # shift by one (exact — never subtract, which breaks on inf
        # and loses precision on cancellation)
        pad = [(0, 0)] * x.ndim
        pad[ax] = (1, 0)
        sl = [slice(None)] * x.ndim
        sl[ax] = slice(0, x.shape[ax])
        y = jnp.pad(y, pad)[tuple(sl)]
    if attrs.get("reverse", False):
        y = jnp.flip(y, ax)
    return y


@op("cumprod", "reduce")
def _cumprod(ins, attrs):
    """TF Cumprod semantics: ``exclusive`` shifts the scan by one
    (first element 1 — the multiplicative identity), ``reverse``
    scans from the end."""
    x = ins[0]
    ax = attrs.get("axis", -1) % x.ndim
    if attrs.get("reverse", False):
        x = jnp.flip(x, ax)
    y = jnp.cumprod(x, axis=ax)
    if attrs.get("exclusive", False):
        pad = [(0, 0)] * x.ndim
        pad[ax] = (1, 0)
        sl = [slice(None)] * x.ndim
        sl[ax] = slice(0, x.shape[ax])
        y = jnp.pad(y, pad, constant_values=1)[tuple(sl)]
    if attrs.get("reverse", False):
        y = jnp.flip(y, ax)
    return y


@op("reduce_any", "reduce")
def _any(ins, attrs):
    return jnp.any(ins[0], axis=attrs.get("axis"))


@op("reduce_all", "reduce")
def _all(ins, attrs):
    return jnp.all(ins[0], axis=attrs.get("axis"))


# -- indexed reductions -----------------------------------------------------
@op("argmax", "indexreduce")
def _argmax(ins, attrs):
    return jnp.argmax(ins[0], axis=attrs.get("axis", -1))


@op("argmin", "indexreduce")
def _argmin(ins, attrs):
    return jnp.argmin(ins[0], axis=attrs.get("axis", -1))


@op("top_k", "indexreduce")
def _topk(ins, attrs):
    """``axis`` (default last) and ``largest`` (default True; False =
    ONNX TopK smallest mode).  Non-last axes move to the minor
    position for the XLA-native minor-dim sort and back.  Smallest
    mode uses a stable ascending argsort (exact for every dtype —
    negation would corrupt unsigned ints and INT_MIN)."""
    x = ins[0]
    k = attrs["k"]
    ax = attrs.get("axis", -1) % x.ndim
    largest = attrs.get("largest", True)
    if ax != x.ndim - 1:
        x = jnp.moveaxis(x, ax, -1)
    if largest:
        vals, idx = lax.top_k(x, k)
    else:
        idx = jnp.argsort(x, axis=-1)[..., :k].astype(jnp.int32)
        vals = jnp.take_along_axis(x, idx, axis=-1)
    if ax != ins[0].ndim - 1:
        vals = jnp.moveaxis(vals, -1, ax)
        idx = jnp.moveaxis(idx, -1, ax)
    return vals, idx


@op("in_top_k", "indexreduce")
def _in_topk(ins, attrs):
    preds, targets = ins
    _, idx = lax.top_k(preds, attrs["k"])
    return jnp.any(idx == targets[:, None], axis=-1)


# -- boolean / comparison ---------------------------------------------------
_binary("eq", "boolean", jnp.equal)
_binary("neq", "boolean", jnp.not_equal)
_binary("gt", "boolean", jnp.greater)
_binary("gte", "boolean", jnp.greater_equal)
_binary("lt", "boolean", jnp.less)
_binary("lte", "boolean", jnp.less_equal)
_binary("logical_and", "boolean", jnp.logical_and)
_binary("logical_or", "boolean", jnp.logical_or)
_binary("logical_xor", "boolean", jnp.logical_xor)
_unary("logical_not", "boolean", jnp.logical_not)
_unary("is_nan", "boolean", jnp.isnan)
_unary("is_inf", "boolean", jnp.isinf)
_unary("is_finite", "boolean", jnp.isfinite)


@op("where", "boolean")
def _where(ins, attrs):
    return jnp.where(ins[0], ins[1], ins[2])


alias("select", "where")

# -- bitwise ----------------------------------------------------------------
_binary("bitwise_and", "bitwise", jnp.bitwise_and)
_binary("bitwise_or", "bitwise", jnp.bitwise_or)
_binary("bitwise_xor", "bitwise", jnp.bitwise_xor)
_binary("left_shift", "bitwise", jnp.left_shift)
_binary("right_shift", "bitwise", jnp.right_shift)


_UNSIGNED = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def _rotate(x, s, left: bool):
    # rotate in the unsigned domain: arithmetic right shift on signed
    # ints would sign-fill instead of wrapping
    ut = _UNSIGNED[x.dtype.itemsize]
    bits = jnp.asarray(x.dtype.itemsize * 8, ut)
    ux = x.astype(ut)
    us = s.astype(ut) % bits
    if left:
        r = (ux << us) | (ux >> ((bits - us) % bits))
    else:
        r = (ux >> us) | (ux << ((bits - us) % bits))
    return r.astype(x.dtype)


@op("cyclic_shift_left", "bitwise")
def _rotl(ins, attrs):
    return _rotate(ins[0], ins[1], left=True)


@op("cyclic_shift_right", "bitwise")
def _rotr(ins, attrs):
    return _rotate(ins[0], ins[1], left=False)
_unary("bitwise_not", "bitwise", jnp.invert)


# -- shape ops --------------------------------------------------------------
@op("reshape", "shape")
def _reshape(ins, attrs):
    return jnp.reshape(ins[0], attrs["shape"])


@op("permute", "shape")
def _permute(ins, attrs):
    return jnp.transpose(ins[0], attrs["axes"])


alias("transpose", "permute")


@op("expand_dims", "shape")
def _expand(ins, attrs):
    return jnp.expand_dims(ins[0], attrs["axis"])


@op("squeeze", "shape")
def _squeeze(ins, attrs):
    return jnp.squeeze(ins[0], attrs.get("axis"))


@op("concat", "shape")
def _concat(ins, attrs):
    return jnp.concatenate(ins, axis=attrs.get("axis", 0))


@op("stack", "shape")
def _stack(ins, attrs):
    return jnp.stack(ins, axis=attrs.get("axis", 0))


@op("unstack", "shape")
def _unstack(ins, attrs):
    axis = attrs.get("axis", 0)
    n = ins[0].shape[axis]
    return tuple(jnp.squeeze(s, axis) for s in
                 jnp.split(ins[0], n, axis=axis))


@op("split", "shape")
def _split(ins, attrs):
    return tuple(jnp.split(ins[0], attrs["num_splits"],
                           axis=attrs.get("axis", 0)))


@op("split_v", "shape")
def _split_v(ins, attrs):
    # sizes are static graph attrs: split points must be concrete
    # under jit, so the cumsum runs in Python, not on device
    idx = list(np.cumsum([int(s) for s in attrs["size_splits"]])[:-1])
    return tuple(jnp.split(ins[0], idx, axis=attrs.get("axis", 0)))


@op("tile", "shape")
def _tile(ins, attrs):
    return jnp.tile(ins[0], attrs["reps"])


@op("repeat", "shape")
def _repeat(ins, attrs):
    return jnp.repeat(ins[0], attrs["repeats"], axis=attrs.get("axis"))


@op("flip", "shape")
def _flip(ins, attrs):
    return jnp.flip(ins[0], axis=attrs.get("axis"))


@op("gather", "shape")
def _gather(ins, attrs):
    """``batch_dims`` (TF GatherV2): the leading b dims of params and
    indices are shared batch dims; the take applies per batch element
    (vmapped — lowers to one XLA gather)."""
    bd = int(attrs.get("batch_dims", 0))
    axis = attrs.get("axis", 0) % ins[0].ndim
    idx = ins[1].astype(jnp.int32)
    if bd == 0:
        return jnp.take(ins[0], idx, axis=axis)
    take = lambda p, i: jnp.take(p, i, axis=axis - bd)
    for _ in range(bd):
        take = jax.vmap(take)
    return take(ins[0], idx)


# -- TensorList / TensorArray (TF dynamic-loop accumulators) ----------------
# TPU-first representation: a STATIC-size list is a dense
# [n, *element_shape] tensor — SetItem/GetItem are dynamic slice
# updates (differentiable, and exactly the loop-carry layout XLA
# wants), Stack/FromTensor are identity.  Dynamic-size lists
# (PushBack) have no static-shape representation and are rejected at
# import.  Documented divergence (README migration table): an
# out-of-bounds index CLAMPS to the last slot (XLA dynamic-slice
# semantics — no runtime assertion exists inside a compiled program)
# where TF raises at runtime.
@op("tensor_list_set_item", "shape")
def _tl_set_item(ins, attrs):
    lst, idx, item = ins
    return jax.lax.dynamic_update_index_in_dim(
        lst, item.astype(lst.dtype), idx.astype(jnp.int32), 0)


@op("tensor_list_get_item", "shape")
def _tl_get_item(ins, attrs):
    return jax.lax.dynamic_index_in_dim(ins[0],
                                        ins[1].astype(jnp.int32), 0,
                                        keepdims=False)


@op("tensor_list_length", "shape")
def _tl_length(ins, attrs):
    return jnp.asarray(ins[0].shape[0], jnp.int32)


@op("gather_nd", "shape")
def _gather_nd(ins, attrs):
    params, indices = ins
    idx = tuple(jnp.moveaxis(indices.astype(jnp.int32), -1, 0))
    return params[idx]


@op("scatter_update", "shape")
def _scatter_upd(ins, attrs):
    ref, indices, updates = ins
    return ref.at[indices.astype(jnp.int32)].set(updates)


@op("scatter_add", "shape")
def _scatter_add(ins, attrs):
    ref, indices, updates = ins
    return ref.at[indices.astype(jnp.int32)].add(updates)


@op("pad", "shape")
def _pad(ins, attrs):
    mode = attrs.get("mode", "constant").lower()
    pads = [tuple(p) for p in attrs["paddings"]]
    if mode == "constant":
        return jnp.pad(ins[0], pads,
                       constant_values=attrs.get("constant", 0.0))
    return jnp.pad(ins[0], pads, mode=mode)


@op("slice", "shape")
def _slice(ins, attrs):
    begin = attrs["begin"]
    size = attrs["size"]
    end = [b + s if s >= 0 else ins[0].shape[i]
           for i, (b, s) in enumerate(zip(begin, size))]
    return ins[0][tuple(slice(b, e) for b, e in zip(begin, end))]


@op("strided_slice", "shape")
def _strided_slice(ins, attrs):
    sl = tuple(slice(b, e, s) for b, e, s in
               zip(attrs["begin"], attrs["end"], attrs["strides"]))
    return ins[0][sl]


@op("shape_of", "shape")
def _shape_of(ins, attrs):
    return jnp.asarray(ins[0].shape, dtype=jnp.int32)


@op("size", "shape")
def _size(ins, attrs):
    return jnp.asarray(ins[0].size, dtype=jnp.int32)


@op("rank", "shape")
def _rank(ins, attrs):
    return jnp.asarray(ins[0].ndim, dtype=jnp.int32)


@op("one_hot", "shape")
def _one_hot(ins, attrs):
    return jax.nn.one_hot(ins[0].astype(jnp.int32), attrs["depth"],
                          axis=attrs.get("axis", -1))


@op("reverse_sequence", "shape")
def _reverse_seq(ins, attrs):
    x, lengths = ins
    sa = attrs.get("seq_axis", 1)
    ba = attrs.get("batch_axis", 0)
    xm = jnp.moveaxis(x, (ba, sa), (0, 1))     # -> [b, t, ...]
    t = xm.shape[1]
    idx = jnp.arange(t)
    rev = jnp.where(idx[None, :] < lengths[:, None],
                    lengths[:, None] - 1 - idx[None, :], idx[None, :])
    out = jnp.take_along_axis(
        xm, rev[(...,) + (None,) * (xm.ndim - 2)], axis=1)
    return jnp.moveaxis(out, (0, 1), (ba, sa))


@op("broadcast_to", "shape")
def _broadcast_to(ins, attrs):
    return jnp.broadcast_to(ins[0], attrs["shape"])


@op("zeros_like", "shape")
def _zeros_like(ins, attrs):
    return jnp.zeros_like(ins[0])


@op("ones_like", "shape")
def _ones_like(ins, attrs):
    return jnp.ones_like(ins[0])


@op("fill", "shape")
def _fill(ins, attrs):
    return jnp.full(attrs["shape"], attrs["value"],
                    dtype=attrs.get("dtype", jnp.float32))


@op("range", "shape")
def _range(ins, attrs):
    return jnp.arange(attrs["start"], attrs["limit"],
                      attrs.get("delta", 1))


@op("linspace", "shape")
def _linspace(ins, attrs):
    return jnp.linspace(attrs["start"], attrs["stop"], attrs["num"])


# -- segment ops ------------------------------------------------------------
@op("segment_sum", "segment")
def _segment_sum(ins, attrs):
    return jax.ops.segment_sum(ins[0], ins[1].astype(jnp.int32),
                               num_segments=attrs.get("num_segments"))


@op("segment_max", "segment")
def _segment_max(ins, attrs):
    return jax.ops.segment_max(ins[0], ins[1].astype(jnp.int32),
                               num_segments=attrs.get("num_segments"))


@op("segment_min", "segment")
def _segment_min(ins, attrs):
    return jax.ops.segment_min(ins[0], ins[1].astype(jnp.int32),
                               num_segments=attrs.get("num_segments"))


@op("segment_mean", "segment")
def _segment_mean(ins, attrs):
    seg = ins[1].astype(jnp.int32)
    n = attrs.get("num_segments")
    s = jax.ops.segment_sum(ins[0], seg, num_segments=n)
    c = jax.ops.segment_sum(jnp.ones_like(ins[0]), seg, num_segments=n)
    return s / jnp.maximum(c, 1)


@op("space_to_depth", "shape")
def _space_to_depth(ins, attrs):
    s = int(attrs.get("block_size", 2))
    b, h, w, c = ins[0].shape
    z = ins[0].reshape(b, h // s, s, w // s, s, c)
    return z.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // s, w // s,
                                                 s * s * c)


@op("depth_to_space", "shape")
def _depth_to_space(ins, attrs):
    s = int(attrs.get("block_size", 2))
    b, h, w, c = ins[0].shape
    co = c // (s * s)
    z = ins[0].reshape(b, h, w, s, s, co)
    return z.transpose(0, 1, 3, 2, 4, 5).reshape(b, h * s, w * s, co)


@op("reverse", "shape")
def _reverse(ins, attrs):
    axes = attrs.get("axes")
    if axes is None and len(ins) > 1:
        axes = [int(a) for a in np.asarray(ins[1]).reshape(-1)]
    return jnp.flip(ins[0], axis=tuple(axes))


@op("roll", "shape")
def _roll(ins, attrs):
    shift = attrs.get("shift")
    axes = attrs.get("axes")
    if shift is None and len(ins) > 2:
        shift = [int(s) for s in np.asarray(ins[1]).reshape(-1)]
        axes = [int(a) for a in np.asarray(ins[2]).reshape(-1)]
    return jnp.roll(ins[0], tuple(np.atleast_1d(shift)),
                    tuple(np.atleast_1d(axes)))


@op("scatter_nd", "shape")
def _scatter_nd(ins, attrs):
    idx, updates = ins[0].astype(jnp.int32), ins[1]
    shape = attrs.get("shape")
    if shape is None and len(ins) > 2:
        shape = [int(s) for s in np.asarray(ins[2]).reshape(-1)]
    out = jnp.zeros(tuple(shape), updates.dtype)
    return out.at[tuple(jnp.moveaxis(idx, -1, 0))].add(updates)


@op("scatter_nd_update", "shape")
def _scatter_nd_update(ins, attrs):
    """data, indices [..., d], updates -> data with updates written
    (reference: scatter_upd declarable op / ONNX ScatterND)."""
    data, idx, updates = ins[0], ins[1].astype(jnp.int32), ins[2]
    return data.at[tuple(jnp.moveaxis(idx, -1, 0))].set(updates)


@op("invert_permutation", "shape")
def _invert_permutation(ins, attrs):
    p = ins[0].astype(jnp.int32)
    return jnp.zeros_like(p).at[p].set(jnp.arange(p.shape[0],
                                                  dtype=p.dtype))


@op("matrix_diag", "linalg")
def _matrix_diag(ins, attrs):
    v = ins[0]
    eye = jnp.eye(v.shape[-1], dtype=v.dtype)
    return v[..., None] * eye


@op("matrix_diag_part", "linalg")
def _matrix_diag_part(ins, attrs):
    return jnp.diagonal(ins[0], axis1=-2, axis2=-1)


@op("segment_prod", "segment")
def _segment_prod(ins, attrs):
    return jax.ops.segment_prod(ins[0], ins[1].astype(jnp.int32),
                                num_segments=attrs.get("num_segments"))


# unsorted variants: jax segment ops accept unsorted ids natively, so
# these alias the sorted spellings (reference: unsortedSegment* ops are
# distinct kernels in libnd4j; XLA scatter handles both)
alias("unsorted_segment_sum", "segment_sum")
alias("unsorted_segment_max", "segment_max")
alias("unsorted_segment_min", "segment_min")
alias("unsorted_segment_mean", "segment_mean")
alias("unsorted_segment_prod", "segment_prod")


@op("unsorted_segment_sqrt_n", "segment")
def _segment_sqrt_n(ins, attrs):
    seg = ins[1].astype(jnp.int32)
    n = attrs.get("num_segments")
    s = jax.ops.segment_sum(ins[0], seg, num_segments=n)
    c = jax.ops.segment_sum(jnp.ones_like(ins[0]), seg, num_segments=n)
    return s / jnp.sqrt(jnp.maximum(c, 1))


# -- normalization ----------------------------------------------------------
@op("layer_norm", "normalization")
def _layer_norm(ins, attrs):
    x = ins[0]
    gain = ins[1] if len(ins) > 1 else None
    bias = ins[2] if len(ins) > 2 else None
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-5)
    mu = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    y = (x - mu) * lax.rsqrt(var + eps)
    if gain is not None:
        y = y * gain
    if bias is not None:
        y = y + bias
    return y


@op("batch_norm", "normalization")
def _batch_norm(ins, attrs):
    x, mean, var, gamma, beta = ins
    eps = attrs.get("epsilon", 1e-5)
    return (x - mean) * lax.rsqrt(var + eps) * gamma + beta


@op("lrn", "normalization")
def _lrn(ins, attrs):
    # local response normalization over the channel (last) axis, NHWC
    x = ins[0]
    depth = attrs.get("depth", 5)
    bias = attrs.get("bias", 1.0)
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 0.5)
    sq = x * x
    half = depth // 2
    pads = [(0, 0)] * (x.ndim - 1) + [(half, half)]
    padded = jnp.pad(sq, pads)
    win = sum(lax.slice_in_dim(padded, i, i + x.shape[-1], axis=-1)
              for i in range(depth))
    return x / jnp.power(bias + alpha * win, beta)


@op("standardize", "normalization")
def _standardize(ins, attrs):
    x = ins[0]
    axis = attrs.get("axis", -1)
    mu = jnp.mean(x, axis=axis, keepdims=True)
    sd = jnp.std(x, axis=axis, keepdims=True)
    return (x - mu) / jnp.maximum(sd, 1e-12)


@op("moments", "normalization")
def _moments(ins, attrs):
    axis = attrs.get("axis")
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return (jnp.mean(ins[0], axis=axis), jnp.var(ins[0], axis=axis))


# -- convolution (NHWC, MXU-friendly) ---------------------------------------
def _conv_dn(ndim):
    if ndim == 3:
        return ("NWC", "WIO", "NWC")
    if ndim == 4:
        return ("NHWC", "HWIO", "NHWC")
    return ("NDHWC", "DHWIO", "NDHWC")


@op("conv2d", "convolution")
def _conv2d(ins, attrs):
    x, w = ins[0], ins[1]
    out = lax.conv_general_dilated(
        x, w, window_strides=tuple(attrs.get("stride", (1, 1))),
        padding=attrs.get("padding", "SAME"),
        rhs_dilation=tuple(attrs.get("dilation", (1, 1))),
        dimension_numbers=_conv_dn(4))
    if len(ins) > 2:
        out = out + ins[2]
    return out


@op("conv1d", "convolution")
def _conv1d(ins, attrs):
    x, w = ins[0], ins[1]
    out = lax.conv_general_dilated(
        x, w, window_strides=(attrs.get("stride", 1),),
        padding=attrs.get("padding", "SAME"),
        rhs_dilation=(attrs.get("dilation", 1),),
        dimension_numbers=_conv_dn(3))
    if len(ins) > 2:
        out = out + ins[2]
    return out


@op("conv3d", "convolution")
def _conv3d(ins, attrs):
    x, w = ins[0], ins[1]
    out = lax.conv_general_dilated(
        x, w, window_strides=tuple(attrs.get("stride", (1, 1, 1))),
        padding=attrs.get("padding", "SAME"),
        rhs_dilation=tuple(attrs.get("dilation", (1, 1, 1))),
        dimension_numbers=_conv_dn(5))
    if len(ins) > 2:
        out = out + ins[2]
    return out


@op("depthwise_conv2d", "convolution")
def _depthwise(ins, attrs):
    x, w = ins[0], ins[1]      # w: [H, W, C, M]
    c = x.shape[-1]
    kh, kw, _, m = w.shape
    out = lax.conv_general_dilated(
        x, jnp.reshape(w, (kh, kw, 1, c * m)),
        window_strides=tuple(attrs.get("stride", (1, 1))),
        padding=attrs.get("padding", "SAME"),
        feature_group_count=c, dimension_numbers=_conv_dn(4))
    if len(ins) > 2:
        out = out + ins[2]
    return out


@op("separable_conv2d", "convolution")
def _separable(ins, attrs):
    x, dw, pw = ins[0], ins[1], ins[2]
    y = _depthwise([x, dw], attrs)
    out = lax.conv_general_dilated(
        y, pw, window_strides=(1, 1), padding="VALID",
        dimension_numbers=_conv_dn(4))
    if len(ins) > 3:
        out = out + ins[3]
    return out


@op("deconv2d", "convolution")
def _deconv2d(ins, attrs):
    x, w = ins[0], ins[1]
    dil = tuple(attrs.get("dilation", (1, 1)))
    out = lax.conv_transpose(
        x, w, strides=tuple(attrs.get("stride", (1, 1))),
        padding=attrs.get("padding", "SAME"),
        rhs_dilation=None if dil == (1, 1) else dil,
        transpose_kernel=attrs.get("transpose_kernel", False),
        dimension_numbers=_conv_dn(4))
    if len(ins) > 2:
        out = out + ins[2]
    return out


def _pool(x, kind, window, strides, padding):
    ndim_sp = len(window)
    dims = (1,) + tuple(window) + (1,)
    strd = (1,) + tuple(strides) + (1,)
    if kind == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strd, padding)
    s = lax.reduce_window(x, 0.0, lax.add, dims, strd, padding)
    if kind == "sum":
        return s
    ones = jnp.ones_like(x)
    cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strd, padding)
    return s / cnt


@op("max_pool2d", "convolution")
def _maxpool(ins, attrs):
    return _pool(ins[0], "max", attrs.get("kernel", (2, 2)),
                 attrs.get("stride", (2, 2)),
                 attrs.get("padding", "VALID"))


@op("avg_pool2d", "convolution")
def _avgpool(ins, attrs):
    return _pool(ins[0], "avg", attrs.get("kernel", (2, 2)),
                 attrs.get("stride", (2, 2)),
                 attrs.get("padding", "VALID"))


@op("max_pool1d", "convolution")
def _maxpool1(ins, attrs):
    return _pool(ins[0], "max", (attrs.get("kernel", 2),),
                 (attrs.get("stride", 2),), attrs.get("padding", "VALID"))


@op("avg_pool1d", "convolution")
def _avgpool1(ins, attrs):
    return _pool(ins[0], "avg", (attrs.get("kernel", 2),),
                 (attrs.get("stride", 2),), attrs.get("padding", "VALID"))


@op("max_pool3d", "convolution")
def _maxpool3(ins, attrs):
    return _pool(ins[0], "max", attrs.get("kernel", (2, 2, 2)),
                 attrs.get("stride", (2, 2, 2)),
                 attrs.get("padding", "VALID"))


@op("avg_pool3d", "convolution")
def _avgpool3(ins, attrs):
    return _pool(ins[0], "avg", attrs.get("kernel", (2, 2, 2)),
                 attrs.get("stride", (2, 2, 2)),
                 attrs.get("padding", "VALID"))


@op("upsampling2d", "convolution")
def _upsample(ins, attrs):
    s = attrs.get("scale", 2)
    sh, sw = (s, s) if isinstance(s, int) else s
    return jnp.repeat(jnp.repeat(ins[0], sh, axis=1), sw, axis=2)


@op("im2col", "convolution")
def _im2col(ins, attrs):
    # patches as columns (reference helper op); NHWC
    x = ins[0]
    kh, kw = attrs["kernel"]
    sh, sw = attrs.get("stride", (1, 1))
    b, h, w, c = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    idx_h = jnp.arange(oh) * sh
    idx_w = jnp.arange(ow) * sw
    patches = x[:, idx_h[:, None, None, None] + jnp.arange(kh)[None, :,
                                                             None, None],
                idx_w[None, None, :, None] + jnp.arange(kw)[None, None,
                                                            None, :], :]
    return patches.reshape(b, oh, ow, kh * kw * c)


# -- image ------------------------------------------------------------------
@op("resize_bilinear", "image")
def _resize_bilinear(ins, attrs):
    x = ins[0]
    h, w = attrs["size"]
    return jax.image.resize(x, (x.shape[0], h, w, x.shape[3]), "bilinear")


@op("resize_nearest", "image")
def _resize_nearest(ins, attrs):
    x = ins[0]
    h, w = attrs["size"]
    if attrs.get("coordinate_mode") == "asymmetric":
        # ONNX/torch nearest export convention (asymmetric + floor):
        # src index = floor(dst * in/out)
        iy = jnp.floor(jnp.arange(h) * (x.shape[1] / h)).astype(
            jnp.int32)
        ix = jnp.floor(jnp.arange(w) * (x.shape[2] / w)).astype(
            jnp.int32)
        return x[:, iy][:, :, ix]
    return jax.image.resize(x, (x.shape[0], h, w, x.shape[3]), "nearest")


def _cubic_weights(out_size: int, in_size: int, a: float,
                   boundary: str):
    """[out, in] separable Keys-cubic interpolation weights with
    half-pixel centers. Two exporter conventions, probed empirically
    against the frameworks (see test_tf_import TestResizeVariants):
    TF ResizeBicubic = a=-0.5 with out-of-range taps DROPPED and the
    row renormalized ("renorm"); torch/ONNX = a=-0.75 with indices
    clamped to the edge ("clamp")."""
    s = in_size / out_size
    src = (np.arange(out_size) + 0.5) * s - 0.5
    base = np.floor(src).astype(np.int64)
    frac = src - base
    w = np.zeros((out_size, in_size), np.float64)
    for o in (-1, 0, 1, 2):
        t = np.abs(frac - o)
        k = np.where(
            t <= 1, (a + 2) * t**3 - (a + 3) * t**2 + 1,
            np.where(t < 2, a * (t**3 - 5 * t**2 + 8 * t - 4), 0.0))
        idx = base + o
        if boundary == "renorm":
            k = np.where((idx < 0) | (idx >= in_size), 0.0, k)
        idx = np.clip(idx, 0, in_size - 1)
        np.add.at(w, (np.arange(out_size), idx), k)
    if boundary == "renorm":
        w /= w.sum(axis=1, keepdims=True)
    return jnp.asarray(w, jnp.float32)


@op("resize_bicubic", "image")
def _resize_bicubic(ins, attrs):
    x = ins[0]
    h, w = attrs["size"]
    a = float(attrs.get("cubic_coeff_a", -0.5))
    boundary = attrs.get("boundary", "renorm")
    wh = _cubic_weights(h, x.shape[1], a, boundary)
    ww = _cubic_weights(w, x.shape[2], a, boundary)
    # HIGHEST: resize is preprocessing — exact f32 interpolation, not
    # the TPU default bf16-accumulate (conformance vs TF/torch)
    y = jnp.einsum("oh,bhwc->bowc", wh, x.astype(jnp.float32),
                   precision=jax.lax.Precision.HIGHEST)
    y = jnp.einsum("ow,bhwc->bhoc", ww, y,
                   precision=jax.lax.Precision.HIGHEST)
    return y.astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.floating) \
        else y


def _area_weights(out_size: int, in_size: int):
    """[out, in] row-stochastic overlap weights for area resize: output
    cell i integrates input cells overlapping [i*s, (i+1)*s), s=in/out,
    weighted by overlap fraction (the TF ResizeArea algorithm)."""
    s = in_size / out_size
    i = np.arange(out_size)[:, None]
    j = np.arange(in_size)[None, :]
    overlap = np.minimum((i + 1) * s, j + 1) - np.maximum(i * s, j)
    w = np.clip(overlap, 0.0, 1.0) / s
    return jnp.asarray(w, jnp.float32)


@op("resize_area", "image")
def _resize_area(ins, attrs):
    x = ins[0]
    h, w = attrs["size"]
    wh = _area_weights(h, x.shape[1])
    ww = _area_weights(w, x.shape[2])
    y = jnp.einsum("oh,bhwc->bowc", wh, x.astype(jnp.float32),
                   precision=jax.lax.Precision.HIGHEST)
    y = jnp.einsum("ow,bhwc->bhoc", ww, y,
                   precision=jax.lax.Precision.HIGHEST)
    return y.astype(x.dtype) if jnp.issubdtype(x.dtype, jnp.floating) \
        else y


@op("crop_and_resize", "image")
def _crop_resize(ins, attrs):
    img, boxes, box_idx = ins
    ch, cw = attrs["crop_size"]

    def one(box, bi):
        y1, x1, y2, x2 = box
        im = img[bi.astype(jnp.int32)]
        h, w = im.shape[0], im.shape[1]
        ys = y1 * (h - 1) + jnp.arange(ch) / max(ch - 1, 1) * \
            (y2 - y1) * (h - 1)
        xs = x1 * (w - 1) + jnp.arange(cw) / max(cw - 1, 1) * \
            (x2 - x1) * (w - 1)
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        return (im[y0][:, x0] * (1 - wy) * (1 - wx) +
                im[y0][:, x1i] * (1 - wy) * wx +
                im[y1i][:, x0] * wy * (1 - wx) +
                im[y1i][:, x1i] * wy * wx)

    return jax.vmap(one)(boxes, box_idx)


@op("extract_image_patches", "image")
def _extract_patches(ins, attrs):
    return _im2col(ins, attrs)


@op("non_max_suppression", "image")
def _nms(ins, attrs):
    boxes, scores = ins
    max_out = attrs["max_output_size"]
    iou_thr = attrs.get("iou_threshold", 0.5)

    def iou(a, b):
        y1 = jnp.maximum(a[0], b[:, 0])
        x1 = jnp.maximum(a[1], b[:, 1])
        y2 = jnp.minimum(a[2], b[:, 2])
        x2 = jnp.minimum(a[3], b[:, 3])
        inter = jnp.clip(y2 - y1, 0) * jnp.clip(x2 - x1, 0)
        area_a = (a[2] - a[0]) * (a[3] - a[1])
        area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        return inter / jnp.maximum(area_a + area_b - inter, 1e-9)

    # static greedy loop (max_out is a static attr); once every box is
    # picked or suppressed, remaining slots are padded with -1 so the
    # caller can distinguish real picks (TF pads with fewer outputs).
    sc = scores
    picks = []
    for _ in range(max_out):
        i = jnp.argmax(sc)
        valid = sc[i] > -jnp.inf
        picks.append(jnp.where(valid, i, -1))
        suppress = iou(boxes[i], boxes) > iou_thr
        sc = jnp.where(valid & suppress, -jnp.inf, sc)
        sc = sc.at[i].set(-jnp.inf)
    return jnp.stack(picks)


# -- random -----------------------------------------------------------------
def _rng_from_attrs(attrs):
    return jax.random.PRNGKey(attrs.get("seed", 0))


@op("random_normal", "random")
def _rand_normal(ins, attrs):
    return attrs.get("mean", 0.0) + attrs.get("stddev", 1.0) * \
        jax.random.normal(attrs["rng"], tuple(attrs["shape"]))


@op("random_uniform", "random")
def _rand_uniform(ins, attrs):
    return jax.random.uniform(attrs["rng"], tuple(attrs["shape"]),
                              minval=attrs.get("min", 0.0),
                              maxval=attrs.get("max", 1.0))


@op("random_bernoulli", "random")
def _rand_bern(ins, attrs):
    return jax.random.bernoulli(attrs["rng"], attrs.get("prob", 0.5),
                                tuple(attrs["shape"])).astype(jnp.float32)


@op("dropout", "random")
def _dropout(ins, attrs):
    x = ins[0]
    p = attrs.get("rate", 0.5)            # drop probability
    rng = attrs.get("rng")
    if rng is None or not attrs.get("training", True):
        return x
    keep = jax.random.bernoulli(rng, 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), 0.0)


# -- losses -----------------------------------------------------------------
def _apply_weights_reduce(loss, weights, reduction):
    if weights is not None:
        loss = loss * weights
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    return jnp.mean(loss)


@op("softmax_cross_entropy", "loss")
def _sce(ins, attrs):
    labels, logits = ins[0], ins[1]
    ls = attrs.get("label_smoothing", 0.0)
    if ls:
        n = labels.shape[-1]
        labels = labels * (1 - ls) + ls / n
    loss = -jnp.sum(labels * jax.nn.log_softmax(logits, -1), axis=-1)
    return _apply_weights_reduce(loss, ins[2] if len(ins) > 2 else None,
                                 attrs.get("reduction", "mean"))


@op("sparse_softmax_cross_entropy", "loss")
def _ssce(ins, attrs):
    labels, logits = ins[0], ins[1]
    lp = jax.nn.log_softmax(logits, -1)
    loss = -jnp.take_along_axis(
        lp, labels.astype(jnp.int32)[..., None], axis=-1)[..., 0]
    return _apply_weights_reduce(loss, None,
                                 attrs.get("reduction", "mean"))


@op("sigmoid_cross_entropy", "loss")
def _bce(ins, attrs):
    labels, logits = ins[0], ins[1]
    loss = jnp.maximum(logits, 0) - logits * labels + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _apply_weights_reduce(loss, ins[2] if len(ins) > 2 else None,
                                 attrs.get("reduction", "mean"))


@op("mean_squared_error", "loss")
def _mse_loss(ins, attrs):
    loss = (ins[0] - ins[1]) ** 2
    return _apply_weights_reduce(loss, ins[2] if len(ins) > 2 else None,
                                 attrs.get("reduction", "mean"))


@op("absolute_difference", "loss")
def _mae_loss(ins, attrs):
    loss = jnp.abs(ins[0] - ins[1])
    return _apply_weights_reduce(loss, ins[2] if len(ins) > 2 else None,
                                 attrs.get("reduction", "mean"))


@op("huber_loss", "loss")
def _huber(ins, attrs):
    d = attrs.get("delta", 1.0)
    err = ins[0] - ins[1]
    loss = jnp.where(jnp.abs(err) <= d, 0.5 * err ** 2,
                     d * (jnp.abs(err) - 0.5 * d))
    return _apply_weights_reduce(loss, ins[2] if len(ins) > 2 else None,
                                 attrs.get("reduction", "mean"))


@op("log_loss", "loss")
def _log_loss(ins, attrs):
    labels, preds = ins[0], ins[1]
    eps = attrs.get("epsilon", 1e-7)
    loss = -(labels * jnp.log(preds + eps) +
             (1 - labels) * jnp.log(1 - preds + eps))
    return _apply_weights_reduce(loss, ins[2] if len(ins) > 2 else None,
                                 attrs.get("reduction", "mean"))


@op("cosine_distance", "loss")
def _cos_loss(ins, attrs):
    a, b = ins[0], ins[1]
    axis = attrs.get("axis", -1)
    loss = 1.0 - jnp.sum(a * b, axis=axis)
    return _apply_weights_reduce(loss, None,
                                 attrs.get("reduction", "mean"))


@op("hinge_loss", "loss")
def _hinge(ins, attrs):
    labels, logits = ins[0], ins[1]
    signed = 2.0 * labels - 1.0
    loss = jnp.maximum(0.0, 1.0 - signed * logits)
    return _apply_weights_reduce(loss, None,
                                 attrs.get("reduction", "mean"))


# -- attention (Appendix A: attention domain) -------------------------------
@op("apply_key_mask", "attention")
def _apply_key_mask(ins, attrs):
    """Pre-softmax mask select: where(mask > 0, scores, neg). The
    strength-reduced form of the exporter's additive
    ``scores + (1-mask)*neg`` bias chain (autodiff.passes.
    mask_strength_reduce) — same post-softmax values for any row with
    >= 1 unmasked key, and the form attention_fuse turns into
    ``sdpa_core``'s native key-mask mode."""
    x, m = ins[0], ins[1]
    neg = attrs.get("neg", -1e9)
    return jnp.where(m > 0, x, jnp.asarray(neg, x.dtype))


@op("sdpa_core", "attention")
def _sdpa_core(ins, attrs):
    """Fused scaled-dot-product-attention core: softmax(q k^T * scale
    [+ bias | masked]) v with q/k/v [..., t, dh]. The target of the
    GraphOptimizer attention fusion — one op XLA schedules as a unit
    (and jax.checkpoint recomputes as a unit).

    ``attrs["mask_mode"] == "key"`` marks the 4th input as a key mask
    (0 = masked, broadcastable to the score shape) instead of an
    additive bias. Backend dispatch: the Pallas flash-attention
    kernel (ops/attention_pallas.py) takes the op when the
    sequence-length/HBM-headroom heuristic (or the
    DL4J_TPU_FLASH_ATTENTION override) selects it and the site is
    structurally streamable (no dense additive bias); otherwise the
    ONE shared einsum implementation (ops/attention.py) runs."""
    from deeplearning4j_tpu.ops.attention import dot_product_attention
    from deeplearning4j_tpu.ops.attention_pallas import maybe_flash_sdpa
    q, k, v = ins[0], ins[1], ins[2]
    extra = ins[3] if len(ins) > 3 else None
    scale = attrs.get("scale", 1.0)
    if attrs.get("mask_mode") == "key":
        mask, bias = extra, None
    else:
        mask, bias = None, extra
    out = maybe_flash_sdpa(q, k, v, scale, mask=mask, bias=bias)
    if out is not None:
        return out
    return dot_product_attention(q, k, v, mask=mask, scale=scale,
                                 bias=bias)


@op("dot_product_attention", "attention")
def _dpa(ins, attrs):
    from deeplearning4j_tpu.ops.attention import dot_product_attention
    q, k, v = ins[0], ins[1], ins[2]
    mask = ins[3] if len(ins) > 3 else None
    return dot_product_attention(q, k, v, mask,
                                 scale=attrs.get("scale"))


@op("multi_head_dot_product_attention", "attention")
def _mhdpa(ins, attrs):
    # x: [b, t, d]; Wq/Wk/Wv: [d, h*dh]; Wo: [h*dh, d]
    from deeplearning4j_tpu.ops.attention import multi_head_attention
    x, wq, wk, wv, wo = ins[0], ins[1], ins[2], ins[3], ins[4]
    mask = ins[5] if len(ins) > 5 else None
    params = {"Wq": wq, "Wk": wk, "Wv": wv, "Wo": wo}
    return multi_head_attention(params, x, x, attrs["num_heads"],
                                key_mask=mask)


# -- recurrent (cell-level ops; layer-level lives in nn.conf) ----------------
@op("lstm_cell", "recurrent")
def _lstm_cell(ins, attrs):
    x, h_prev, c_prev, w, rw, b = ins
    H = h_prev.shape[-1]
    z = x @ w + h_prev @ rw + b
    i = jax.nn.sigmoid(z[:, :H])
    f = jax.nn.sigmoid(z[:, H:2 * H])
    o = jax.nn.sigmoid(z[:, 2 * H:3 * H])
    g = jnp.tanh(z[:, 3 * H:])
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return h, c


@op("gru_cell", "recurrent")
def _gru_cell(ins, attrs):
    x, h_prev, w, rw, b = ins
    H = h_prev.shape[-1]
    xw = x @ w + b
    hr = h_prev @ rw
    r = jax.nn.sigmoid(xw[:, :H] + hr[:, :H])
    zt = jax.nn.sigmoid(xw[:, H:2 * H] + hr[:, H:2 * H])
    n = jnp.tanh(xw[:, 2 * H:] + r * hr[:, 2 * H:])
    return (1 - zt) * n + zt * h_prev


@op("sru_cell", "recurrent")
def _sru_cell(ins, attrs):
    x, c_prev, w, b = ins
    H = c_prev.shape[-1]
    z = x @ w + b
    f = jax.nn.sigmoid(z[:, H:2 * H])
    r = jax.nn.sigmoid(z[:, 2 * H:3 * H])
    c = f * c_prev + (1 - f) * z[:, :H]
    return r * jnp.tanh(c) + (1 - r) * x[:, :H], c


@op("lstm_layer", "recurrent")
def _lstm_layer(ins, attrs):
    """Full-sequence LSTM via lax.scan (reference: libnd4j lstmLayer,
    the op behind the reference's cuDNN LSTM path). Inputs: x [b, t, f],
    h0 [b, H], c0 [b, H], w [f, 4H], rw [H, 4H], b [4H].
    Returns (h_seq [b, t, H], h_last, c_last)."""
    x, h0, c0, w, rw, b = ins
    H = h0.shape[-1]

    def cell(carry, xt):
        h_prev, c_prev = carry
        z = xt @ w + h_prev @ rw + b
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H:2 * H])
        o = jax.nn.sigmoid(z[:, 2 * H:3 * H])
        g = jnp.tanh(z[:, 3 * H:])
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (h_last, c_last), hs = lax.scan(cell, (h0, c0),
                                    jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(hs, 0, 1), h_last, c_last


@op("rnn_layer", "recurrent")
def _rnn_layer(ins, attrs):
    """Full-sequence vanilla RNN via lax.scan (ONNX RNN semantics):
    h_t = tanh(x_t W + h_{t-1} R + b).  Inputs: x [b, t, f],
    h0 [b, H], w [f, H], rw [H, H], b [H].
    Returns (h_seq [b, t, H], h_last)."""
    x, h0, w, rw, b = ins

    def cell(h, xt):
        hn = jnp.tanh(xt @ w + h @ rw + b)
        return hn, hn

    h_last, hs = lax.scan(cell, h0, jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(hs, 0, 1), h_last


@op("gru_layer", "recurrent")
def _gru_layer(ins, attrs):
    """Full-sequence GRU via lax.scan, ONNX GRU semantics (gate order
    (z, r, h) on the last weight axis; ``linear_before_reset`` per the
    spec — torch exports 1).  Inputs: x [b, t, f], h0 [b, H],
    w [f, 3H], rw [H, 3H], wb [3H], rb [3H].
    Returns (h_seq [b, t, H], h_last)."""
    x, h0, w, rw, wb, rb = ins
    H = h0.shape[-1]
    lbr = bool(attrs.get("linear_before_reset", False))

    def cell(h, xt):
        xz = xt @ w + wb
        # lbr=0 computes the h-gate recurrent term on (r*h) separately
        # — slice the main recurrent matmul to z/r there (a dot can't
        # be dead-code-split by XLA)
        hz = h @ (rw if lbr else rw[:, :2 * H])
        z = jax.nn.sigmoid(xz[:, :H] + hz[:, :H] + rb[:H])
        r = jax.nn.sigmoid(xz[:, H:2 * H] + hz[:, H:2 * H]
                           + rb[H:2 * H])
        if lbr:
            n = jnp.tanh(xz[:, 2 * H:]
                         + r * (hz[:, 2 * H:] + rb[2 * H:]))
        else:
            n = jnp.tanh(xz[:, 2 * H:]
                         + (r * h) @ rw[:, 2 * H:] + rb[2 * H:])
        hn = (1.0 - z) * n + z * h
        return hn, hn

    h_last, hs = lax.scan(cell, h0, jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(hs, 0, 1), h_last


@op("sru", "recurrent")
def _sru_layer(ins, attrs):
    """Full-sequence SRU via lax.scan (reference: libnd4j sru op).
    Inputs: x [b, t, f], c0 [b, H], w [f, 3H], b [3H] with H == f.
    Returns (out_seq [b, t, H], c_last)."""
    x, c0, w, b = ins
    H = c0.shape[-1]

    def cell(c_prev, xt):
        z = xt @ w + b
        f = jax.nn.sigmoid(z[:, H:2 * H])
        r = jax.nn.sigmoid(z[:, 2 * H:3 * H])
        c = f * c_prev + (1 - f) * z[:, :H]
        out = r * jnp.tanh(c) + (1 - r) * xt[:, :H]
        return c, out

    c_last, outs = lax.scan(cell, c0, jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(outs, 0, 1), c_last


# -- compression (threshold encoding, SURVEY.md J11/P2) ---------------------
@op("encode_threshold", "compression")
def _encode_thr(ins, attrs):
    from deeplearning4j_tpu.parallel.encoding import encode_threshold
    return encode_threshold(ins[0], attrs.get("threshold", 1e-3))


@op("decode_threshold", "compression")
def _decode_thr(ins, attrs):
    return ins[0]


# -- generic contraction / indexing (used by the TF importer) ---------------
@op("einsum", "blas")
def _einsum(ins, attrs):
    return jnp.einsum(attrs["equation"], *ins)


def spec_to_index(spec) -> tuple:
    """{"kind": "slice"|"int"|"newaxis"|"ellipsis", ...} items → a
    python indexing tuple (shared by the 'index' op and the TF
    importer's StridedSlice constant folder)."""
    idx = []
    for item in spec:
        kind = item["kind"]
        if kind == "slice":
            idx.append(slice(item.get("begin"), item.get("end"),
                             item.get("stride")))
        elif kind == "int":
            idx.append(item["i"])
        elif kind == "newaxis":
            idx.append(None)
        elif kind == "ellipsis":
            idx.append(Ellipsis)
        else:
            raise ValueError(f"bad index spec kind {kind!r}")
    return tuple(idx)


@op("index", "shape")
def _index(ins, attrs):
    """Generalized indexing — the importer's lowering target for TF
    StridedSlice masks."""
    return ins[0][spec_to_index(attrs["spec"])]


@op("identity", "transform")
def _identity_op(ins, attrs):
    return ins[0]


# -- control flow (SURVEY.md S3 / Appendix A: while/cond/merge/switch) ------
# These ops carry TRACED SUBGRAPHS in their attrs (callables built by
# SameDiff.while_loop/cond/scan from child graphs) and lower to
# lax.while_loop / lax.cond / lax.scan — the XLA-native control flow
# the reference's TF-style Enter/Exit/Merge/Switch frames compile to.
@jax.custom_vjp
def _while_capture_trap(x):
    """Identity on the forward pass; requesting a gradient through it
    raises — an unbounded while_loop has no reverse rule (XLA while
    is not reverse-differentiable), and silently stopping the
    gradient trains wrong. Applied to the loop outputs, so every
    reverse path into the loop hits it."""
    return x


def _while_trap_fwd(x):
    return x, None


def _while_trap_bwd(_res, _g):
    raise NotImplementedError(
        "gradient requested through a while_loop capture. XLA's while "
        "has no reverse rule; pass max_iterations=N to while_loop to "
        "lower it to a reverse-differentiable bounded scan (the "
        "TF maximum_iterations semantics), or thread the value so the "
        "loss does not depend on the loop.")


_while_capture_trap.defvjp(_while_trap_fwd, _while_trap_bwd)


@op("while_loop", "control")
def _while_loop(ins, attrs):
    cond = attrs["_cond_call"]
    body = attrs["_body_call"]
    n = attrs.get("n_loop", len(ins))
    ncc = attrs.get("n_cond_caps", 0)
    loop0 = tuple(ins[:n])
    max_iter = attrs.get("max_iterations")

    if max_iter is not None:
        # Reverse-differentiable lowering (reference: SameDiff builds
        # gradients through TF Enter/Exit/NextIteration loop frames;
        # TF's while_loop(maximum_iterations=...)): run a lax.scan for
        # the static bound, masking updates once the condition goes
        # false. scan has a transpose rule, so gradients flow through
        # loop vars AND captures; trips beyond the bound truncate
        # exactly like TF's maximum_iterations.
        cond_caps = tuple(ins[n:n + ncc])
        body_caps = tuple(ins[n + ncc:])

        def step(carry, _):
            vars_, done = carry
            cnd = jnp.squeeze(
                cond(*vars_, *cond_caps)[0]).astype(bool)
            active = jnp.logical_and(jnp.logical_not(done), cnd)
            new_vars = tuple(body(*vars_, *body_caps))
            vars_ = tuple(jnp.where(active, nv, ov)
                          for nv, ov in zip(new_vars, vars_))
            return (vars_, jnp.logical_or(done,
                                          jnp.logical_not(cnd))), None

        (out, _done), _ = lax.scan(
            step, (loop0, jnp.asarray(False)), None,
            length=int(max_iter))
        return out if len(out) > 1 else out[0]

    # Unbounded: true lax.while_loop. No reverse rule exists, so the
    # gradient must not SILENTLY vanish — every reverse path into the
    # loop enters through its outputs, and the trap on them raises
    # with the fix (max_iterations) the moment a gradient is
    # requested. Captures stay live (stop_gradient would be the
    # silent-wrong-training trap this replaces).
    cond_caps = tuple(ins[n:n + ncc])
    body_caps = tuple(ins[n + ncc:])

    def c(carry):
        return jnp.squeeze(cond(*carry, *cond_caps)[0]).astype(bool)

    def b(carry):
        return tuple(body(*carry, *body_caps))

    out = tuple(_while_capture_trap(o)
                for o in lax.while_loop(c, b, loop0))
    return out if len(out) > 1 else out[0]


@op("cond", "control")
def _cond(ins, attrs):
    true_call = attrs["_true_call"]
    false_call = attrs["_false_call"]
    n_ops = attrs.get("n_operands", len(ins) - 1)
    ntc = attrs.get("n_true_caps", 0)
    pred = jnp.squeeze(ins[0]).astype(bool)
    operands = tuple(ins[1:1 + n_ops])
    t_caps = tuple(ins[1 + n_ops:1 + n_ops + ntc])
    f_caps = tuple(ins[1 + n_ops + ntc:])
    out = lax.cond(pred,
                   lambda ops: tuple(true_call(*ops, *t_caps)),
                   lambda ops: tuple(false_call(*ops, *f_caps)),
                   operands)
    return out if len(out) > 1 else out[0]


@op("scan", "control")
def _scan(ins, attrs):
    body = attrs["_body_call"]
    n_carry = attrs["n_carry"]
    n_xs = attrs.get("n_xs", len(ins) - n_carry)
    carry0 = tuple(ins[:n_carry])
    xs = tuple(ins[n_carry:n_carry + n_xs])
    caps = tuple(ins[n_carry + n_xs:])

    def b(carry, x):
        step_args = () if x is None else tuple(x)
        res = body(*carry, *step_args, *caps)
        return tuple(res[:n_carry]), tuple(res[n_carry:])

    carry, ys = lax.scan(b, carry0, xs if xs else None,
                         length=attrs.get("length"))
    out = tuple(carry) + tuple(ys)
    return out if len(out) > 1 else out[0]


# TF-graph-style primitives, select-lowered: XLA computes BOTH
# branches and merge selects by the predicate (no dead-branch
# pruning — which is how GSPMD treats data-dependent branches anyway).
# switch(data, pred) -> (false_out, true_out): both carry the data so
# arbitrary (non-zero-preserving) ops can follow on either branch;
# merge(false_val, true_val, pred) selects the live one.
@op("switch", "control")
def _switch(ins, attrs):
    data, _pred = ins
    return (data, data)


@op("merge", "control")
def _merge(ins, attrs):
    if len(ins) != 3:
        raise ValueError("merge expects (false_val, true_val, pred)")
    false_val, true_val, pred = ins
    p = jnp.squeeze(pred).astype(bool)
    return jnp.where(p, true_val, false_val)
