"""GraphOptimizer — ordered, fixpoint-iterated rewrite passes over a
SameDiff op graph.

Reference parity: ``org.nd4j.autodiff.samediff.optimize.GraphOptimizer``
+ ``OptimizationConfig`` (the reference runs ordered ``Optimizer`` lists
until quiescence); pipeline design in the spirit of TVM's pass manager
(PAPERS.md, 1802.04799). This grows the single
``fuse_attention_patterns`` seam into a real pass suite targeting the
arithmetic TF/ONNX *exporters* bake into transformer graphs — the
residual imported-vs-native gap isolated in BENCH_notes_r05:

  cast_fold             constant-fold casts of constants, drop identity
                        casts and dead dtype round-trips
  mask_strength_reduce  rewrite the exporter's ``(1-mask)*-1e9`` additive
                        attention-bias chains into one ``apply_key_mask``
                        select — the native key-mask form ``sdpa_core``
                        accepts directly
  layernorm_refuse      re-fuse decomposed LayerNorm op walks
                        (mean/var/rsqrt TF form AND the HF-ONNX
                        sub/pow/sqrt/div form) into the native
                        ``layer_norm`` op
  gelu_refuse           re-fuse decomposed GELU chains (erf form and
                        tanh approximation) into ``gelu``/``gelu_tanh``
  attention_fuse        the existing attention fusion, now also matching
                        the ``apply_key_mask`` form so imported masked
                        attention lowers to ONE ``sdpa_core`` with a
                        native key mask

Every pass follows the r5 fusion discipline: pattern interiors must be
consumed ONLY inside the matched pattern (conservative at
multi-consumer sites), the terminal op of the chain is rewritten IN
PLACE so requested output names stay stable, and dead interior ops are
simply left behind — the executor walks ancestors of the requested
outputs only. Rewrites are exactness-preserving for the exporter
conventions they target (see each pass docstring for the precise
contract); each pass is idempotent, so a second ``run()`` reports zero
rewrites.

Observability: ``dl4j_graphopt_rewrites_total{pass=...}`` counts
rewrites on the telemetry spine, each pass runs under a
``graphopt.<pass>`` span, and ``DL4J_TPU_DUMP_GRAPHOPT=1`` dumps the
op walk before/after each mutating pass. ``DL4J_TPU_GRAPHOPT=0`` kills
the post-import pipeline invocation entirely.
"""
from __future__ import annotations

import logging
import os
import sys
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import (OpNode, SDVariable,
                                                  VariableType)
from deeplearning4j_tpu.common import telemetry

log = logging.getLogger("deeplearning4j_tpu")

_REWRITES = telemetry.counter(
    "dl4j_graphopt_rewrites_total",
    "GraphOptimizer rewrites applied, labeled by pass")

#: ops that only select/rearrange elements — they commute with any
#: elementwise computation, so a chain of them between a matched
#: pattern and its use site can be replayed on a different input
_SHAPE_ONLY_OPS = frozenset({
    "reshape", "expand_dims", "squeeze", "index", "slice",
    "strided_slice", "permute", "transpose", "tile", "broadcast_to",
    "identity",
})


def graphopt_enabled() -> bool:
    """Post-import pipeline gate: on unless DL4J_TPU_GRAPHOPT=0
    (Environment ``extra["graphopt"]`` overrides)."""
    from deeplearning4j_tpu.common.environment import Environment
    flag = Environment.get().extra.get("graphopt")
    if flag is None:
        flag = os.environ.get("DL4J_TPU_GRAPHOPT", "1")
    return str(flag) in ("1", "true", "True", "yes")


def _dump_enabled() -> bool:
    from deeplearning4j_tpu.common.environment import Environment
    flag = Environment.get().extra.get("dump_graphopt")
    if flag is None:
        flag = os.environ.get("DL4J_TPU_DUMP_GRAPHOPT", "0")
    return str(flag) in ("1", "true", "True", "yes")


def dump_walk(sd, tag: str, stream=None) -> None:
    """Print the op walk (idx, op, inputs -> outputs, attrs) — the
    DL4J_TPU_DUMP_GRAPHOPT debugging surface."""
    stream = stream or sys.stderr
    lines = [f"[graphopt] {tag}: {len(sd.ops)} ops"]
    for i, o in enumerate(sd.ops):
        at = f"  {o.attrs}" if o.attrs else ""
        lines.append(f"  {i:4d}  {o.op_name}({', '.join(o.inputs)})"
                     f" -> {', '.join(o.outputs)}{at}")
    print("\n".join(lines), file=stream)


# -- shared pattern-matching helpers ----------------------------------------
class _Ctx:
    """Per-pass view of the graph: consumer map + lookup helpers.
    Built once at pass start; rewrites within the pass only ever
    REMOVE consumers from matched sites (patterns are disjoint by the
    interior-consumer discipline), so stale entries overcount
    consumers — which errs conservative."""

    def __init__(self, sd):
        self.sd = sd
        self.consumers: Dict[str, List[int]] = {}
        for idx, o in enumerate(sd.ops):
            for inp in o.inputs:
                self.consumers.setdefault(inp, []).append(idx)

    def producer(self, name: str) -> Optional[OpNode]:
        i = self.sd._producer.get(name)
        return self.sd.ops[i] if i is not None else None

    def producer_idx(self, name: str) -> Optional[int]:
        return self.sd._producer.get(name)

    def single_use(self, name: str) -> bool:
        return len(self.consumers.get(name, ())) == 1

    def scalar_const(self, name: str) -> Optional[float]:
        a = self.sd._arrays.get(name)
        if a is None or np.size(np.asarray(a)) != 1:
            return None
        v = self.sd.vars.get(name)
        if v is None or v.var_type is not VariableType.CONSTANT:
            return None
        return float(np.asarray(a).reshape(()))

    def interiors_private(self, op_idxs, terminal_idx: int) -> bool:
        """True iff every value produced by ``op_idxs`` (except the
        terminal's outputs) is consumed only inside the matched
        pattern — the conservative multi-consumer guard every pass
        shares."""
        idx_set = set(op_idxs) | {terminal_idx}
        for i in idx_set:
            if i == terminal_idx:
                continue
            o = self.sd.ops[i]
            for out in o.outputs:
                for c in self.consumers.get(out, ()):
                    if c not in idx_set:
                        return False
        return True

    def append_op(self, op_name: str, inputs: List[str], attrs: dict,
                  base: str) -> str:
        """Append a fresh op at raw level (the pass runs outside
        ``_op``'s user-facing validation); returns the output name."""
        out = self.sd._unique(base)
        node = OpNode(op_name, list(inputs), [out], dict(attrs))
        idx = len(self.sd.ops)
        self.sd.ops.append(node)
        self.sd.vars[out] = SDVariable(self.sd, out, VariableType.ARRAY)
        self.sd._producer[out] = idx
        for inp in inputs:
            self.consumers.setdefault(inp, []).append(idx)
        return out

    def repoint(self, old: str, new: str) -> None:
        """Redirect every consumer of ``old`` to read ``new``."""
        for i in self.consumers.pop(old, []):
            o = self.sd.ops[i]
            o.inputs = [new if n == old else n for n in o.inputs]
            self.consumers.setdefault(new, []).append(i)


def _dtype_of(ctx: _Ctx, name: str):
    """Best statically-known dtype of a value, or None. Sources, in
    order: a stored array (constants/variables), var metadata, the
    producing cast's target dtype."""
    a = ctx.sd._arrays.get(name)
    if a is not None:
        try:
            return np.dtype(a.dtype)
        except TypeError:
            return None
    v = ctx.sd.vars.get(name)
    dt = getattr(v, "dtype", None)
    if dt is not None:
        try:
            return np.dtype(dt)
        except TypeError:
            return None
    p = ctx.producer(name)
    if p is not None and p.op_name == "cast":
        try:
            return np.dtype(p.attrs.get("dtype"))
        except TypeError:
            return None
    return None


def _value_preserving(src, dst) -> bool:
    """True iff casting src->dst loses no values (so a later cast of
    the result equals a direct cast of the source)."""
    try:
        return bool(np.can_cast(src, dst, casting="safe"))
    except TypeError:
        return False


def _last_axis_reduce(ctx: _Ctx, node: OpNode) -> bool:
    if node.op_name != "reduce_mean":
        return False
    if not node.attrs.get("keep_dims"):
        return False
    ax = node.attrs.get("axis")
    if isinstance(ax, (list, tuple)):
        if len(ax) != 1:
            return False
        ax = ax[0]
    if ax is None:
        return False
    if int(ax) == -1:
        return True
    v = ctx.sd.vars.get(node.inputs[0])
    shp = getattr(v, "shape", None)
    return shp is not None and int(ax) == len(shp) - 1


def _close(val: Optional[float], target: float, rtol: float = 1e-3):
    return val is not None and abs(val - target) <= rtol * abs(target)


def _resort_ops(sd) -> None:
    """Restore topological op order (the executor runs ops in index
    order) after a pass appends ops whose consumers sit earlier in
    the walk. Stable Kahn sort — untouched regions keep their
    relative order — followed by a ``_producer`` rebuild."""
    import heapq
    prod = {}
    for i, o in enumerate(sd.ops):
        for out in o.outputs:
            prod[out] = i
    succs: Dict[int, List[int]] = {}
    indeg = [0] * len(sd.ops)
    for i, o in enumerate(sd.ops):
        for inp in o.inputs:
            j = prod.get(inp)
            if j is not None and j != i:
                succs.setdefault(j, []).append(i)
                indeg[i] += 1
    heap = [i for i, d in enumerate(indeg) if d == 0]
    heapq.heapify(heap)
    order = []
    while heap:
        i = heapq.heappop(heap)
        order.append(i)
        for s in succs.get(i, ()):
            indeg[s] -= 1
            if indeg[s] == 0:
                heapq.heappush(heap, s)
    if len(order) != len(sd.ops):     # cycle — leave the walk alone
        log.warning("graphopt: topo re-sort found a cycle; "
                    "keeping existing op order")
        return
    sd.ops = [sd.ops[i] for i in order]
    sd._producer = {out: idx for idx, o in enumerate(sd.ops)
                    for out in o.outputs}


# -- pass 1: cast folding ---------------------------------------------------
def cast_fold(sd) -> int:
    """Constant-fold and eliminate exporter cast arithmetic.

    Three exact rewrites, fixpoint-composable:
      * identity cast (target dtype == known input dtype): consumers
        read the input directly;
      * cast-of-cast where the inner hop is value-preserving
        (np.can_cast 'safe'): skip the intermediate — this unwinds
        the f32->f64->f32 round-trips exporters bake, because after
        the skip the outer cast becomes an identity cast;
      * cast of a CONSTANT: folded to a new constant at import time
        (memoized per (const, dtype)), so the per-step graph never
        recasts frozen weights.

    The cast op itself is never deleted — a requested output named
    after it still executes; it merely goes dead when nothing reads
    it."""
    ctx = _Ctx(sd)
    folded_consts: Dict[Tuple[str, str], str] = {}
    count = 0
    for node in list(sd.ops):
        if node.op_name != "cast":
            continue
        try:
            target = np.dtype(node.attrs.get("dtype"))
        except TypeError:
            continue
        src_name = node.inputs[0]
        src_dt = _dtype_of(ctx, src_name)
        # identity cast: x.astype(x.dtype) is exact for every dtype
        if src_dt is not None and src_dt == target:
            if ctx.consumers.get(node.outputs[0]):
                ctx.repoint(node.outputs[0], src_name)
                count += 1
            continue
        # cast-of-cast: collapse through a value-preserving inner hop
        inner = ctx.producer(src_name)
        if inner is not None and inner.op_name == "cast":
            base = inner.inputs[0]
            base_dt = _dtype_of(ctx, base)
            if base_dt is not None and src_dt is not None \
                    and _value_preserving(base_dt, src_dt):
                idx = ctx.producer_idx(node.outputs[0])
                cons = ctx.consumers.get(src_name)
                if cons is not None and idx in cons:
                    cons.remove(idx)
                node.inputs = [base if n == src_name else n
                               for n in node.inputs]
                ctx.consumers.setdefault(base, []).append(idx)
                count += 1
                continue
        # constant folding: cast(CONSTANT) -> new constant
        v = sd.vars.get(src_name)
        arr = sd._arrays.get(src_name)
        if v is not None and arr is not None \
                and v.var_type is VariableType.CONSTANT \
                and ctx.consumers.get(node.outputs[0]):
            key = (src_name, target.name)
            new = folded_consts.get(key)
            if new is None:
                import jax.numpy as jnp
                new = sd.constant(f"{src_name}__as_{target.name}",
                                  jnp.asarray(arr).astype(target)).name
                folded_consts[key] = new
            ctx.repoint(node.outputs[0], new)
            count += 1
    return count


# -- pass 2: mask strength reduction ----------------------------------------
def mask_strength_reduce(sd) -> int:
    """Rewrite the exporter's additive attention-mask arithmetic

        scores + broadcast((1 - mask) * neg)     (neg <= -1e4)

    into the native select form ``apply_key_mask(scores, mask)`` —
    the key-mask form ``sdpa_core`` accepts directly, and what unlocks
    the Pallas flash backend (which streams a [b, t_k] key mask but
    cannot stream a dense additive bias).

    Exactness contract: requires (a) the mask provably 0/1-valued —
    its producer chain must bottom out in an integer/bool placeholder
    or cast-from-integer (the TF and HF-ONNX export conventions), or a
    constant whose values are all 0/1; (b) the rewritten add feeds
    ONLY a last-axis softmax. Then unmasked scores pass through
    bitwise (x + 0.0*neg == x) and masked scores underflow to exactly
    0.0 post-softmax in both forms (exp(x + neg - max) == exp(neg -
    max) == 0.0 in f32 for neg <= -1e4 and |scores| within any sane
    range), so the softmax output is identical. Rows with ALL keys
    masked are undefined by the exporter convention (padding masks
    always keep >= 1 token) and may differ.

    Shape-only ops between the mul and the add (the exporter's
    ``[:, None, None, :]`` broadcast) are replayed on the mask itself,
    memoized so N layers sharing one bias chain share one mask
    broadcast."""
    ctx = _Ctx(sd)
    memo: Dict[tuple, str] = {}
    count = 0

    def _binary_provenance(name: str) -> bool:
        # strip value-preserving unary hops to the mask's origin
        seen = 0
        while seen < 8:
            p = ctx.producer(name)
            if p is not None and p.op_name == "cast":
                name = p.inputs[0]
                seen += 1
                continue
            break
        dt = _dtype_of(ctx, name)
        if dt is not None and (dt.kind in ("i", "u", "b")):
            return True
        v = sd.vars.get(name)
        a = sd._arrays.get(name)
        if v is not None and a is not None \
                and v.var_type is VariableType.CONSTANT:
            vals = np.asarray(a)
            return bool(np.all((vals == 0) | (vals == 1)))
        return False

    def _match_bias_chain(name: str):
        """bias operand -> (mask_name, neg_const, shape_chain ops
        add-side-first) or None."""
        chain: List[OpNode] = []
        cur = name
        for _ in range(8):
            p = ctx.producer(cur)
            if p is None:
                return None
            if p.op_name in _SHAPE_ONLY_OPS:
                chain.append(p)
                cur = p.inputs[0]
                continue
            if p.op_name != "mul":
                return None
            # mul((1 - mask), neg) — neg on either side
            a, b = p.inputs
            neg = ctx.scalar_const(b)
            sub_name = a
            if neg is None:
                neg = ctx.scalar_const(a)
                sub_name = b
            if neg is None or neg > -1e4:
                return None
            s = ctx.producer(sub_name)
            if s is None or s.op_name != "sub":
                return None
            one = ctx.scalar_const(s.inputs[0])
            if one is None or one != 1.0:
                return None
            mask = s.inputs[1]
            if not _binary_provenance(mask):
                return None
            # interiors (mul out, sub out) may be shared across
            # layers — we clone, never mutate, so multi-consumer
            # chains are fine here
            return mask, float(neg), chain
        return None

    for node in list(sd.ops):
        if node.op_name != "add":
            continue
        out = node.outputs[0]
        cons = ctx.consumers.get(out, [])
        if len(cons) != 1:
            continue
        nxt = sd.ops[cons[0]]
        if nxt.op_name != "softmax" \
                or nxt.attrs.get("axis", -1) not in (-1, None):
            continue
        for x_name, b_name in (node.inputs, node.inputs[::-1]):
            m = _match_bias_chain(b_name)
            if m is None:
                continue
            mask, neg, chain = m
            key = (mask,) + tuple(
                (c.op_name, repr(sorted(c.attrs.items())))
                for c in chain)
            mvar = memo.get(key)
            if mvar is None:
                mvar = mask
                for c in reversed(chain):    # mul-side first
                    mvar = ctx.append_op(c.op_name, [mvar], c.attrs,
                                         "graphopt_mask")
                memo[key] = mvar
            node.op_name = "apply_key_mask"
            node.inputs = [x_name, mvar]
            node.attrs = {"neg": neg}
            count += 1
            break
    if count:
        # cloned mask-broadcast ops were appended at the end of the
        # walk; their consumers sit earlier — restore topo order
        _resort_ops(sd)
    return count


# -- pass 3: LayerNorm re-fusion --------------------------------------------
def layernorm_refuse(sd) -> int:
    """Re-fuse decomposed LayerNorm chains into the native
    ``layer_norm`` op. Matches BOTH exporter decompositions over the
    last axis:

      TF:   (x - mu) * rsqrt(mean(squared_difference(x, mu)) + eps)
            * gamma + beta
      ONNX: (x - mu) / sqrt(mean((x - mu)^2) + eps) * gamma + beta
            (the HF export: ReduceMean/Sub/Pow/ReduceMean/Add/Sqrt/
            Div/Mul/Add)

    plus the mul(d, d)/square(d) variance spellings. The native op
    computes the identical mean/variance formulation (jnp.mean /
    jnp.var are the same reductions); the only float difference is
    rsqrt-mul vs sqrt-div association in the ONNX form, ~1 ulp.
    Conservative: every interior value must be consumed only inside
    the matched chain; eps must be a scalar constant."""
    ctx = _Ctx(sd)
    count = 0

    def _match_var(veps_name: str, x: str, mu: str, d: str):
        """add(var, eps) -> (eps, [op idxs]) or None."""
        veps = ctx.producer(veps_name)
        if veps is None or veps.op_name != "add":
            return None
        for var_name, eps_name in (veps.inputs, veps.inputs[::-1]):
            eps = ctx.scalar_const(eps_name)
            if eps is None or not (0.0 < eps < 1e-2):
                continue
            red = ctx.producer(var_name)
            if red is None or not _last_axis_reduce(ctx, red):
                continue
            sq = ctx.producer(red.inputs[0])
            if sq is None:
                continue
            ok = False
            if sq.op_name == "squared_difference":
                ok = sq.inputs[0] == x and sq.inputs[1] == mu
            elif sq.op_name == "pow":
                ok = sq.inputs[0] == d \
                    and _close(ctx.scalar_const(sq.inputs[1]), 2.0,
                               1e-9)
            elif sq.op_name == "mul":
                ok = sq.inputs[0] == d and sq.inputs[1] == d
            elif sq.op_name == "square":
                ok = sq.inputs[0] == d
            if not ok:
                continue
            idxs = [ctx.producer_idx(n) for n in
                    (veps_name, var_name, red.inputs[0])]
            return eps, idxs
        return None

    def _match_core(core_name: str):
        """normalized core -> (x, eps, op idxs) or None."""
        core = ctx.producer(core_name)
        if core is None or core.op_name not in ("mul", "div"):
            return None
        orders = [core.inputs] if core.op_name == "div" \
            else [core.inputs, core.inputs[::-1]]
        for d_name, r_name in orders:
            dnode = ctx.producer(d_name)
            if dnode is None or dnode.op_name != "sub":
                continue
            x, mu_name = dnode.inputs
            mu = ctx.producer(mu_name)
            if mu is None or not _last_axis_reduce(ctx, mu) \
                    or mu.inputs[0] != x:
                continue
            rnode = ctx.producer(r_name)
            if rnode is None:
                continue
            if core.op_name == "mul" and rnode.op_name == "rsqrt":
                pass
            elif core.op_name == "div" and rnode.op_name == "sqrt":
                pass
            else:
                continue
            got = _match_var(rnode.inputs[0], x, mu_name, d_name)
            if got is None:
                continue
            eps, var_idxs = got
            idxs = var_idxs + [ctx.producer_idx(n) for n in
                               (core_name, d_name, mu_name, r_name)]
            return x, eps, idxs
        return None

    for node in list(sd.ops):
        if node.op_name != "add":
            continue
        for yg_name, beta in (node.inputs, node.inputs[::-1]):
            yg = ctx.producer(yg_name)
            if yg is None or yg.op_name != "mul":
                continue
            hit = None
            for core_name, gamma in (yg.inputs, yg.inputs[::-1]):
                got = _match_core(core_name)
                if got is not None:
                    hit = (*got, core_name, gamma)
                    break
            if hit is None:
                continue
            x, eps, idxs, core_name, gamma = hit
            idxs = idxs + [ctx.producer_idx(yg_name)]
            term_idx = ctx.producer_idx(node.outputs[0])
            if None in idxs or term_idx is None \
                    or not ctx.interiors_private(idxs, term_idx):
                continue
            node.op_name = "layer_norm"
            node.inputs = [x, gamma, beta]
            node.attrs = {"axis": -1, "epsilon": float(eps)}
            count += 1
            break
    return count


# -- pass 4: GELU re-fusion -------------------------------------------------
def gelu_refuse(sd) -> int:
    """Re-fuse decomposed GELU chains into the native ops.

    erf form  (TF/ONNX exact GELU):
        0.5 * x * (1 + erf(x / sqrt(2)))      -> gelu
    tanh form (the BERT approximation):
        0.5 * x * (1 + tanh(0.79788456 * (x + 0.044715 * x^3)))
                                              -> gelu_tanh

    The multiplication tree is flattened, so any association of
    {0.5, x, (1 + ...)} matches; ``x / sqrt(2)`` and
    ``x * 0.7071067`` both match the erf argument; ``x^3`` matches
    pow(x, 3), x*x*x and square-mul spellings. The native ops are
    jax.nn.gelu(approximate=False/True) — the same formulas, ~1 ulp
    association differences. Conservative at multi-consumer interiors
    (x itself may of course fan out)."""
    ctx = _Ctx(sd)
    count = 0
    SQRT2, INV_SQRT2 = 1.4142135623730951, 0.7071067811865476
    C0, C1 = 0.7978845608028654, 0.044715

    def _factors(term: OpNode):
        """Flatten the terminal mul tree into <= 3 leaves + the
        interior mul op idxs."""
        leaves, idxs = [], []
        stack = [(term, 0)]
        while stack:
            op, depth = stack.pop()
            for inp in op.inputs:
                p = ctx.producer(inp)
                if p is not None and p.op_name == "mul" \
                        and depth < 2 and ctx.single_use(inp) \
                        and len(leaves) + len(stack) < 3:
                    idxs.append(ctx.producer_idx(inp))
                    stack.append((p, depth + 1))
                else:
                    leaves.append(inp)
        return leaves, idxs

    def _match_cube(name: str, x: str):
        p = ctx.producer(name)
        if p is None:
            return None
        if p.op_name == "pow" and p.inputs[0] == x \
                and _close(ctx.scalar_const(p.inputs[1]), 3.0, 1e-9):
            return [ctx.producer_idx(name)]
        if p.op_name == "mul":
            for a, b in (p.inputs, p.inputs[::-1]):
                q = ctx.producer(a)
                if q is None:
                    continue
                if b == x and ((q.op_name == "mul"
                                and q.inputs == [x, x])
                               or (q.op_name == "square"
                                   and q.inputs[0] == x)):
                    return [ctx.producer_idx(name),
                            ctx.producer_idx(a)]
        return None

    def _match_inner(name: str, x: str):
        """erf(x/sqrt2) -> ("gelu", idxs); tanh(...) ->
        ("gelu_tanh", idxs); else None."""
        g = ctx.producer(name)
        if g is None:
            return None
        if g.op_name == "erf":
            u = ctx.producer(g.inputs[0])
            if u is None:
                return None
            ok = False
            if u.op_name == "div" and u.inputs[0] == x:
                ok = _close(ctx.scalar_const(u.inputs[1]), SQRT2, 1e-4)
            elif u.op_name == "mul":
                for a, b in (u.inputs, u.inputs[::-1]):
                    if a == x and _close(ctx.scalar_const(b),
                                         INV_SQRT2, 1e-4):
                        ok = True
            if not ok:
                return None
            return "gelu", [ctx.producer_idx(name),
                            ctx.producer_idx(g.inputs[0])]
        if g.op_name == "tanh":
            arg = ctx.producer(g.inputs[0])
            if arg is None or arg.op_name != "mul":
                return None
            for c_name, inner_name in (arg.inputs, arg.inputs[::-1]):
                if not _close(ctx.scalar_const(c_name), C0, 1e-3):
                    continue
                inner = ctx.producer(inner_name)
                if inner is None or inner.op_name != "add":
                    continue
                for a, b in (inner.inputs, inner.inputs[::-1]):
                    if a != x:
                        continue
                    cub = ctx.producer(b)
                    if cub is None or cub.op_name != "mul":
                        continue
                    for cc, x3 in (cub.inputs, cub.inputs[::-1]):
                        if not _close(ctx.scalar_const(cc), C1, 1e-3):
                            continue
                        ci = _match_cube(x3, x)
                        if ci is None:
                            continue
                        return "gelu_tanh", (
                            [ctx.producer_idx(name),
                             ctx.producer_idx(g.inputs[0]),
                             ctx.producer_idx(inner_name),
                             ctx.producer_idx(b)] + ci)
            return None
        return None

    for node in list(sd.ops):
        if node.op_name != "mul":
            continue
        leaves, mul_idxs = _factors(node)
        if len(leaves) != 3:
            continue
        half = [n for n in leaves
                if _close(ctx.scalar_const(n), 0.5, 1e-6)]
        if len(half) != 1:
            continue
        rest = [n for n in leaves if n is not half[0]]
        hit = None
        for x, add1 in (rest, rest[::-1]):
            a = ctx.producer(add1)
            if a is None or a.op_name != "add":
                continue
            for one, g in (a.inputs, a.inputs[::-1]):
                if not _close(ctx.scalar_const(one), 1.0, 1e-9):
                    continue
                got = _match_inner(g, x)
                if got is not None:
                    hit = (x, got[0],
                           got[1] + [ctx.producer_idx(add1)])
                    break
            if hit:
                break
        if hit is None:
            continue
        x, fused_op, idxs = hit
        idxs = idxs + mul_idxs
        term_idx = ctx.producer_idx(node.outputs[0])
        if None in idxs or term_idx is None \
                or not ctx.interiors_private(idxs, term_idx):
            continue
        node.op_name = fused_op
        node.inputs = [x]
        node.attrs = {}
        count += 1
    return count


# -- pass 5: attention fusion (the r5 pass, extended) -----------------------
def attention_fuse(sd) -> int:
    """Recognize the exporter's op-by-op attention —

        matmul(q, k, transpose_b) -> div/mul(const)
        [-> add(bias) | -> apply_key_mask(mask)] -> softmax
        -> matmul(., v)

    — and rewrite each occurrence to ONE fused ``sdpa_core`` op. XLA
    then schedules (and under remat, recomputes) the whole pattern as
    a unit, the way natively-authored attention lowers. The
    ``apply_key_mask`` form (produced by the mask_strength_reduce
    pass) fuses to ``sdpa_core``'s native key-mask mode — the form
    the Pallas flash backend can stream. Conservative: every interior
    value must have exactly one consumer and the scale must be a
    scalar constant; anything else is left untouched."""
    ctx = _Ctx(sd)
    fused = 0
    for sm in list(sd.ops):
        if sm.op_name != "softmax":
            continue
        ax = sm.attrs.get("axis", -1)
        if ax not in (-1, None):
            continue
        pre = ctx.producer(sm.inputs[0])
        bias = None
        mask = None
        if pre is not None and pre.op_name == "add":
            l, r = pre.inputs
            lp, rp = ctx.producer(l), ctx.producer(r)
            if lp is not None and lp.op_name in ("div", "mul"):
                scal, bias = lp, r
            elif rp is not None and rp.op_name in ("div", "mul"):
                scal, bias = rp, l
            else:
                continue
            if not ctx.single_use(scal.outputs[0]):
                continue
        elif pre is not None and pre.op_name == "apply_key_mask":
            scal = ctx.producer(pre.inputs[0])
            mask = pre.inputs[1]
            if scal is None or scal.op_name not in ("div", "mul") \
                    or not ctx.single_use(scal.outputs[0]):
                continue
        elif pre is not None and pre.op_name in ("div", "mul"):
            scal = pre
        else:
            continue
        # div's operand order is load-bearing; mul commutes, so
        # accept the constant on either side
        score_in, c = scal.inputs[0], ctx.scalar_const(scal.inputs[1])
        if c is None and scal.op_name == "mul":
            score_in, c = scal.inputs[1], \
                ctx.scalar_const(scal.inputs[0])
        if c is None or (scal.op_name == "div" and c == 0.0):
            continue
        scale = (1.0 / c) if scal.op_name == "div" else c
        mm = ctx.producer(score_in)
        if mm is None or mm.op_name != "matmul" \
                or mm.attrs.get("transpose_a") \
                or not ctx.single_use(mm.outputs[0]) \
                or not ctx.single_use(sm.inputs[0]):
            continue
        q_name, k_name = mm.inputs
        if not mm.attrs.get("transpose_b"):
            # the ONNX export spells k^T as an explicit Transpose
            # swapping the two trailing axes — absorb it
            tr = ctx.producer(k_name)
            axes = (tr.attrs.get("axes")
                    if tr is not None
                    and tr.op_name in ("transpose", "permute")
                    else None)
            n = len(axes) if axes else 0
            if not (n >= 2
                    and list(axes[:-2]) == list(range(n - 2))
                    and list(axes[-2:]) == [n - 1, n - 2]
                    and ctx.single_use(k_name)):
                continue
            k_name = tr.inputs[0]
        cons = ctx.consumers.get(sm.outputs[0], [])
        if len(cons) != 1:
            continue
        out_mm = sd.ops[cons[0]]
        if out_mm.op_name != "matmul" \
                or out_mm.inputs[0] != sm.outputs[0] \
                or out_mm.attrs.get("transpose_a") \
                or out_mm.attrs.get("transpose_b"):
            continue
        v_name = out_mm.inputs[1]
        # rewrite IN PLACE: the consumer matmul becomes the fused op;
        # the old chain is dead (the executor walks ancestors of the
        # requested outputs only)
        extra = mask if mask is not None else bias
        out_mm.op_name = "sdpa_core"
        out_mm.inputs = ([q_name, k_name, v_name] +
                         ([extra] if extra is not None else []))
        out_mm.attrs = {"scale": scale}
        if mask is not None:
            out_mm.attrs["mask_mode"] = "key"
        fused += 1
    return fused


# -- the driver -------------------------------------------------------------
PASSES: Tuple[Tuple[str, Callable], ...] = (
    ("cast_fold", cast_fold),
    ("mask_strength_reduce", mask_strength_reduce),
    ("layernorm_refuse", layernorm_refuse),
    ("gelu_refuse", gelu_refuse),
    ("attention_fuse", attention_fuse),
)


class GraphOptimizer:
    """Ordered, fixpoint-iterated pass pipeline over one SameDiff.

    ``run()`` applies the passes in order and repeats the whole
    pipeline until an iteration makes no rewrite (canonicalizations
    feed each other: cast folding exposes mask chains, mask strength
    reduction feeds the attention fusion), capped at
    ``max_iterations``. Returns {pass_name: total rewrites}. Compiled
    program caches are dropped iff anything changed."""

    def __init__(self, sd, passes=None, max_iterations: int = 8):
        self.sd = sd
        self.passes = tuple(passes) if passes is not None else PASSES
        self.max_iterations = int(max_iterations)

    def run(self) -> Dict[str, int]:
        sd = self.sd
        dump = _dump_enabled()
        totals: Dict[str, int] = {name: 0 for name, _ in self.passes}
        if dump:
            dump_walk(sd, "before")
        for it in range(self.max_iterations):
            changed = 0
            for name, fn in self.passes:
                with telemetry.span(f"graphopt.{name}", iteration=it):
                    n = int(fn(sd))
                if n:
                    _REWRITES.inc(n, **{"pass": name})
                    totals[name] += n
                    changed += n
                    if dump:
                        dump_walk(sd, f"after {name} (+{n})")
            if not changed:
                break
        if any(totals.values()):
            sd._exec_cache.clear()
            log.info("graphopt: %s", totals)
        return totals


def optimize(sd, passes=None) -> Dict[str, int]:
    """Convenience front door: run the full pipeline on ``sd``."""
    return GraphOptimizer(sd, passes=passes).run()
