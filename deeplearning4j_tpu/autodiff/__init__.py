"""Autodiff graph layer — the SameDiff equivalent (SURVEY.md §2.3).

Reference parity: ``org.nd4j.autodiff.samediff.SameDiff`` (S1 graph
builder), per-op ``doDiff`` reverse-mode autodiff (S2), Inference/
TrainingSession executors (S3), ``TrainingConfig``/``fit`` (S4),
FlatBuffers save/load (S5).

TPU-first mapping: the reference executes the retained op graph
op-by-op through OpExecutioner, building a second backward graph via
per-op doDiff. Here the graph IS a trace: evaluation walks the DAG once
inside ``jax.jit`` so XLA compiles the whole graph (fusing across op
boundaries the reference cannot), and the gradient function is
``jax.grad`` of that trace — no per-op doDiff, no second graph, no
Enter/Exit/Merge/Switch frames (structured ``lax.while_loop``/``cond``
ops instead). Serialization keeps the reference's contract (graph +
params + updater state + training config in one file) in a zip of
JSON + npz rather than FlatBuffers.
"""
from deeplearning4j_tpu.autodiff.samediff import (SameDiff, SDVariable,
                                                  VariableType)
from deeplearning4j_tpu.autodiff.training import TrainingConfig, History
from deeplearning4j_tpu.autodiff.registry import OP_REGISTRY, op_coverage

__all__ = ["SameDiff", "SDVariable", "VariableType", "TrainingConfig",
           "History", "OP_REGISTRY", "op_coverage"]
