"""Advantage actor-critic (reference: `org.deeplearning4j.rl4j.
learning.async.a3c.discrete.A3CDiscreteDense`). The reference runs
asynchronous JVM worker threads against a shared model; on TPU the
idiomatic equivalent is synchronous A2C — N rollouts collected, ONE
jitted policy+value update (async gradient races buy nothing when the
step itself is a single fused XLA program)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .mdp import MDP
from .qlearning import _mlp_apply, _mlp_init


@dataclass
class A2CConfiguration:
    seed: int = 123
    gamma: float = 0.99
    learning_rate: float = 3e-3
    n_step: int = 32            # rollout length between updates
    entropy_coef: float = 0.01
    value_coef: float = 0.5
    max_step: int = 20_000
    hidden: tuple = (64,)


class A2CDiscreteDense:
    """Shared-trunk actor-critic over dense observations."""

    def __init__(self, mdp: MDP, conf: Optional[A2CConfiguration]
                 = None):
        self.mdp = mdp
        self.conf = conf or A2CConfiguration()
        c = self.conf
        key = jax.random.PRNGKey(c.seed)
        k1, k2, k3 = jax.random.split(key, 3)
        trunk_sizes = (mdp.obs_size,) + tuple(c.hidden)
        self.params = {
            "trunk": _mlp_init(k1, trunk_sizes),
            "pi": _mlp_init(k2, (trunk_sizes[-1], mdp.n_actions)),
            "v": _mlp_init(k3, (trunk_sizes[-1], 1)),
        }
        self._rng = np.random.RandomState(c.seed + 1)
        self.step_count = 0
        self._update = jax.jit(self._make_update())

    def _forward(self, params, obs):
        h = _mlp_apply(params["trunk"], obs)
        h = jax.nn.relu(h)
        return (_mlp_apply(params["pi"], h),
                _mlp_apply(params["v"], h)[..., 0])

    def _make_update(self):
        c = self.conf

        def update(params, obs, act, ret):
            def loss_fn(p):
                logits, v = self._forward(p, obs)
                logp = jax.nn.log_softmax(logits)
                adv = ret - v
                pg = -jnp.mean(jnp.take_along_axis(
                    logp, act[:, None], -1)[:, 0]
                    * jax.lax.stop_gradient(adv))
                vloss = jnp.mean(adv ** 2)
                ent = -jnp.mean(jnp.sum(jnp.exp(logp) * logp, -1))
                return pg + c.value_coef * vloss - c.entropy_coef * ent

            loss, g = jax.value_and_grad(loss_fn)(params)
            new = jax.tree_util.tree_map(
                lambda p_, g_: p_ - c.learning_rate * g_, params, g)
            return new, loss

        return update

    def choose_action(self, obs, greedy: bool = False) -> int:
        logits, _ = self._forward(self.params,
                                  jnp.asarray(obs[None]))
        p = np.asarray(jax.nn.softmax(logits[0]))
        if greedy:
            return int(p.argmax())
        return int(self._rng.choice(len(p), p=p / p.sum()))

    def train(self, n_updates: Optional[int] = None) -> List[float]:
        """Collect n_step rollouts and update until max_step;
        returns per-episode rewards."""
        c = self.conf
        rewards, ep_reward = [], 0.0
        obs = self.mdp.reset()
        buf_o, buf_a, buf_r, buf_d = [], [], [], []
        updates = 0
        while self.step_count < c.max_step:
            buf_o.append(obs)
            a = self.choose_action(obs)
            reply = self.mdp.step(a)
            buf_a.append(a)
            buf_r.append(reply.reward)
            buf_d.append(reply.done)
            ep_reward += reply.reward
            obs = reply.observation
            self.step_count += 1
            if reply.done:
                rewards.append(ep_reward)
                ep_reward = 0.0
                obs = self.mdp.reset()
            if len(buf_o) >= c.n_step:
                # n-step discounted returns, bootstrapped from V
                _, v_last = self._forward(
                    self.params, jnp.asarray(obs[None]))
                ret = float(v_last[0]) if not buf_d[-1] else 0.0
                rets = np.zeros(len(buf_r), np.float32)
                for i in reversed(range(len(buf_r))):
                    ret = buf_r[i] + c.gamma * ret * (1 - buf_d[i])
                    rets[i] = ret
                self.params, _ = self._update(
                    self.params,
                    jnp.asarray(np.stack(buf_o)),
                    jnp.asarray(np.asarray(buf_a, np.int32)),
                    jnp.asarray(rets))
                buf_o, buf_a, buf_r, buf_d = [], [], [], []
                updates += 1
                if n_updates is not None and updates >= n_updates:
                    break
        return rewards
