"""MDP environment contract + built-in test environments.

Reference: `org.deeplearning4j.rl4j.mdp.MDP` (reset/step/isDone +
observation/action spaces) and its toy MDPs; `StepReply` is the
reference's step return carrier. CartPole matches the classic
dynamics (the reference ships gym bindings; zero-egress here, so the
physics live in-repo). GridWorld is a small deterministic MDP for
exact-value tests.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import numpy as np


@dataclass
class StepReply:
    observation: np.ndarray
    reward: float
    done: bool
    info: Any = None


class MDP:
    """reset() -> obs; step(action) -> StepReply; close()."""

    obs_size: int
    n_actions: int

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> StepReply:
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    def close(self):
        pass


class CartPole(MDP):
    """Classic cart-pole balancing (gym CartPole-v1 dynamics)."""

    obs_size = 4
    n_actions = 2

    def __init__(self, seed: int = 0, max_steps: int = 500):
        self._rng = np.random.RandomState(seed)
        self.max_steps = max_steps
        self.gravity = 9.8
        self.masscart, self.masspole = 1.0, 0.1
        self.total_mass = self.masscart + self.masspole
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self._state = None
        self._steps = 0
        self._done = True

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, 4)
        self._steps = 0
        self._done = False
        return self._state.astype(np.float32)

    def step(self, action: int) -> StepReply:
        x, x_dot, th, th_dot = self._state
        force = self.force_mag if action == 1 else -self.force_mag
        cos, sin = np.cos(th), np.sin(th)
        temp = (force + self.polemass_length * th_dot ** 2 * sin) \
            / self.total_mass
        th_acc = (self.gravity * sin - cos * temp) / (
            self.length * (4.0 / 3.0
                           - self.masspole * cos ** 2
                           / self.total_mass))
        x_acc = temp - self.polemass_length * th_acc * cos \
            / self.total_mass
        x += self.tau * x_dot
        x_dot += self.tau * x_acc
        th += self.tau * th_dot
        th_dot += self.tau * th_acc
        self._state = np.array([x, x_dot, th, th_dot])
        self._steps += 1
        self._done = bool(x < -self.x_threshold or x > self.x_threshold
                          or th < -self.theta_threshold
                          or th > self.theta_threshold
                          or self._steps >= self.max_steps)
        return StepReply(self._state.astype(np.float32), 1.0,
                         self._done)

    def is_done(self) -> bool:
        return self._done


class GridWorld(MDP):
    """1-D corridor: start left, +1 reward at the right end,
    deterministic — Q-values have a closed form (gamma^k), used for
    exact DQN convergence tests."""

    def __init__(self, n: int = 6):
        self.n = n
        self.obs_size = n
        self.n_actions = 2   # 0 = left, 1 = right
        self._pos = 0
        self._done = True

    def _obs(self):
        o = np.zeros(self.n, np.float32)
        o[self._pos] = 1.0
        return o

    def reset(self):
        self._pos = 0
        self._done = False
        return self._obs()

    def step(self, action: int) -> StepReply:
        self._pos = max(0, min(self.n - 1,
                               self._pos + (1 if action == 1 else -1)))
        done = self._pos == self.n - 1
        self._done = done
        return StepReply(self._obs(), 1.0 if done else 0.0, done)

    def is_done(self):
        return self._done
