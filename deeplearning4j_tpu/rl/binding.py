"""External environment binding seam (reference:
``org.deeplearning4j.rl4j.mdp.gym.GymEnv`` / the gym-java-client
bridge — SURVEY.md D18).

``GymMDPAdapter`` wraps any object speaking the gym API — duck-typed,
no gym import, zero egress — as an :class:`MDP`, accepting both the
classic 4-tuple ``(obs, reward, done, info)`` and the gymnasium
5-tuple ``(obs, reward, terminated, truncated, info)`` step returns,
and ``reset()`` returning either ``obs`` or ``(obs, info)``."""
from __future__ import annotations

from typing import Any, Optional

import numpy as np

from deeplearning4j_tpu.rl.mdp import MDP, StepReply


class GymMDPAdapter(MDP):
    """Adapt a gym/gymnasium-style env to the MDP contract."""

    def __init__(self, env: Any, obs_size: Optional[int] = None,
                 n_actions: Optional[int] = None):
        self._env = env
        self.obs_size = obs_size if obs_size is not None else \
            int(np.prod(env.observation_space.shape))
        self.n_actions = n_actions if n_actions is not None else \
            int(env.action_space.n)
        self._done = True

    def reset(self) -> np.ndarray:
        out = self._env.reset()
        obs = out[0] if isinstance(out, tuple) else out
        self._done = False
        return np.asarray(obs, np.float32).reshape(-1)

    def step(self, action: int) -> StepReply:
        out = self._env.step(action)
        if len(out) == 5:        # gymnasium: terminated | truncated
            obs, reward, terminated, truncated, info = out
            done = bool(terminated or truncated)
        else:                    # classic gym 4-tuple
            obs, reward, done, info = out
            done = bool(done)
        self._done = done
        return StepReply(np.asarray(obs, np.float32).reshape(-1),
                         float(reward), done, info)

    def is_done(self) -> bool:
        return self._done

    def close(self):
        close = getattr(self._env, "close", None)
        if close is not None:
            close()
