"""Vectorized actor-critic — the SPMD-natural A3C equivalent
(reference: ``org.deeplearning4j.rl4j.learning.async.a3c.discrete.
A3CDiscreteDense`` and its ``AsyncGlobal``/worker-thread machinery).

The reference parallelizes by racing N JVM worker threads against a
shared model.  On TPU the idiomatic equivalent is N PARALLEL
ENVIRONMENTS advanced in lockstep inside the compiled program: the
environment dynamics are a pure jax function, so one update =
``lax.scan`` over T steps of (policy forward → categorical sample →
batched env step) followed by the n-step return recursion (a reverse
scan) and the gradient update — ONE jitted XLA program end to end.
No host↔device transfer happens inside an update; the only host work
is the python loop over updates.

``VectorCartPole`` implements the classic cart-pole dynamics batched
over envs with per-env auto-reset — exact same physics as
``mdp.CartPole``.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.rl.qlearning import _mlp_apply, _mlp_init


class VectorCartPole:
    """Batched cart-pole (gym CartPole dynamics) as pure jax.

    State: dict(s=[n, 4], steps=[n], ep_ret=[n]).  ``step`` applies
    one action per env, auto-resetting finished envs (the returned
    ``done``/``ep_ret`` describe the transition BEFORE the reset)."""

    obs_size = 4
    n_actions = 2

    def __init__(self, n_envs: int, max_steps: int = 200):
        self.n_envs = n_envs
        self.max_steps = max_steps

    def reset(self, key) -> dict:
        s = jax.random.uniform(key, (self.n_envs, 4), minval=-0.05,
                               maxval=0.05)
        return {"s": s, "steps": jnp.zeros(self.n_envs, jnp.int32),
                "ep_ret": jnp.zeros(self.n_envs, jnp.float32)}

    def step(self, state: dict, action, key
             ) -> Tuple[dict, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        s = state["s"]
        x, x_dot, th, th_dot = (s[:, 0], s[:, 1], s[:, 2], s[:, 3])
        force = jnp.where(action == 1, 10.0, -10.0)
        cos, sin = jnp.cos(th), jnp.sin(th)
        polemass_length, total_mass = 0.05, 1.1
        temp = (force + polemass_length * th_dot ** 2 * sin) \
            / total_mass
        th_acc = (9.8 * sin - cos * temp) / (
            0.5 * (4.0 / 3.0 - 0.1 * cos ** 2 / total_mass))
        x_acc = temp - polemass_length * th_acc * cos / total_mass
        tau = 0.02
        ns = jnp.stack([x + tau * x_dot, x_dot + tau * x_acc,
                        th + tau * th_dot, th_dot + tau * th_acc], 1)
        steps = state["steps"] + 1
        theta_thr = 12 * 2 * jnp.pi / 360
        done = ((jnp.abs(ns[:, 0]) > 2.4)
                | (jnp.abs(ns[:, 2]) > theta_thr)
                | (steps >= self.max_steps))
        reward = jnp.ones(self.n_envs, jnp.float32)
        ep_ret = state["ep_ret"] + reward
        # auto-reset finished envs
        fresh = jax.random.uniform(key, ns.shape, minval=-0.05,
                                   maxval=0.05)
        ns = jnp.where(done[:, None], fresh, ns)
        new_state = {"s": ns,
                     "steps": jnp.where(done, 0, steps),
                     "ep_ret": jnp.where(done, 0.0, ep_ret)}
        return new_state, reward, done, ep_ret


@dataclass
class A3CVectorizedConfiguration:
    seed: int = 7
    n_envs: int = 16             # = the reference's N async workers
    n_step: int = 32             # rollout length per update
    gamma: float = 0.99
    learning_rate: float = 3e-3
    entropy_coef: float = 0.01
    value_coef: float = 0.5
    hidden: tuple = (64,)
    max_grad_norm: float = 0.5


class A3CVectorized:
    """N-parallel-env advantage actor-critic, one jitted program per
    update (rollout + returns + gradient step)."""

    def __init__(self, env: VectorCartPole,
                 conf: Optional[A3CVectorizedConfiguration] = None):
        self.env = env
        self.conf = conf or A3CVectorizedConfiguration()
        c = self.conf
        key = jax.random.PRNGKey(c.seed)
        k1, k2, k3, k4, self._key = jax.random.split(key, 5)
        trunk_sizes = (env.obs_size,) + tuple(c.hidden)
        self.params = {
            "trunk": _mlp_init(k1, trunk_sizes),
            "pi": _mlp_init(k2, (trunk_sizes[-1], env.n_actions)),
            "v": _mlp_init(k3, (trunk_sizes[-1], 1)),
        }
        from deeplearning4j_tpu.learning import Adam
        self._updater = Adam(c.learning_rate)
        self._opt_state = {
            "inner": self._updater.init_state(self.params),
            "t": jnp.asarray(0, jnp.int32)}
        self.env_state = env.reset(k4)
        self._update = jax.jit(self._make_update())

    def _forward(self, params, obs):
        h = jax.nn.relu(_mlp_apply(params["trunk"], obs))
        return (_mlp_apply(params["pi"], h),
                _mlp_apply(params["v"], h)[..., 0])

    def _make_update(self):
        c = self.conf
        env = self.env

        def rollout(params, env_state, key):
            def step(carry, key_t):
                est = carry
                ka, ke = jax.random.split(key_t)
                obs = est["s"]
                logits, v = self._forward(params, obs)
                a = jax.random.categorical(ka, logits)
                nst, r, d, ep = env.step(est, a, ke)
                return nst, (obs, a, r, d, ep)

            keys = jax.random.split(key, c.n_step)
            nst, traj = jax.lax.scan(step, env_state, keys)
            return nst, traj

        def update(params, opt_state, env_state, key):
            k_roll, k_next = jax.random.split(key)
            nst, (obs, act, rew, done, ep_ret) = rollout(
                params, env_state, k_roll)

            def loss_fn(p):
                T, N = rew.shape
                logits, v = self._forward(
                    p, obs.reshape(T * N, -1))
                logits = logits.reshape(T, N, -1)
                v = v.reshape(T, N)
                _, v_boot = self._forward(p, nst["s"])
                # n-step returns: reverse scan, cut at dones
                def back(ret, x):
                    r, d, = x
                    ret = r + c.gamma * ret * (1.0 - d)
                    return ret, ret

                _, rets = jax.lax.scan(
                    back, jax.lax.stop_gradient(v_boot),
                    (rew, done.astype(jnp.float32)), reverse=True)
                adv = jax.lax.stop_gradient(rets) - v
                logp = jax.nn.log_softmax(logits)
                lp_a = jnp.take_along_axis(
                    logp, act[..., None], -1)[..., 0]
                pg = -jnp.mean(lp_a * jax.lax.stop_gradient(adv))
                vloss = jnp.mean(adv ** 2)
                ent = -jnp.mean(jnp.sum(jnp.exp(logp) * logp, -1))
                return (pg + c.value_coef * vloss
                        - c.entropy_coef * ent)

            loss, g = jax.value_and_grad(loss_fn)(params)
            gnorm = jnp.sqrt(sum(
                jnp.sum(jnp.square(x))
                for x in jax.tree_util.tree_leaves(g)))
            scale = jnp.minimum(1.0, c.max_grad_norm
                                / jnp.maximum(gnorm, 1e-8))
            g = jax.tree_util.tree_map(lambda x: x * scale, g)
            upd, new_inner = self._updater.apply(g, opt_state["inner"],
                                                 opt_state["t"])
            new_params = jax.tree_util.tree_map(
                lambda p, u: p - u, params, upd)
            new_opt = {"inner": new_inner, "t": opt_state["t"] + 1}
            # episode returns finished during this rollout
            fin = jnp.where(done, ep_ret, jnp.nan)
            return new_params, new_opt, nst, k_next, loss, fin

        return update

    def train(self, n_updates: int) -> List[float]:
        """Run ``n_updates`` jitted updates; returns the rewards of
        every episode finished during training."""
        finished: List[float] = []
        for _ in range(n_updates):
            (self.params, self._opt_state, self.env_state, self._key,
             loss, fin) = self._update(self.params, self._opt_state,
                                       self.env_state, self._key)
            f = np.asarray(fin)
            finished.extend(f[~np.isnan(f)].tolist())
        return finished

    def evaluate(self, n_episodes: int = 10,
                 max_steps: Optional[int] = None) -> float:
        """Greedy policy, single-env episodes; mean episode reward."""
        from deeplearning4j_tpu.rl.mdp import CartPole
        total = 0.0
        for ep in range(n_episodes):
            mdp = CartPole(seed=1000 + ep,
                           max_steps=max_steps or self.env.max_steps)
            obs = mdp.reset()
            while not mdp.is_done():
                logits, _ = self._forward(self.params,
                                          jnp.asarray(obs[None]))
                reply = mdp.step(int(np.asarray(logits[0]).argmax()))
                total += reply.reward
                obs = reply.observation
        return total / n_episodes
