"""Policies (reference: `org.deeplearning4j.rl4j.policy.{Policy,
DQNPolicy,EpsGreedy}`)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class DQNPolicy:
    """Greedy argmax-Q policy over a trained network."""

    def __init__(self, params, q_fn):
        self.params = params
        self._q_fn = q_fn

    def next_action(self, obs) -> int:
        q = self._q_fn(self.params, jnp.asarray(
            np.asarray(obs)[None]))
        return int(jnp.argmax(q[0]))

    def play(self, mdp, max_steps: int = 1000) -> float:
        """Run one greedy episode; returns total reward
        (reference: Policy.play)."""
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            reply = mdp.step(self.next_action(obs))
            total += reply.reward
            obs = reply.observation
            if reply.done:
                break
        return total


class EpsGreedy:
    """Epsilon-greedy wrapper (reference: EpsGreedy policy)."""

    def __init__(self, inner, n_actions: int, epsilon: float = 0.1,
                 seed: int = 0):
        self.inner = inner
        self.n_actions = n_actions
        self.epsilon = epsilon
        self._rng = np.random.RandomState(seed)

    def next_action(self, obs) -> int:
        if self._rng.rand() < self.epsilon:
            return self._rng.randint(self.n_actions)
        return self.inner.next_action(obs)
