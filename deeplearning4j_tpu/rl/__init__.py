"""Reinforcement learning subsystem (SURVEY.md D18 — RL4J parity).

Reference: `rl4j/` — `org.deeplearning4j.rl4j.mdp.MDP` (environment
contract), `learning.sync.qlearning.QLearningDiscreteDense` (DQN with
target network, epsilon-greedy, experience replay),
`learning.async.a3c` (advantage actor-critic), `policy.DQNPolicy`.

TPU-first: the Q/policy networks are jitted pure functions; the DQN
TD-target update and the A2C advantage update are each ONE jitted
step over a replay minibatch (the reference runs per-transition JVM
loops + fit() calls).
"""
from .mdp import MDP, CartPole, GridWorld, StepReply
from .qlearning import QLearningConfiguration, QLearningDiscreteDense
from .policy import DQNPolicy, EpsGreedy
from .a2c import A2CConfiguration, A2CDiscreteDense
from .vectorized import (A3CVectorized, A3CVectorizedConfiguration,
                         VectorCartPole)
from .binding import GymMDPAdapter

__all__ = ["MDP", "StepReply", "CartPole", "GridWorld",
           "QLearningConfiguration", "QLearningDiscreteDense",
           "DQNPolicy", "EpsGreedy", "A2CConfiguration",
           "A2CDiscreteDense", "A3CVectorized",
           "A3CVectorizedConfiguration", "VectorCartPole",
           "GymMDPAdapter"]
