"""DQN (reference: `org.deeplearning4j.rl4j.learning.sync.qlearning.
discrete.QLearningDiscreteDense` + `QLearning.QLConfiguration`):
epsilon-greedy exploration, uniform experience replay, target network
synced every ``target_dqn_update_freq`` steps, double-DQN option.

TPU-first: the Q-network is a pure MLP over params pytrees; the TD
update is one jitted step (gather/argmax/Huber) over a replay batch.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .mdp import MDP


@dataclass
class QLearningConfiguration:
    """reference: QLearning.QLConfiguration (field-for-field)."""
    seed: int = 123
    max_epoch_step: int = 200
    max_step: int = 10_000
    exp_replay_size: int = 10_000
    batch_size: int = 64
    target_dqn_update_freq: int = 100
    update_start: int = 100
    reward_factor: float = 1.0
    gamma: float = 0.99
    error_clamp: float = 1.0
    min_epsilon: float = 0.05
    epsilon_nb_step: int = 3000
    double_dqn: bool = True
    learning_rate: float = 1e-3
    hidden: tuple = (64, 64)


def _mlp_init(key, sizes):
    params = []
    for i in range(len(sizes) - 1):
        key, k = jax.random.split(key)
        w = jax.random.normal(k, (sizes[i], sizes[i + 1])) \
            * np.sqrt(2.0 / sizes[i])
        params.append({"w": w, "b": jnp.zeros(sizes[i + 1])})
    return params


def _mlp_apply(params, x):
    for i, lyr in enumerate(params):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


class ReplayMemory:
    """Uniform ring-buffer replay (reference: ExpReplay)."""

    def __init__(self, capacity: int, obs_size: int, seed: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_size), np.float32)
        self.next_obs = np.zeros((capacity, obs_size), np.float32)
        self.action = np.zeros(capacity, np.int32)
        self.reward = np.zeros(capacity, np.float32)
        self.done = np.zeros(capacity, np.float32)
        self.size = 0
        self._pos = 0
        self._rng = np.random.RandomState(seed)

    def store(self, o, a, r, no, d):
        i = self._pos
        self.obs[i], self.action[i], self.reward[i] = o, a, r
        self.next_obs[i], self.done[i] = no, float(d)
        self._pos = (self._pos + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)

    def sample(self, n):
        idx = self._rng.randint(0, self.size, n)
        return (self.obs[idx], self.action[idx], self.reward[idx],
                self.next_obs[idx], self.done[idx])


class QLearningDiscreteDense:
    """DQN over a dense-observation MDP (reference class name)."""

    def __init__(self, mdp: MDP,
                 conf: Optional[QLearningConfiguration] = None):
        self.mdp = mdp
        self.conf = conf or QLearningConfiguration()
        c = self.conf
        key = jax.random.PRNGKey(c.seed)
        sizes = (mdp.obs_size,) + tuple(c.hidden) + (mdp.n_actions,)
        self.params = _mlp_init(key, sizes)
        self.target_params = jax.tree_util.tree_map(
            lambda a: a, self.params)
        self.memory = ReplayMemory(c.exp_replay_size, mdp.obs_size,
                                   c.seed + 1)
        self._rng = np.random.RandomState(c.seed + 2)
        self.step_count = 0
        self._train_step = jax.jit(self._make_step())
        self._q_fn = jax.jit(_mlp_apply)

    def _make_step(self):
        c = self.conf

        def step(params, target_params, obs, act, rew, nobs, done):
            if c.double_dqn:
                # online net picks, target net evaluates
                next_a = jnp.argmax(_mlp_apply(params, nobs), -1)
                next_q = jnp.take_along_axis(
                    _mlp_apply(target_params, nobs),
                    next_a[:, None], -1)[:, 0]
            else:
                next_q = jnp.max(_mlp_apply(target_params, nobs), -1)
            target = rew * c.reward_factor \
                + c.gamma * next_q * (1.0 - done)

            def loss_fn(p):
                q = jnp.take_along_axis(_mlp_apply(p, obs),
                                        act[:, None], -1)[:, 0]
                err = q - jax.lax.stop_gradient(target)
                # Huber (the reference's error clamp)
                d = c.error_clamp
                ae = jnp.abs(err)
                return jnp.mean(jnp.where(
                    ae <= d, 0.5 * err ** 2, d * (ae - 0.5 * d)))

            loss, g = jax.value_and_grad(loss_fn)(params)
            new = jax.tree_util.tree_map(
                lambda p, gg: p - c.learning_rate * gg, params, g)
            return new, loss

        return step

    # -- policy -------------------------------------------------------
    def epsilon(self) -> float:
        c = self.conf
        f = min(1.0, self.step_count / max(1, c.epsilon_nb_step))
        return 1.0 + f * (c.min_epsilon - 1.0)

    def choose_action(self, obs, greedy: bool = False) -> int:
        if not greedy and self._rng.rand() < self.epsilon():
            return self._rng.randint(self.mdp.n_actions)
        q = self._q_fn(self.params, jnp.asarray(obs[None]))
        return int(jnp.argmax(q[0]))

    # -- training -----------------------------------------------------
    def train_epoch(self) -> float:
        """One episode; returns its total reward."""
        c = self.conf
        obs = self.mdp.reset()
        total = 0.0
        for _ in range(c.max_epoch_step):
            a = self.choose_action(obs)
            reply = self.mdp.step(a)
            self.memory.store(obs, a, reply.reward,
                              reply.observation, reply.done)
            total += reply.reward
            obs = reply.observation
            self.step_count += 1
            if (self.memory.size >= c.update_start):
                batch = self.memory.sample(c.batch_size)
                self.params, _ = self._train_step(
                    self.params, self.target_params,
                    *(jnp.asarray(x) for x in batch))
            if self.step_count % c.target_dqn_update_freq == 0:
                self.target_params = jax.tree_util.tree_map(
                    lambda a_: a_, self.params)
            if reply.done:
                break
        return total

    def train(self, n_epochs: Optional[int] = None) -> List[float]:
        rewards = []
        while self.step_count < self.conf.max_step:
            rewards.append(self.train_epoch())
            if n_epochs is not None and len(rewards) >= n_epochs:
                break
        return rewards

    def get_policy(self):
        from .policy import DQNPolicy
        return DQNPolicy(self.params, self._q_fn)
