"""Word2Vec / SequenceVectors / ParagraphVectors.

Reference parity: `org.deeplearning4j.models.word2vec.Word2Vec`,
`models.sequencevectors.SequenceVectors`,
`models.paragraphvectors.ParagraphVectors` (SURVEY.md D16) with the
reference's builder API (minWordFrequency / layerSize / windowSize /
negativeSample / iterations → snake_case).

TPU-first: the reference trains word-by-word on JVM threads
(HS/negative-sampling inner loops). Here training is ONE jitted SGNS
step over a [batch] of skip-gram pairs — gathers, a [b,d]×[b,k,d]
einsum, log-sigmoid losses, and scatter-add parameter updates, all
fused by XLA. Negative samples are drawn host-side from the
unigram^0.75 table (vocab.py) and shipped with the batch.
"""
from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .tokenization import DefaultTokenizerFactory
from .vocab import VocabCache, build_vocab


def build_huffman(counts: np.ndarray):
    """Huffman tree over word frequencies (reference: the
    `HuffmanTree`/`PointIndex` construction behind
    ``useHierarchicSoftmax``).  Returns per-word padded path arrays
    ``(nodes [V, L], codes [V, L], mask [V, L])`` where ``nodes`` are
    internal-node ids (0..V-2), ``codes`` the binary branch taken and
    ``mask`` marks real path entries."""
    import heapq
    v = len(counts)
    if v == 1:
        return (np.zeros((1, 1), np.int32), np.zeros((1, 1),
                np.float32), np.ones((1, 1), np.float32))
    heap = [(int(c), i) for i, c in enumerate(counts)]
    heapq.heapify(heap)
    parent = {}
    binary = {}
    nxt = v                       # internal nodes: v .. 2v-2
    while len(heap) > 1:
        ca, a = heapq.heappop(heap)
        cb, b = heapq.heappop(heap)
        parent[a], parent[b] = nxt, nxt
        binary[a], binary[b] = 0, 1
        heapq.heappush(heap, (ca + cb, nxt))
        nxt += 1
    paths = []
    for w in range(v):
        nodes, codes = [], []
        n = w
        while n in parent:
            nodes.append(parent[n] - v)   # internal id 0..v-2
            codes.append(binary[n])
            n = parent[n]
        paths.append((nodes, codes))
    L = max(len(n) for n, _ in paths)
    nodes_a = np.zeros((v, L), np.int32)
    codes_a = np.zeros((v, L), np.float32)
    mask_a = np.zeros((v, L), np.float32)
    for w, (nodes, codes) in enumerate(paths):
        k = len(nodes)
        nodes_a[w, :k] = nodes
        codes_a[w, :k] = codes
        mask_a[w, :k] = 1.0
    return nodes_a, codes_a, mask_a


def _hs_step(win, wout, centers, nodes, codes, mask, lr):
    """One skip-gram HIERARCHICAL-SOFTMAX SGD step (jitted): the
    output distribution is the product of sigmoid branch decisions
    along the context word's Huffman path — O(log V) dot products per
    pair instead of k negatives."""
    def loss_fn(win, wout):
        v = win[centers]                        # [b, d]
        u = wout[nodes]                         # [b, L, d]
        s = jnp.einsum("bd,bld->bl", v, u)
        sign = 1.0 - 2.0 * codes                # code 0 → +1, 1 → -1
        return -jnp.sum(jax.nn.log_sigmoid(sign * s) * mask)

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(win, wout)
    return win - lr * grads[0], wout - lr * grads[1], loss


def _sgns_step(win, wout, centers, contexts, negatives, lr):
    """One skip-gram negative-sampling SGD step (jitted)."""
    def loss_fn(win, wout):
        v = win[centers]                       # [b, d]
        u = wout[contexts]                     # [b, d]
        pos = jax.nn.log_sigmoid(jnp.sum(v * u, -1))
        s = jnp.einsum("bd,bkd->bk", v, wout[negatives])
        neg = jnp.sum(jax.nn.log_sigmoid(-s), -1)
        # SUM, not mean: per-pair gradient magnitude then matches the
        # classic per-pair SGD update at word2vec's canonical lr
        return -jnp.sum(pos + neg)

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(win, wout)
    return win - lr * grads[0], wout - lr * grads[1], loss


class SequenceVectors:
    """Shared SGNS trainer over (center, context) index pairs.

    Subclasses define how pairs are generated from sequences; this
    class owns vocab, embedding matrices, training, and the lookup /
    similarity API (reference: SequenceVectors is exactly this seam).

    ``learning_rate`` applies to the batched SUM loss, i.e. per-pair
    update scale. A word hit by many pairs in one batch accumulates
    all of them simultaneously, so small vocabularies want a smaller
    lr than the classic 0.025 (rule of thumb: 0.025 * vocab/batch
    capped at 0.025; divergence shows as NaN similarities).
    """

    def __init__(self, layer_size=64, window_size=5, negative=5,
                 learning_rate=0.01, min_learning_rate=1e-4,
                 epochs=1, batch_size=512, min_word_frequency=1,
                 seed=12345, tokenizer_factory=None,
                 use_hierarchic_softmax=False):
        self.layer_size = layer_size
        self.window_size = window_size
        self.negative = negative
        #: reference `useHierarchicSoftmax`: O(log V) Huffman-path
        #: sigmoid decisions replace the k negative samples
        self.use_hierarchic_softmax = use_hierarchic_softmax
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.min_word_frequency = min_word_frequency
        self.seed = seed
        self.tokenizer_factory = (tokenizer_factory or
                                  DefaultTokenizerFactory())
        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[np.ndarray] = None   # input/lookup table
        self.syn1: Optional[np.ndarray] = None   # output table
        self._step = jax.jit(_sgns_step)
        self._hs = jax.jit(_hs_step)
        self._huffman = None

    # -- data --------------------------------------------------------
    def _tokenize_corpus(self, sentences: Iterable) -> List[List[str]]:
        seqs = []
        for s in sentences:
            if isinstance(s, str):
                seqs.append(self.tokenizer_factory.create(s)
                            .get_tokens())
            else:
                seqs.append(list(s))
        return seqs

    def _skipgram_pairs(self, ids: List[int], rng) -> List:
        pairs = []
        for i, c in enumerate(ids):
            w = 1 + rng.randint(self.window_size)  # shrunk window
            for j in range(max(0, i - w), min(len(ids), i + w + 1)):
                if j != i:
                    pairs.append((c, ids[j]))
        return pairs

    # -- training ----------------------------------------------------
    def _init_tables(self, n_in: int, n_out: int):
        rng = np.random.RandomState(self.seed)
        self.syn0 = ((rng.rand(n_in, self.layer_size) - 0.5)
                     / self.layer_size).astype(np.float32)
        self.syn1 = np.zeros((n_out, self.layer_size), np.float32)

    def _train_pairs(self, all_pairs: np.ndarray, n_out: int):
        rng = np.random.RandomState(self.seed + 1)
        hs = self.use_hierarchic_softmax
        if hs:
            counts = np.array([self.vocab.counts[w]
                               for w in self.vocab.words], np.int64)
            h_nodes, h_codes, h_mask = build_huffman(counts)
            self._huffman = (h_nodes, h_codes, h_mask)
        else:
            probs = self.vocab.neg_sampling_probs().astype(np.float64)
            probs = probs / probs.sum()
        win = jnp.asarray(self.syn0)
        wout = jnp.asarray(self.syn1)
        n = len(all_pairs)
        steps_total = max(1, self.epochs * ((n + self.batch_size - 1)
                                            // self.batch_size))
        step_i = 0
        for ep in range(self.epochs):
            order = rng.permutation(n)
            for s in range(0, n, self.batch_size):
                sel = order[s:s + self.batch_size]
                if len(sel) < self.batch_size:   # pad to fixed shape
                    sel = np.concatenate(
                        [sel, rng.choice(n, self.batch_size - len(sel))])
                batch = all_pairs[sel]
                lr = max(self.min_learning_rate,
                         self.learning_rate
                         * (1 - step_i / steps_total))
                if hs:
                    ctx = batch[:, 1]
                    win, wout, _ = self._hs(
                        win, wout, jnp.asarray(batch[:, 0]),
                        jnp.asarray(h_nodes[ctx]),
                        jnp.asarray(h_codes[ctx]),
                        jnp.asarray(h_mask[ctx]), lr)
                else:
                    negs = rng.choice(len(probs),
                                      (self.batch_size, self.negative),
                                      p=probs)
                    win, wout, _ = self._step(
                        win, wout, jnp.asarray(batch[:, 0]),
                        jnp.asarray(batch[:, 1]),
                        jnp.asarray(negs), lr)
                step_i += 1
        self.syn0 = np.asarray(win)
        self.syn1 = np.asarray(wout)

    # -- lookup API (reference: WordVectors interface) ----------------
    def get_word_vector(self, word: str) -> np.ndarray:
        return self.syn0[self.vocab.id_of(word)]

    def get_word_vector_matrix(self) -> np.ndarray:
        return self.syn0

    def has_word(self, w: str) -> bool:
        return self.vocab is not None and w in self.vocab

    def similarity(self, a: str, b: str) -> float:
        from .vocab import cosine_similarity
        return cosine_similarity(self.get_word_vector(a),
                                 self.get_word_vector(b))

    def words_nearest(self, word: str, n: int = 10) -> List[str]:
        from .vocab import nearest_words
        return nearest_words(self.syn0, self.vocab.words,
                             self.get_word_vector(word), n,
                             exclude=word)


class Word2Vec(SequenceVectors):
    """Skip-gram negative-sampling word embeddings (reference:
    Word2Vec.Builder().minWordFrequency().layerSize().windowSize()...)."""

    class Builder:
        def __init__(self):
            self._kw = {}
            self._sentences = None

        def min_word_frequency(self, v):
            self._kw["min_word_frequency"] = v
            return self

        def layer_size(self, v):
            self._kw["layer_size"] = v
            return self

        def window_size(self, v):
            self._kw["window_size"] = v
            return self

        def negative_sample(self, v):
            self._kw["negative"] = int(v)
            return self

        def use_hierarchic_softmax(self, v=True):
            self._kw["use_hierarchic_softmax"] = bool(v)
            return self

        def learning_rate(self, v):
            self._kw["learning_rate"] = v
            return self

        def min_learning_rate(self, v):
            self._kw["min_learning_rate"] = v
            return self

        def epochs(self, v):
            self._kw["epochs"] = v
            return self

        def batch_size(self, v):
            self._kw["batch_size"] = v
            return self

        def seed(self, v):
            self._kw["seed"] = int(v)
            return self

        def tokenizer_factory(self, tf):
            self._kw["tokenizer_factory"] = tf
            return self

        def iterate(self, sentences):
            self._sentences = sentences
            return self

        def build(self) -> "Word2Vec":
            w = Word2Vec(**self._kw)
            w._pending = self._sentences
            return w

    def __init__(self, **kw):
        super().__init__(**kw)
        self._pending = None

    def fit(self, sentences=None):
        sentences = sentences if sentences is not None else self._pending
        seqs = self._tokenize_corpus(sentences)
        self.vocab = build_vocab(seqs, self.min_word_frequency)
        v = len(self.vocab)
        self._init_tables(
            v, max(v - 1, 1) if self.use_hierarchic_softmax else v)
        pairs = []
        rng = np.random.RandomState(self.seed + 2)
        for seq in seqs:
            ids = [self.vocab.id_of(t) for t in seq
                   if t in self.vocab]
            pairs.extend(self._skipgram_pairs(ids, rng))
        if not pairs:
            raise ValueError("no training pairs (corpus too small "
                             "for min_word_frequency?)")
        self._train_pairs(np.asarray(pairs, np.int32), v)
        return self


class ParagraphVectors(SequenceVectors):
    """PV-DBOW document embeddings (reference: ParagraphVectors with
    DBOW sequence learning): a document vector is trained to predict
    the words it contains via the same SGNS objective."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.doc_vectors: Optional[np.ndarray] = None
        self.labels: List[str] = []

    def fit(self, documents: Sequence, labels: Optional[List[str]]
            = None):
        seqs = self._tokenize_corpus(documents)
        self.labels = labels or [f"DOC_{i}" for i in
                                 range(len(seqs))]
        self.vocab = build_vocab(seqs, self.min_word_frequency)
        v = len(self.vocab)
        self._init_tables(
            len(seqs),
            max(v - 1, 1) if self.use_hierarchic_softmax else v)
        pairs = []
        for d, seq in enumerate(seqs):
            for t in seq:
                if t in self.vocab:
                    pairs.append((d, self.vocab.id_of(t)))
        self._train_pairs(np.asarray(pairs, np.int32), v)
        self.doc_vectors = self.syn0
        return self

    def get_doc_vector(self, label_or_idx) -> np.ndarray:
        i = (self.labels.index(label_or_idx)
             if isinstance(label_or_idx, str) else label_or_idx)
        return self.doc_vectors[i]

    def infer_vector(self, text, steps: int = 50,
                     learning_rate: float = 0.05) -> np.ndarray:
        """Train a fresh doc vector against the FROZEN word table
        (reference: ParagraphVectors.inferVector)."""
        toks = (self.tokenizer_factory.create(text).get_tokens()
                if isinstance(text, str) else list(text))
        ids = np.asarray([self.vocab.id_of(t) for t in toks
                          if t in self.vocab], np.int32)
        if ids.size == 0:
            return np.zeros(self.layer_size, np.float32)
        rng = np.random.RandomState(self.seed + 3)
        dv = ((rng.rand(self.layer_size) - 0.5)
              / self.layer_size).astype(np.float32)
        wout = jnp.asarray(self.syn1)

        if self.use_hierarchic_softmax:
            # inference against the FROZEN Huffman internal-node
            # table: the same path objective training used
            h_nodes, h_codes, h_mask = self._huffman
            nodes = jnp.asarray(h_nodes[ids])
            codes = jnp.asarray(h_codes[ids])
            mask = jnp.asarray(h_mask[ids])

            @jax.jit
            def hs_step(dv, lr):
                def loss_fn(dv):
                    s = jnp.einsum("d,bld->bl", dv, wout[nodes])
                    sign = 1.0 - 2.0 * codes
                    # mean over words, SUM over the path — the same
                    # per-word gradient scale as the SGNS branch
                    return -jnp.mean(jnp.sum(
                        jax.nn.log_sigmoid(sign * s) * mask, -1))
                return dv - lr * jax.grad(loss_fn)(dv)

            dv = jnp.asarray(dv)
            for i in range(steps):
                lr = learning_rate * (1 - i / steps) + 1e-4
                dv = hs_step(dv, lr)
            return np.asarray(dv)

        probs = self.vocab.neg_sampling_probs().astype(np.float64)
        probs = probs / probs.sum()

        @jax.jit
        def step(dv, contexts, negatives, lr):
            def loss_fn(dv):
                u = wout[contexts]
                pos = jax.nn.log_sigmoid(u @ dv)
                s = jnp.einsum("d,bkd->bk", dv, wout[negatives])
                neg = jnp.sum(jax.nn.log_sigmoid(-s), -1)
                return -jnp.mean(pos + neg)
            g = jax.grad(loss_fn)(dv)
            return dv - lr * g

        dv = jnp.asarray(dv)
        for i in range(steps):
            negs = rng.choice(len(probs), (ids.size, self.negative),
                              p=probs)
            lr = learning_rate * (1 - i / steps) + 1e-4
            dv = step(dv, jnp.asarray(ids), jnp.asarray(negs), lr)
        return np.asarray(dv)

    def similarity_to_label(self, text, label) -> float:
        from .vocab import cosine_similarity
        return cosine_similarity(self.infer_vector(text),
                                 self.get_doc_vector(label))
