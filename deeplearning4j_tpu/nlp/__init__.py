"""NLP subsystem (SURVEY.md D16 parity).

Reference: `deeplearning4j-nlp` — `org.deeplearning4j.text.tokenization`
(tokenizer factories + preprocessors), `org.deeplearning4j.models`
(Word2Vec / ParagraphVectors / SequenceVectors over a VocabCache), and
`org.deeplearning4j.iterator.BertIterator` (wordpiece + MLM masking).

TPU-first design: embedding training is a single jitted SGNS step —
batched skip-gram pairs with negative sampling as one gather/einsum/
scatter-add program (the reference trains per-word with HS/NS inner
loops on the JVM; here the MXU sees [batch, dim] matmuls).
"""
from .tokenization import (BertWordPieceTokenizer, DefaultTokenizer,
                           DefaultTokenizerFactory,
                           CommonPreprocessor)
from .vocab import VocabCache, build_vocab
from .word2vec import ParagraphVectors, SequenceVectors, Word2Vec
from .glove import Glove
from .bert_iterator import BertIterator
from .serializer import (StaticWordVectors, read_word2vec_model,
                         read_word_vectors, write_word2vec_model,
                         write_word_vectors)

__all__ = ["DefaultTokenizer", "DefaultTokenizerFactory",
           "CommonPreprocessor", "BertWordPieceTokenizer",
           "VocabCache", "build_vocab", "Word2Vec", "SequenceVectors",
           "Glove",
           "ParagraphVectors", "BertIterator",
           "write_word_vectors", "read_word_vectors",
           "write_word2vec_model", "read_word2vec_model",
           "StaticWordVectors"]
