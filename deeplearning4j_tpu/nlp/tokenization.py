"""Tokenizers + preprocessors.

Reference parity: `org.deeplearning4j.text.tokenization.tokenizer.
DefaultTokenizer` / `DefaultTokenizerFactory` /
`CommonPreprocessor`, and `BertWordPieceTokenizer`
(`deeplearning4j-nlp`'s wordpiece implementation used by
`BertIterator`). Pure host-side code — no device work.
"""
from __future__ import annotations

import re
import unicodedata
from typing import Iterable, List, Optional


class CommonPreprocessor:
    """Lowercase + strip punctuation (reference: CommonPreprocessor)."""

    _PUNCT = re.compile(r"[\W_]+", re.UNICODE)

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token.lower())


class DefaultTokenizer:
    """Whitespace tokenizer with optional per-token preprocessor
    (reference: DefaultTokenizer over java.util.StringTokenizer)."""

    def __init__(self, text: str, pre_processor=None):
        self._tokens = [t for t in text.split()]
        if pre_processor is not None:
            self._tokens = [pre_processor.pre_process(t)
                            for t in self._tokens]
        self._tokens = [t for t in self._tokens if t]
        self._pos = 0

    def has_more_tokens(self) -> bool:
        return self._pos < len(self._tokens)

    def next_token(self) -> str:
        t = self._tokens[self._pos]
        self._pos += 1
        return t

    def count_tokens(self) -> int:
        return len(self._tokens)

    def get_tokens(self) -> List[str]:
        return list(self._tokens)


class DefaultTokenizerFactory:
    """reference: DefaultTokenizerFactory (+ setTokenPreProcessor)."""

    def __init__(self):
        self._pre = None

    def set_token_pre_processor(self, pre):
        self._pre = pre

    def create(self, text: str) -> DefaultTokenizer:
        return DefaultTokenizer(text, self._pre)


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


class BertWordPieceTokenizer:
    """Greedy longest-match-first wordpiece (reference:
    BertWordPieceTokenizer; algorithm identical to the original BERT
    tokenizer: basic split -> wordpiece with '##' continuations).
    """

    def __init__(self, vocab, lower_case: bool = True,
                 unk_token: str = "[UNK]",
                 max_chars_per_word: int = 100):
        if not isinstance(vocab, dict):
            vocab = {w: i for i, w in enumerate(vocab)}
        self.vocab = vocab
        self.inv_vocab = {i: w for w, i in vocab.items()}
        self.lower_case = lower_case
        self.unk_token = unk_token
        self.max_chars = max_chars_per_word

    # -- basic tokenization (whitespace + punctuation split) -----------
    def _basic(self, text: str) -> List[str]:
        if self.lower_case:
            text = text.lower()
            text = "".join(c for c in unicodedata.normalize("NFD", text)
                           if unicodedata.category(c) != "Mn")
        out, cur = [], []
        for ch in text:
            if ch.isspace():
                if cur:
                    out.append("".join(cur))
                    cur = []
            elif _is_punct(ch):
                if cur:
                    out.append("".join(cur))
                    cur = []
                out.append(ch)
            else:
                cur.append(ch)
        if cur:
            out.append("".join(cur))
        return out

    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_chars:
            return [self.unk_token]
        pieces, start = [], 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            pieces.append(piece)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        out = []
        for w in self._basic(text):
            out.extend(self._wordpiece(w))
        return out

    def encode(self, text: str) -> List[int]:
        unk = self.vocab.get(self.unk_token, 0)
        return [self.vocab.get(t, unk) for t in self.tokenize(text)]

    def id_of(self, token: str) -> int:
        return self.vocab.get(token,
                              self.vocab.get(self.unk_token, 0))

    @staticmethod
    def build_vocab(corpus: Iterable[str], size: int = 1000,
                    lower_case: bool = True,
                    specials: Optional[List[str]] = None):
        """Frequency-based wordpiece vocab builder for tests/fixtures
        (whole words + character pieces; real deployments load a
        pretrained vocab file via ``from_vocab_file``)."""
        from collections import Counter
        specials = specials or ["[PAD]", "[UNK]", "[CLS]", "[SEP]",
                                "[MASK]"]
        tk = BertWordPieceTokenizer({}, lower_case=lower_case)
        words = Counter()
        chars = Counter()
        for line in corpus:
            for w in tk._basic(line):
                words[w] += 1
                chars.update(w)
                chars.update("##" + c for c in w[1:])
        vocab = list(specials)
        vocab += [c for c, _ in chars.most_common()]
        for w, _ in words.most_common():
            if len(vocab) >= size:
                break
            if w not in vocab:
                vocab.append(w)
        return {w: i for i, w in enumerate(vocab[:max(size,
                                                      len(specials))])}

    @staticmethod
    def from_vocab_file(path: str, lower_case: bool = True):
        with open(path, encoding="utf-8") as f:
            vocab = {line.rstrip("\n"): i for i, line in enumerate(f)}
        return BertWordPieceTokenizer(vocab, lower_case=lower_case)
