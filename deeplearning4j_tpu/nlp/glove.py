"""GloVe embeddings (reference: ``org.deeplearning4j.models.glove.
Glove`` — co-occurrence-matrix factorization with AdaGrad, SURVEY.md
D16).

TPU-first: the reference trains per-pair on JVM threads with an
AdaGrad inner loop; here one jitted step processes a [batch] of
non-zero co-occurrence entries — gathers, the weighted-least-squares
loss f(x)(w_i·w̃_j + b_i + b̃_j − log x)², and scatter-add AdaGrad
updates — fused by XLA. Co-occurrence accumulation (sparse,
data-dependent) stays host-side, like the reference's
AbstractCoOccurrences pass.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .vocab import build_vocab
from .word2vec import SequenceVectors


def _glove_step(state, rows, cols, logx, fx, lr):
    """One AdaGrad step over a batch of co-occurrence entries."""
    w, wc, b, bc, gw, gwc, gb, gbc = state

    def loss_fn(w, wc, b, bc):
        diff = (jnp.sum(w[rows] * wc[cols], -1) + b[rows] + bc[cols]
                - logx)
        return jnp.sum(fx * diff * diff)

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2, 3))(
        w, wc, b, bc)
    out = []
    for p, g, acc in ((w, grads[0], gw), (wc, grads[1], gwc),
                      (b, grads[2], gb), (bc, grads[3], gbc)):
        acc = acc + g * g
        p = p - lr * g / jnp.sqrt(acc + 1e-8)
        out.extend([p, acc])
    new_state = (out[0], out[2], out[4], out[6],
                 out[1], out[3], out[5], out[7])
    return new_state, loss


class Glove(SequenceVectors):
    """GloVe trainer with the reference's builder surface
    (``xMax``/``alpha``/``learningRate``/``epochs``/...); shares the
    WordVectors lookup/similarity API via SequenceVectors."""

    def __init__(self, layer_size=64, window_size=5, x_max=100.0,
                 alpha=0.75, learning_rate=0.05, epochs=5,
                 batch_size=2048, min_word_frequency=1, seed=12345,
                 symmetric=True, tokenizer_factory=None):
        super().__init__(layer_size=layer_size, window_size=window_size,
                         learning_rate=learning_rate, epochs=epochs,
                         batch_size=batch_size,
                         min_word_frequency=min_word_frequency,
                         seed=seed,
                         tokenizer_factory=tokenizer_factory)
        self.x_max = float(x_max)
        self.alpha = float(alpha)
        self.symmetric = bool(symmetric)
        self._glove_jit = jax.jit(_glove_step)

    # -- builder (reference API shape) -----------------------------------
    class Builder:
        def __init__(self):
            self._kw = {}
            self._sentences = None

        def iterate(self, sentences):
            self._sentences = sentences
            return self

        def layer_size(self, v):
            self._kw["layer_size"] = int(v)
            return self

        def window_size(self, v):
            self._kw["window_size"] = int(v)
            return self

        def x_max(self, v):
            self._kw["x_max"] = float(v)
            return self

        def alpha(self, v):
            self._kw["alpha"] = float(v)
            return self

        def learning_rate(self, v):
            self._kw["learning_rate"] = float(v)
            return self

        def epochs(self, v):
            self._kw["epochs"] = int(v)
            return self

        def batch_size(self, v):
            self._kw["batch_size"] = int(v)
            return self

        def min_word_frequency(self, v):
            self._kw["min_word_frequency"] = int(v)
            return self

        def symmetric(self, v):
            self._kw["symmetric"] = bool(v)
            return self

        def seed(self, v):
            self._kw["seed"] = int(v)
            return self

        def tokenizer_factory(self, v):
            self._kw["tokenizer_factory"] = v
            return self

        def build(self) -> "Glove":
            g = Glove(**self._kw)
            g._sentences = self._sentences
            return g

    # -- co-occurrence accumulation (reference: AbstractCoOccurrences) ---
    def _cooccurrences(self, seqs: List[List[str]]) -> Tuple[np.ndarray,
                                                             np.ndarray,
                                                             np.ndarray]:
        counts: Dict[Tuple[int, int], float] = {}
        for toks in seqs:
            ids = [self.vocab.id_of(t) for t in toks
                   if t in self.vocab]
            for i, ci in enumerate(ids):
                for j in range(i + 1, min(len(ids),
                                          i + 1 + self.window_size)):
                    w = 1.0 / (j - i)          # distance weighting
                    a, b = ci, ids[j]
                    counts[(a, b)] = counts.get((a, b), 0.0) + w
                    if self.symmetric:
                        counts[(b, a)] = counts.get((b, a), 0.0) + w
        rows = np.fromiter((k[0] for k in counts), np.int32,
                           len(counts))
        cols = np.fromiter((k[1] for k in counts), np.int32,
                           len(counts))
        vals = np.fromiter(counts.values(), np.float32, len(counts))
        return rows, cols, vals

    # -- training --------------------------------------------------------
    def fit(self, sentences: Optional[Iterable] = None) -> "Glove":
        sentences = sentences if sentences is not None \
            else getattr(self, "_sentences", None)
        seqs = self._tokenize_corpus(sentences)
        self.vocab = build_vocab(seqs, self.min_word_frequency)
        n = len(self.vocab)
        rows, cols, vals = self._cooccurrences(seqs)
        if rows.size == 0:
            raise ValueError("empty co-occurrence matrix (corpus too "
                             "small for the vocab/window settings)")
        logx = np.log(vals)
        fx = np.minimum(1.0, (vals / self.x_max) ** self.alpha) \
            .astype(np.float32)

        rng = np.random.RandomState(self.seed)
        d = self.layer_size
        def init(shape):
            return ((rng.rand(*shape) - 0.5) / d).astype(np.float32)
        state = (jnp.asarray(init((n, d))), jnp.asarray(init((n, d))),
                 jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32),
                 jnp.zeros((n, d), jnp.float32),
                 jnp.zeros((n, d), jnp.float32),
                 jnp.zeros(n, jnp.float32), jnp.zeros(n, jnp.float32))

        nnz = rows.size
        bs = min(self.batch_size, nnz)
        for _ in range(self.epochs):
            order = rng.permutation(nnz)
            for s in range(0, nnz, bs):
                sel = order[s:s + bs]
                if len(sel) < bs:              # pad to a fixed shape
                    sel = np.concatenate(
                        [sel, rng.choice(nnz, bs - len(sel))])
                state, _ = self._glove_jit(
                    state, jnp.asarray(rows[sel]),
                    jnp.asarray(cols[sel]), jnp.asarray(logx[sel]),
                    jnp.asarray(fx[sel]),
                    jnp.float32(self.learning_rate))
        # final embedding: w + w̃ (the GloVe paper's recommendation,
        # which the reference follows)
        self.syn0 = np.asarray(state[0]) + np.asarray(state[1])
        self.syn1 = np.asarray(state[1])
        return self
