"""BertIterator — wordpiece featurization + MLM masking.

Reference parity: `org.deeplearning4j.iterator.BertIterator`
(SURVEY.md D16; BASELINE.json BERT config): sentence provider →
`BertWordPieceTokenizer` → fixed-length `[CLS] … [SEP]` id tensors
with attention masks; task UNSUPERVISED applies the BERT MLM
corruption (15% of positions: 80% → [MASK], 10% → random id,
10% → kept) and emits `mlm_labels` with -1 on unmasked positions —
exactly the batch dict `models.bert.Bert.pretrain_loss` consumes.
Task SEQ_CLASSIFICATION emits one-hot labels instead.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .tokenization import BertWordPieceTokenizer


class BertIterator:
    UNSUPERVISED = "UNSUPERVISED"
    SEQ_CLASSIFICATION = "SEQ_CLASSIFICATION"

    def __init__(self, tokenizer: BertWordPieceTokenizer,
                 sentences: Sequence,
                 max_length: int = 128,
                 batch_size: int = 16,
                 task: str = UNSUPERVISED,
                 labels: Optional[Sequence[int]] = None,
                 n_labels: Optional[int] = None,
                 mask_prob: float = 0.15,
                 seed: int = 0,
                 pad_token: str = "[PAD]",
                 cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]",
                 mask_token: str = "[MASK]"):
        self.tk = tokenizer
        self.sentences = list(sentences)
        self.max_length = max_length
        self.batch_size = batch_size
        self.task = task
        self.labels = list(labels) if labels is not None else None
        self.n_labels = n_labels or (
            (max(self.labels) + 1) if self.labels else None)
        self.mask_prob = mask_prob
        self.seed = seed
        self.pad_id = tokenizer.id_of(pad_token)
        self.cls_id = tokenizer.id_of(cls_token)
        self.sep_id = tokenizer.id_of(sep_token)
        self.mask_id = tokenizer.id_of(mask_token)
        self._special = {self.pad_id, self.cls_id, self.sep_id}
        self._rng = np.random.RandomState(seed)
        self._pos = 0

    # -- iterator protocol (DataSetIterator-shaped) -------------------
    def reset(self):
        self._pos = 0
        self._rng = np.random.RandomState(self.seed)

    def has_next(self) -> bool:
        return self._pos < len(self.sentences)

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()

    def batch(self) -> int:
        return self.batch_size

    # -- featurization ------------------------------------------------
    def _encode_one(self, sentence):
        """-> (ids, token_type_ids): segment 1 covers sentence B of a
        pair including its trailing [SEP] (BERT convention)."""
        types = None
        if isinstance(sentence, tuple):      # sentence pair
            a, b = sentence
            seg_a = ([self.cls_id]
                     + self.tk.encode(a)[: self.max_length - 3]
                     + [self.sep_id])
            seg_b = self.tk.encode(b)
            seg_b = seg_b[: self.max_length - len(seg_a) - 1] \
                + [self.sep_id]
            ids = seg_a + seg_b
            types = [0] * len(seg_a) + [1] * len(seg_b)
        else:
            ids = ([self.cls_id]
                   + self.tk.encode(sentence)[: self.max_length - 2]
                   + [self.sep_id])
        out = np.full(self.max_length, self.pad_id, np.int32)
        out[: len(ids)] = ids
        tt = np.zeros(self.max_length, np.int32)
        if types is not None:
            tt[: len(types)] = types
        return out, tt

    def _mask(self, ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """BERT MLM corruption. Returns (corrupted, labels)."""
        labels = np.full_like(ids, -1)
        out = ids.copy()
        vocab_size = len(self.tk.vocab)
        for i, t in enumerate(ids):
            if int(t) in self._special:
                continue
            if self._rng.rand() >= self.mask_prob:
                continue
            labels[i] = t
            r = self._rng.rand()
            if r < 0.8:
                out[i] = self.mask_id
            elif r < 0.9:
                out[i] = self._rng.randint(vocab_size)
            # else: keep original token
        return out, labels

    def next(self) -> Dict[str, np.ndarray]:  # noqa: A003
        if not self.has_next():
            raise StopIteration("iterator exhausted; call reset()")
        end = min(self._pos + self.batch_size, len(self.sentences))
        rows = [self._encode_one(self.sentences[i])
                for i in range(self._pos, end)]
        sl = slice(self._pos, end)
        self._pos = end
        ids = np.stack([r[0] for r in rows])
        tts = np.stack([r[1] for r in rows])
        att = (ids != self.pad_id).astype(np.float32)
        batch = {"input_ids": ids,
                 "token_type_ids": tts,
                 "attention_mask": att}
        if self.task == self.UNSUPERVISED:
            pairs = [self._mask(r) for r in ids]
            batch["input_ids"] = np.stack([p[0] for p in pairs])
            batch["mlm_labels"] = np.stack([p[1] for p in pairs])
        else:
            lab = np.asarray(self.labels[sl], np.int32)
            batch["labels"] = np.eye(self.n_labels,
                                     dtype=np.float32)[lab]
        return batch
