"""Vocabulary cache (reference: `org.deeplearning4j.models.word2vec.
wordstore.inmemory.AbstractCache` / `VocabConstructor`).

Holds word -> index, counts, and the unigram^0.75 negative-sampling
table the SGNS trainer draws from (the reference builds the same
table natively for its negative sampling).
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np


class VocabCache:
    def __init__(self, words: List[str], counts: Dict[str, int]):
        self.words = words
        self.index: Dict[str, int] = {w: i for i, w in
                                      enumerate(words)}
        self.counts = counts
        self._neg_table: Optional[np.ndarray] = None

    def __len__(self):
        return len(self.words)

    def __contains__(self, w):
        return w in self.index

    def id_of(self, w: str) -> int:
        return self.index[w]

    def word_at(self, i: int) -> str:
        return self.words[i]

    def count_of(self, w: str) -> int:
        return self.counts.get(w, 0)

    def total_count(self) -> int:
        return sum(self.counts[w] for w in self.words)

    def neg_sampling_probs(self, power: float = 0.75) -> np.ndarray:
        """Unigram^power distribution over word indices (word2vec's
        negative-sampling table, normalized instead of the reference's
        1e8-slot discretized table)."""
        if self._neg_table is None:
            f = np.array([self.counts[w] for w in self.words],
                         np.float64) ** power
            self._neg_table = (f / f.sum()).astype(np.float32)
        return self._neg_table


def cosine_similarity(a: np.ndarray, b: np.ndarray) -> float:
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)
                          + 1e-12))


def nearest_words(matrix: np.ndarray, words, vec: np.ndarray,
                  n: int, exclude=None):
    """Top-n words by cosine similarity to ``vec``."""
    sims = (matrix @ vec) / (np.linalg.norm(matrix, axis=1)
                             * np.linalg.norm(vec) + 1e-12)
    order = np.argsort(-sims)
    return [words[i] for i in order if words[i] != exclude][:n]


def build_vocab(token_seqs: Iterable[List[str]],
                min_word_frequency: int = 1,
                max_size: Optional[int] = None) -> VocabCache:
    """reference: VocabConstructor.buildJointVocabulary — count,
    prune by min frequency, order by descending count."""
    c = Counter()
    for seq in token_seqs:
        c.update(seq)
    items = [(w, n) for w, n in c.most_common()
             if n >= min_word_frequency]
    if max_size:
        items = items[:max_size]
    return VocabCache([w for w, _ in items], dict(items))
