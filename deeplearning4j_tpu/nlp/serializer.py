"""Word-vector persistence (reference: `org.deeplearning4j.models.
embeddings.loader.WordVectorSerializer` — SURVEY.md D16).

Two formats:
- ``.txt``: the classic word2vec text format (``word v1 v2 ...`` per
  line, optional count header) — interoperable with gensim/fastText
  text exports;
- ``.npz``: compact binary (words + matrix [+ syn1 + counts]) for
  exact round-trips including the trainable state.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def write_word_vectors(model, path: str, include_header: bool = True):
    """Text format from any model with ``vocab`` + ``syn0``."""
    words = model.vocab.words
    vecs = model.syn0
    with open(path, "w", encoding="utf-8") as f:
        if include_header:
            f.write(f"{len(words)} {vecs.shape[1]}\n")
        for i, w in enumerate(words):
            f.write(w + " " + " ".join("%.6g" % v for v in vecs[i])
                    + "\n")
    return path


def read_word_vectors(path: str):
    """Text format -> StaticWordVectors (lookup-only model)."""
    words, rows = [], []
    with open(path, encoding="utf-8") as f:
        # .split() (not split(' ')) so CRLF endings and stray spaces
        # from other tools' exports parse cleanly
        first = f.readline().split()
        if len(first) == 2 and all(p.isdigit() for p in first):
            pass                      # header line; skip
        elif first:
            words.append(first[0])
            rows.append([float(v) for v in first[1:]])
        for line in f:
            parts = line.split()
            if len(parts) < 2:
                continue
            words.append(parts[0])
            rows.append([float(v) for v in parts[1:]])
    return StaticWordVectors(words, np.asarray(rows, np.float32))


def write_word2vec_model(model, path: str):
    """Full binary round-trip incl. output weights + counts
    (reference: writeWord2VecModel)."""
    payload = dict(
        words=np.asarray(model.vocab.words, dtype=object),
        counts=np.asarray([model.vocab.counts[w]
                           for w in model.vocab.words], np.int64),
        syn0=model.syn0,
        syn1=model.syn1 if model.syn1 is not None else np.zeros(0),
        layer_size=np.int64(model.layer_size))
    if str(path).endswith(".npz"):
        np.savez_compressed(path, **payload)
    else:
        # np.savez_compressed appends '.npz' to bare paths; write to
        # a handle so the caller's path is exactly what exists
        with open(path, "wb") as fh:
            np.savez_compressed(fh, **payload)
    return path


def read_word2vec_model(path: str):
    """-> Word2Vec with vocab/tables restored (resumable training)."""
    from .vocab import VocabCache
    from .word2vec import Word2Vec
    z = np.load(path, allow_pickle=True)
    words = [str(w) for w in z["words"]]
    counts = dict(zip(words, (int(c) for c in z["counts"])))
    w2v = Word2Vec(layer_size=int(z["layer_size"]))
    w2v.vocab = VocabCache(words, counts)
    w2v.syn0 = z["syn0"].astype(np.float32)
    syn1 = z["syn1"].astype(np.float32)
    w2v.syn1 = syn1 if syn1.size else None
    return w2v


class StaticWordVectors:
    """Lookup-only word vectors (reference: StaticWord2Vec /
    WordVectors interface). Similarity math is shared with the
    trainable models via :mod:`.vocab` helpers."""

    def __init__(self, words, matrix: np.ndarray):
        self.words = list(words)
        self.index = {w: i for i, w in enumerate(self.words)}
        self.syn0 = matrix

    def has_word(self, w) -> bool:
        return w in self.index

    def get_word_vector(self, w) -> np.ndarray:
        return self.syn0[self.index[w]]

    def similarity(self, a, b) -> float:
        from .vocab import cosine_similarity
        return cosine_similarity(self.get_word_vector(a),
                                 self.get_word_vector(b))

    def words_nearest(self, word, n: int = 10):
        from .vocab import nearest_words
        return nearest_words(self.syn0, self.words,
                             self.get_word_vector(word), n,
                             exclude=word)
