"""Model checkpoint serialization.

Reference parity: ``org.deeplearning4j.util.ModelSerializer`` (SURVEY.md
D11, section 5.4): a zip holding ``configuration.json`` +
``coefficients.bin`` (flattened params in save order) +
``updaterState.bin`` + optional normalizer. Here the same zip layout with
npz payloads: the pytree is flattened to the deterministic
``paramTable`` order, so the "single flattened params view" survives as a
serialization order only (SURVEY.md section 5.4 TPU note).

For sharded/multi-host async checkpointing use orbax via
``parallel.checkpoint`` (extension); this serializer is the API-parity
single-process path.
"""
from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path

import jax.numpy as jnp
import numpy as np

CONFIG_ENTRY = "configuration.json"
COEFFICIENTS_ENTRY = "coefficients.npz"
UPDATER_ENTRY = "updaterState.npz"
STATE_ENTRY = "modelState.npz"
NORMALIZER_ENTRY = "normalizer.json"
META_ENTRY = "meta.json"


def _tree_to_flat_dict(tree, prefix="") -> dict:
    out = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.update(_tree_to_flat_dict(tree[k], f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_tree_to_flat_dict(v, f"{prefix}{i}/"))
    elif tree is None:
        pass
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def _write_npz(zf: zipfile.ZipFile, name: str, flat: dict):
    buf = io.BytesIO()
    np.savez(buf, **flat)
    zf.writestr(name, buf.getvalue())


def _read_npz(zf: zipfile.ZipFile, name: str) -> dict:
    with zf.open(name) as f:
        data = np.load(io.BytesIO(f.read()))
        return {k: data[k] for k in data.files}


class ModelSerializer:
    @staticmethod
    def write_model(model, path, save_updater: bool = True,
                    normalizer=None, model_class: str = None):
        """model: MultiLayerNetwork or ComputationGraph (or a host
        snapshot shim exposing the same attrs; ``model_class`` then
        names the real class for restore dispatch)."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        # fsdp keeps params resident as mesh-shaped padded flats;
        # checkpoints always store the dense per-tensor layout so they
        # restore on any device count (states_to_dense also needs the
        # dense params to rebuild its flatten spec)
        params = (model.dense_params()
                  if hasattr(model, "dense_params") else model.params)
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(CONFIG_ENTRY, model.conf.to_json())
            _write_npz(zf, COEFFICIENTS_ENTRY,
                       _tree_to_flat_dict(params))
            _write_npz(zf, STATE_ENTRY, _tree_to_flat_dict(model.states))
            if save_updater:
                # ZeRO-1 sharded layouts (parallel.zero) are mesh-shaped
                # padded flat vectors; checkpoints always store the dense
                # per-tensor layout so they restore on any device count
                from deeplearning4j_tpu.parallel.zero import \
                    states_to_dense
                _write_npz(zf, UPDATER_ENTRY,
                           _tree_to_flat_dict(states_to_dense(
                               params, model.updater_states)))
            if normalizer is not None:
                zf.writestr(NORMALIZER_ENTRY,
                            json.dumps(normalizer.to_map()))
            zf.writestr(META_ENTRY, json.dumps({
                "model_class": model_class or type(model).__name__,
                "iteration_count": model.iteration_count,
                "epoch_count": model.epoch_count,
                "format_version": 1,
            }))

    @staticmethod
    def peek_meta(path) -> dict:
        """The archive's identity without loading any weights:
        ``model_class`` (sniffed for pre-meta / SameDiff zips),
        iteration/epoch counts, format version. The serving registry
        uses this to describe artifacts it hasn't loaded yet."""
        with zipfile.ZipFile(Path(path)) as zf:
            names = zf.namelist()
            meta = json.loads(zf.read(META_ENTRY).decode()) \
                if META_ENTRY in names else {}
            if "model_class" not in meta:
                meta["model_class"] = ("SameDiff"
                                       if "graph.json" in names
                                       else "MultiLayerNetwork")
        return meta

    @staticmethod
    def restore_model(path, load_updater: bool = True):
        """Dispatch on the archive's meta.json model_class. SameDiff
        archives (a zip with a ``graph.json`` entry — written by
        ``SameDiff.save``/``checkpoint_snapshot``) load via
        ``SameDiff.load``: one restore entry point for every zip the
        stack writes."""
        meta = ModelSerializer.peek_meta(path)
        cls = meta.get("model_class")
        if cls == "SameDiff":
            from deeplearning4j_tpu.autodiff.samediff import SameDiff
            return SameDiff.load(str(path))
        if cls == "ComputationGraph":
            return ModelSerializer.restore_computation_graph(
                path, load_updater)
        return ModelSerializer.restore_multi_layer_network(
            path, load_updater)

    @staticmethod
    def restore_multi_layer_network(path, load_updater: bool = True):
        from deeplearning4j_tpu.nn.conf.builders import \
            MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        path = Path(path)
        with zipfile.ZipFile(path) as zf:
            conf = MultiLayerConfiguration.from_json(
                zf.read(CONFIG_ENTRY).decode())
            net = MultiLayerNetwork(conf).init()
            ModelSerializer._restore_into(zf, net, load_updater)
        return net

    @staticmethod
    def restore_computation_graph(path, load_updater: bool = True):
        from deeplearning4j_tpu.nn.conf.graph_conf import \
            ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        path = Path(path)
        with zipfile.ZipFile(path) as zf:
            conf = ComputationGraphConfiguration.from_json(
                zf.read(CONFIG_ENTRY).decode())
            net = ComputationGraph(conf).init()
            ModelSerializer._restore_into(zf, net, load_updater)
        return net

    @staticmethod
    def _restore_into(zf, net, load_updater):
        flat = _read_npz(zf, COEFFICIENTS_ENTRY)
        net.params = _merge_flat(net.params, flat)
        if STATE_ENTRY in zf.namelist():
            net.states = _merge_flat(net.states,
                                     _read_npz(zf, STATE_ENTRY))
        if load_updater and UPDATER_ENTRY in zf.namelist():
            flat = _read_npz(zf, UPDATER_ENTRY)
            net.updater_states = _graft_encoded(
                _merge_flat(net.updater_states, flat), flat)
        meta = json.loads(zf.read(META_ENTRY).decode()) \
            if META_ENTRY in zf.namelist() else {}
        net.iteration_count = meta.get("iteration_count", 0)
        net.epoch_count = meta.get("epoch_count", 0)

    @staticmethod
    def restore_normalizer(path):
        from deeplearning4j_tpu.datasets.normalizers import Normalizer
        with zipfile.ZipFile(Path(path)) as zf:
            if NORMALIZER_ENTRY not in zf.namelist():
                return None
            return Normalizer.from_map(
                json.loads(zf.read(NORMALIZER_ENTRY).decode()))


def _graft_encoded(tree, flat: dict):
    """Re-attach encoded-rung subtrees the dense template has no slot
    for. A fresh net's updater states carry only the optimizer's own
    slots, so ``_merge_flat`` would silently drop the ``__encoded__``
    error-feedback residual (+ tau/step/sparsity) that
    ``states_to_dense`` wrote; graft those npz keys back so encoded
    checkpoints restore bitwise on any device count."""
    from deeplearning4j_tpu.learning.updaters import ENCODED_KEY
    marker = f"/{ENCODED_KEY}/"
    extras: dict = {}
    for key, value in flat.items():
        entry, _, rest = key.partition(marker)
        if not rest:
            continue
        node = extras.setdefault(entry, {})
        parts = rest.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(value)
    if not extras:
        return tree
    out = dict(tree)
    for entry, enc in extras.items():
        base = out.get(entry)
        base = dict(base) if isinstance(base, dict) else {}
        base[ENCODED_KEY] = enc
        out[entry] = base
    return out


def _merge_flat(template_tree, flat: dict):
    """Rebuild a pytree shaped like template_tree from a flat npz dict."""
    def build(node, prefix):
        if isinstance(node, dict):
            return {k: build(v, f"{prefix}{k}/") for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(build(v, f"{prefix}{i}/")
                              for i, v in enumerate(node))
        if node is None:
            return node
        key = prefix[:-1]
        if key in flat:
            return jnp.asarray(flat[key])
        return node
    return build(template_tree, "")
