"""CheckpointListener + fault-tolerant resumable training.

Reference parity: ``org.deeplearning4j.optimize.listeners.
CheckpointListener`` (SURVEY.md D7, section 5.4): every N iterations /
epochs / minutes, keep-last / keep-every rotation, plus the static
checkpoint accessors (``availableCheckpoints`` / ``lastCheckpoint`` /
``loadCheckpointMLN``). Saves are ATOMIC (tmp + rename) so a crash
mid-save never corrupts the newest checkpoint on disk.

:class:`FaultTolerantTrainer` is SURVEY.md §5.3's TPU translation of
the reference's (weak) elasticity guarantees: "elasticity = resumable
jobs". It restores the newest loadable checkpoint before training and
skips over corrupt files — a restarted job resumes with optimizer
state, iteration count, and epoch count intact.
"""
from __future__ import annotations

import logging
import os
import re
import time
from pathlib import Path
from typing import List, Optional

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.utils.serializer import ModelSerializer

log = logging.getLogger("deeplearning4j_tpu")


class CheckpointListener(TrainingListener):
    def __init__(self, save_dir, *, save_every_n_iterations: int = 0,
                 save_every_n_epochs: int = 0,
                 save_every_n_seconds: float = 0.0,
                 keep_last: int = 0, keep_every: int = 0):
        self.dir = Path(save_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.n_iter = save_every_n_iterations
        self.n_epoch = save_every_n_epochs
        self.n_seconds = save_every_n_seconds
        self.keep_last = keep_last
        self.keep_every = keep_every
        self._last_save_time = time.time()
        self._saved: List[Path] = []
        self._counter = 0

    def _save(self, model):
        path = self.dir / f"checkpoint_{self._counter}.zip"
        tmp = self.dir / f".checkpoint_{self._counter}.zip.tmp"
        ModelSerializer.write_model(model, tmp)
        os.replace(tmp, path)      # atomic: readers never see partials
        self._counter += 1
        self._saved.append(path)
        self._last_saved_state = (model.iteration_count,
                                  model.epoch_count)
        self._rotate()

    def _rotate(self):
        if self.keep_last <= 0:
            return
        keep: set = set(self._saved[-self.keep_last:])
        if self.keep_every > 0:
            for i, p in enumerate(self._saved):
                if i % self.keep_every == 0:
                    keep.add(p)
        for p in self._saved:
            if p not in keep and p.exists():
                p.unlink()
        self._saved = [p for p in self._saved if p in keep or p.exists()]

    def iteration_done(self, model, iteration: int, epoch: int):
        if self.n_iter > 0 and (iteration + 1) % self.n_iter == 0:
            self._save(model)
        elif self.n_seconds > 0 and \
                time.time() - self._last_save_time >= self.n_seconds:
            self._save(model)
            self._last_save_time = time.time()

    def on_epoch_end(self, model):
        # epoch_count is epochs COMPLETED by the time listeners fire
        if self.n_epoch > 0 and model.epoch_count % self.n_epoch == 0:
            self._save(model)

    def last_checkpoint(self) -> Optional[Path]:
        return self._saved[-1] if self._saved else None

    # -- static accessors (reference: CheckpointListener statics) --------
    @staticmethod
    def available_checkpoints(save_dir) -> List[Path]:
        """Checkpoints on disk, oldest -> newest (reference:
        availableCheckpoints)."""
        d = Path(save_dir)
        if not d.is_dir():
            return []
        def idx(p):
            m = re.match(r"checkpoint_(\d+)\.zip$", p.name)
            return int(m.group(1)) if m else -1
        return sorted((p for p in d.glob("checkpoint_*.zip")
                       if idx(p) >= 0), key=idx)

    @staticmethod
    def last_checkpoint_in(save_dir) -> Optional[Path]:
        cps = CheckpointListener.available_checkpoints(save_dir)
        return cps[-1] if cps else None

    @staticmethod
    def load_checkpoint(save_dir_or_path, *, skip_corrupt: bool = True):
        """Load the newest loadable checkpoint (reference:
        loadCheckpointMLN/loadLastCheckpointMLN). With ``skip_corrupt``
        a truncated/partial newest file falls back to the previous one
        — the §5.3 crash-recovery path."""
        p = Path(save_dir_or_path)
        candidates = ([p] if p.is_file()
                      else list(reversed(
                          CheckpointListener.available_checkpoints(p))))
        last_err = None
        for cp in candidates:
            try:
                return ModelSerializer.restore_model(cp)
            except Exception as e:            # corrupt / partial file
                last_err = e
                if not skip_corrupt:
                    raise
                log.warning("skipping unreadable checkpoint %s: %s",
                            cp, e)
        if last_err is not None:
            raise last_err
        return None


class FaultTolerantTrainer:
    """Resumable training loop (SURVEY.md §5.3: checkpoint-restart is
    the framework's elasticity story, matching the reference's actual
    guarantees). Restores the newest loadable checkpoint at
    construction; ``fit`` then trains with periodic atomic checkpoints.

    Usage::

        trainer = FaultTolerantTrainer(lambda: build_net(), "ckpts",
                                       save_every_n_iterations=100)
        trainer.fit(train_iter, n_epochs=10)   # safe to re-run after
                                               # a crash: it resumes
    """

    def __init__(self, model_factory, save_dir, *,
                 save_every_n_iterations: int = 0,
                 save_every_n_epochs: int = 1,
                 keep_last: int = 3):
        self.save_dir = Path(save_dir)
        restored = None
        if CheckpointListener.available_checkpoints(self.save_dir):
            restored = CheckpointListener.load_checkpoint(self.save_dir)
        self.model = restored if restored is not None \
            else model_factory()
        self.resumed = restored is not None
        self._listener = CheckpointListener(
            self.save_dir,
            save_every_n_iterations=save_every_n_iterations,
            save_every_n_epochs=save_every_n_epochs,
            keep_last=keep_last)
        # continue numbering after existing checkpoints
        existing = CheckpointListener.available_checkpoints(
            self.save_dir)
        if existing:
            m = re.match(r"checkpoint_(\d+)\.zip$", existing[-1].name)
            self._listener._counter = int(m.group(1)) + 1
            self._listener._saved = list(existing)
        self.model.add_listeners(self._listener)

    def fit(self, data, *, n_epochs: int = 1):
        """Train until ``n_epochs`` TOTAL epochs are done — a resumed
        job runs only the remaining epochs, so crash + re-run converges
        to the same amount of training as an uncrashed run."""
        remaining = n_epochs - self.model.epoch_count
        if remaining <= 0:
            log.info("fit: %d epochs already done, nothing to do",
                     self.model.epoch_count)
            return self.model
        self.model.fit(data, n_epochs=remaining)
        # final checkpoint — skipped when the epoch-end listener just
        # saved this exact state (don't burn a rotation slot on a dup)
        state = (self.model.iteration_count, self.model.epoch_count)
        if getattr(self._listener, "_last_saved_state", None) != state:
            self._listener._save(self.model)
        return self.model
