"""CheckpointListener + fault-tolerant resumable training.

Reference parity: ``org.deeplearning4j.optimize.listeners.
CheckpointListener`` (SURVEY.md D7, section 5.4): every N iterations /
epochs / minutes, keep-last / keep-every rotation, plus the static
checkpoint accessors (``availableCheckpoints`` / ``lastCheckpoint`` /
``loadCheckpointMLN``). Saves are ATOMIC (tmp + rename) so a crash
mid-save never corrupts the newest checkpoint on disk.

:class:`FaultTolerantTrainer` is SURVEY.md §5.3's TPU translation of
the reference's (weak) elasticity guarantees: "elasticity = resumable
jobs". It restores the newest loadable checkpoint before training and
skips over corrupt files — a restarted job resumes with optimizer
state, iteration count, and epoch count intact.
"""
from __future__ import annotations

import concurrent.futures
import json
import logging
import os
import re
import time
from pathlib import Path
from typing import List, Optional

import jax

from deeplearning4j_tpu.common import faults, telemetry
from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.utils.serializer import ModelSerializer

log = logging.getLogger("deeplearning4j_tpu")


class _ModelSnapshot:
    """Copy of everything ``write_model`` reads, taken at save time so
    the training loop can keep mutating the live model while the
    background thread serializes.

    Two flavors (ROADMAP item 4's async-snapshotting ask):

    - eager (``defer=False``): the device->host transfer happens HERE,
      on the step loop — the pre-elasticity behavior, still used by
      synchronous listeners;
    - deferred (``defer=True``): only donation-safe ON-DEVICE copies
      are forked here (``jnp.copy`` dispatches asynchronously and
      preserves sharding, so fsdp flats stay 1/N resident); the
      device->host transfer — and under fsdp the dense re-gather —
      runs in :meth:`materialize` on the checkpoint worker.  The stall
      histogram then collapses to ~the previous-write join plus the
      copy dispatch."""

    class _ConfShim:
        def __init__(self, conf_json: str):
            self._json = conf_json

        def to_json(self) -> str:
            return self._json

    def __init__(self, model, *, defer: bool = False):
        self.model_class = type(model).__name__
        self.conf = _ModelSnapshot._ConfShim(model.conf.to_json())
        self.iteration_count = model.iteration_count
        self.epoch_count = model.epoch_count
        self._device_trees = None
        self._fsdp_specs = None
        if defer:
            # the copy is REQUIRED for the same donation reason as
            # np.array below: the next train step donates param/state
            # buffers, and an executable honoring the donation would
            # mutate the snapshot while the worker reads it.  jnp.copy
            # forks fresh buffers without a host sync.
            import jax.numpy as jnp

            def fork(a):
                return (jnp.copy(a)
                        if hasattr(a, "shape") and hasattr(a, "dtype")
                        else a)

            if getattr(model, "_params_are_fsdp", None) is not None \
                    and model._params_are_fsdp():
                self._fsdp_specs = dict(model._fsdp_specs)
            self._device_trees = jax.tree_util.tree_map(
                fork, (model.params, model.states,
                       model.updater_states))
            return
        # device->host transfers (the only part the step loop waits on).
        # np.array (copy) is REQUIRED, not np.asarray: on the CPU
        # backend device_get returns zero-copy VIEWS of the XLA
        # buffers, and the train step donates params — an executable
        # that honors the donation (cache-loaded ones do) would mutate
        # the snapshot in place while the background thread writes it
        import numpy as _np
        # dense_params() regathers fsdp flat shards into per-tensor
        # arrays so the snapshot (and the checkpoint on disk) is
        # device-count portable
        params = (model.dense_params()
                  if hasattr(model, "dense_params") else model.params)
        self.params = jax.tree_util.tree_map(
            _np.array, jax.device_get(params))
        self.states = jax.tree_util.tree_map(
            _np.array, jax.device_get(model.states))
        self.updater_states = jax.tree_util.tree_map(
            _np.array, jax.device_get(model.updater_states))

    def materialize(self) -> "_ModelSnapshot":
        """Deferred device->host transfer (checkpoint worker); no-op
        for an eager snapshot.  The fsdp dense re-gather happens here
        too, off the step path."""
        if self._device_trees is None:
            return self
        import numpy as _np
        params, states, upd = self._device_trees
        if self._fsdp_specs:
            from deeplearning4j_tpu.parallel.zero import params_to_dense
            params = params_to_dense(params, self._fsdp_specs)
        self.params = jax.tree_util.tree_map(
            _np.array, jax.device_get(params))
        self.states = jax.tree_util.tree_map(
            _np.array, jax.device_get(states))
        self.updater_states = jax.tree_util.tree_map(
            _np.array, jax.device_get(upd))
        self._device_trees = None
        return self


class CheckpointListener(TrainingListener):
    """``asynchronous=True`` (default, SURVEY.md §5.4's "async
    multi-host checkpointing" prescription): ``_save`` snapshots the
    model device->host and hands serialization + the atomic rename to
    a background thread, so the step loop never blocks on IO.  At most
    ONE write is in flight; a new save first joins the previous one
    (bounded memory, strict file ordering).  Call :meth:`flush` before
    reading checkpoints from disk."""

    def __init__(self, save_dir, *, save_every_n_iterations: int = 0,
                 save_every_n_epochs: int = 0,
                 save_every_n_seconds: float = 0.0,
                 keep_last: int = 0, keep_every: int = 0,
                 asynchronous: bool = True,
                 defer_snapshot: Optional[bool] = None):
        #: defer the device->host snapshot copy to the background
        #: writer (async listeners only; None -> DL4J_TPU_ASYNC_SNAPSHOT)
        self.defer_snapshot = defer_snapshot
        self.dir = Path(save_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.n_iter = save_every_n_iterations
        self.n_epoch = save_every_n_epochs
        self.n_seconds = save_every_n_seconds
        self.keep_last = keep_last
        self.keep_every = keep_every
        self.asynchronous = asynchronous
        self._last_save_time = time.time()
        self._saved: List[Path] = []
        self._counter = 0
        self._executor = None
        self._pending: Optional[concurrent.futures.Future] = None

    def _write(self, snapshot, tmp: Path, path: Path):
        with telemetry.span("checkpoint.save", path=str(path)):
            t0 = time.perf_counter()
            if hasattr(snapshot, "materialize"):
                # deferred snapshot: the device->host transfer (and
                # fsdp dense re-gather) runs here, off the step path
                snapshot.materialize()
            if hasattr(snapshot, "write"):
                # model-provided snapshot (SameDiff.checkpoint_snapshot:
                # the imported-model path has its own zip format)
                snapshot.write(tmp)
            else:
                ModelSerializer.write_model(
                    snapshot, tmp, model_class=snapshot.model_class)
            n_bytes = tmp.stat().st_size
            os.replace(tmp, path)  # atomic: readers never see partials
            if telemetry.enabled():
                telemetry.histogram(
                    "dl4j_checkpoint_save_seconds",
                    "checkpoint serialize + atomic-rename time "
                    "(background thread when asynchronous)").observe(
                        time.perf_counter() - t0)
                telemetry.counter(
                    "dl4j_checkpoint_bytes_total",
                    "checkpoint bytes moved, by op").inc(n_bytes,
                                                         op="save")
        self._rotate()

    def _save(self, model):
        # everything in here runs ON the step loop — join of the
        # previous write, device->host snapshot, and (when synchronous)
        # the full serialize.  That is the checkpoint STALL the scaling
        # observatory attributes (ROADMAP item 5's named metric): async
        # snapshotting succeeds when this histogram collapses to the
        # snapshot copy alone.
        t0 = time.perf_counter()
        try:
            self.flush()     # join the previous in-flight write FIRST:
            # the worker's _rotate reassigns self._saved, so bookkeeping
            # below must not race it
            path = self.dir / f"checkpoint_{self._counter}.zip"
            tmp = self.dir / f".checkpoint_{self._counter}.zip.tmp"
            self._counter += 1
            self._saved.append(path)
            self._last_saved_state = (model.iteration_count,
                                      model.epoch_count)
            defer = (self.defer_snapshot
                     if self.defer_snapshot is not None
                     else Environment.get().async_snapshot)
            snap = (model.checkpoint_snapshot()
                    if hasattr(model, "checkpoint_snapshot")
                    else _ModelSnapshot(
                        model, defer=bool(defer) and self.asynchronous))
            if not self.asynchronous:
                self._write(snap, tmp, path)
                return
            if self._executor is None:
                self._executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix="dl4j-tpu-ckpt")
            self._pending = self._executor.submit(self._write, snap,
                                                  tmp, path)
        finally:
            stall = time.perf_counter() - t0
            if telemetry.enabled():
                telemetry.histogram(
                    "dl4j_checkpoint_stall_seconds",
                    "step-loop-blocking checkpoint time: join of the "
                    "previous async write + device->host snapshot "
                    "(plus the whole serialize when synchronous)"
                    ).observe(stall)
            from deeplearning4j_tpu.common import stepstats
            stepstats.note_checkpoint_stall(stall)

    def flush(self):
        """Join the in-flight background write (reference analogue:
        orbax ``wait_until_finished``), then park the worker thread.
        Re-raises a failed write's exception."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            try:
                pending.result()
            finally:
                if self._executor is not None:
                    # no non-daemon thread outlives the save burst; a
                    # later save recreates the executor
                    self._executor.shutdown(wait=True)
                    self._executor = None

    def resume_numbering(self, save_dir=None):
        """Continue checkpoint numbering after whatever an earlier
        (crashed) run left in ``save_dir`` — the one place the
        filename pattern is decoded for resume."""
        existing = CheckpointListener.available_checkpoints(
            save_dir if save_dir is not None else self.dir)
        if existing:
            m = re.match(r"checkpoint_(\d+)\.zip$", existing[-1].name)
            self._counter = int(m.group(1)) + 1
            self._saved = list(existing)
        return self

    def _rotate(self):
        if self.keep_last <= 0:
            return
        keep: set = set(self._saved[-self.keep_last:])
        if self.keep_every > 0:
            for i, p in enumerate(self._saved):
                if i % self.keep_every == 0:
                    keep.add(p)
        for p in self._saved:
            if p not in keep and p.exists():
                p.unlink()
        self._saved = [p for p in self._saved if p in keep or p.exists()]

    def iteration_done(self, model, iteration: int, epoch: int):
        if self.n_iter > 0 and (iteration + 1) % self.n_iter == 0:
            self._save(model)
        elif self.n_seconds > 0 and \
                time.time() - self._last_save_time >= self.n_seconds:
            self._save(model)
            self._last_save_time = time.time()

    def on_epoch_end(self, model):
        # epoch_count is epochs COMPLETED by the time listeners fire
        if self.n_epoch > 0 and model.epoch_count % self.n_epoch == 0:
            self._save(model)

    def last_checkpoint(self) -> Optional[Path]:
        return self._saved[-1] if self._saved else None

    # -- static accessors (reference: CheckpointListener statics) --------
    @staticmethod
    def available_checkpoints(save_dir) -> List[Path]:
        """Checkpoints on disk, oldest -> newest (reference:
        availableCheckpoints)."""
        d = Path(save_dir)
        if not d.is_dir():
            return []
        def idx(p):
            m = re.match(r"checkpoint_(\d+)\.zip$", p.name)
            return int(m.group(1)) if m else -1
        return sorted((p for p in d.glob("checkpoint_*.zip")
                       if idx(p) >= 0), key=idx)

    @staticmethod
    def last_checkpoint_in(save_dir) -> Optional[Path]:
        cps = CheckpointListener.available_checkpoints(save_dir)
        return cps[-1] if cps else None

    @staticmethod
    def _restore_any(cp: Path):
        """Format-dispatching restore. ``ModelSerializer.restore_model``
        sniffs SameDiff archives (zip with a ``graph.json`` entry) and
        MLN/graph zips alike — the one restore entry point
        FaultTolerantTrainer resume and the serving registry share
        (ADVICE.md: SameDiff resumes used to fall into
        restore_multi_layer_network and fail confusingly)."""
        with telemetry.span("checkpoint.load", path=str(cp)):
            t0 = time.perf_counter()
            model = ModelSerializer.restore_model(cp)
            if telemetry.enabled():
                telemetry.histogram(
                    "dl4j_checkpoint_load_seconds",
                    "checkpoint restore time (seconds)").observe(
                        time.perf_counter() - t0)
                telemetry.counter(
                    "dl4j_checkpoint_bytes_total",
                    "checkpoint bytes moved, by op").inc(
                        Path(cp).stat().st_size, op="load")
        return model

    @staticmethod
    def load_checkpoint(save_dir_or_path, *, skip_corrupt: bool = True):
        """Load the newest loadable checkpoint (reference:
        loadCheckpointMLN/loadLastCheckpointMLN). With ``skip_corrupt``
        a truncated/partial newest file falls back to the previous one
        — the §5.3 crash-recovery path. Dispatches on the zip format:
        MLN/ComputationGraph and SameDiff checkpoints both load."""
        p = Path(save_dir_or_path)
        candidates = ([p] if p.is_file()
                      else list(reversed(
                          CheckpointListener.available_checkpoints(p))))
        last_err = None
        for cp in candidates:
            try:
                return CheckpointListener._restore_any(cp)
            except Exception as e:            # corrupt / partial file
                last_err = e
                if not skip_corrupt:
                    raise
                log.warning("skipping unreadable checkpoint %s: %s",
                            cp, e)
        if last_err is not None:
            raise last_err
        return None


class _ResumableCheckpointListener(CheckpointListener):
    """CheckpointListener that writes a ``checkpoint_N.meta.json``
    sidecar per save recording how deep into the current epoch the
    snapshot is, so a resumed :class:`FaultTolerantTrainer` skips
    exactly the batches already trained instead of replaying the
    interrupted epoch (the loss-trajectory-continuity requirement of
    the chaos harness)."""

    def __init__(self, trainer, save_dir, **kw):
        super().__init__(save_dir, **kw)
        self._trainer = trainer

    def _save(self, model):
        ckpt_idx = self._counter     # the index _save is about to use
        super()._save(model)
        t = self._trainer
        meta = {
            "iteration_count": int(model.iteration_count),
            "epoch_count": int(model.epoch_count),
            "iters_into_epoch": int(max(
                model.iteration_count - t._epoch_start_iter, 0)),
        }
        # atomic like the checkpoint itself; written AFTER the zip is
        # submitted so the worker's rotate never races a half sidecar
        tmp = self.dir / f".checkpoint_{ckpt_idx}.meta.json.tmp"
        tmp.write_text(json.dumps(meta))
        os.replace(tmp, self.dir / f"checkpoint_{ckpt_idx}.meta.json")

    def _rotate(self):
        super()._rotate()
        # drop sidecars whose checkpoint was rotated away
        for mp in self.dir.glob("checkpoint_*.meta.json"):
            zp = mp.with_name(mp.name.replace(".meta.json", ".zip"))
            if not zp.exists():
                try:
                    mp.unlink()
                except OSError:
                    pass

    @staticmethod
    def read_meta(checkpoint_path: Optional[Path]) -> Optional[dict]:
        if checkpoint_path is None:
            return None
        mp = Path(checkpoint_path).with_name(
            Path(checkpoint_path).stem + ".meta.json")
        if not mp.exists():
            return None
        try:
            return json.loads(mp.read_text())
        except (OSError, ValueError):
            return None


class FaultTolerantTrainer:
    """Resumable, preemption-tolerant training loop (SURVEY.md §5.3;
    ROADMAP item 4). Restores the newest loadable checkpoint at
    construction; ``fit`` then trains with periodic atomic checkpoints
    and three fault-tolerance behaviors on top:

    - **preemption capture**: a SIGTERM (``common.faults``) is caught
      as a flag, the current step finishes, one final checkpoint is
      made durable, and :class:`~deeplearning4j_tpu.common.faults.
      TrainingPreempted` is raised — re-running the same command
      resumes with nothing lost;
    - **auto-resume**: any other training failure triggers a
      supervised in-process retry (``DL4J_TPU_RESUME_RETRIES`` /
      ``DL4J_TPU_RESUME_BACKOFF``, capped exponential backoff) from
      the newest VALID checkpoint — a torn/corrupt newest file is
      skipped;
    - **exact mid-epoch resume** (MLN/ComputationGraph): a
      ``checkpoint_N.meta.json`` sidecar records the batch offset into
      the epoch, and the resumed loop skips exactly those batches.
      SameDiff models fall back to whole-epoch resume granularity
      (their fit owns the epoch loop).

    Usage::

        trainer = FaultTolerantTrainer(lambda: build_net(), "ckpts",
                                       save_every_n_iterations=100)
        trainer.fit(train_iter, n_epochs=10)   # safe to re-run after
                                               # a crash: it resumes

    Note the trainer drives the epoch/batch loop itself for MLN/graph
    models (batch-at-a-time ``model.fit(ds)``), so extra listeners
    should be attached to ``trainer.model`` AFTER construction and are
    re-attached on in-process resume only if registered via
    :meth:`add_listeners`.
    """

    def __init__(self, model_factory, save_dir, *,
                 save_every_n_iterations: int = 0,
                 save_every_n_epochs: int = 1,
                 keep_last: int = 3, asynchronous: bool = True):
        self.save_dir = Path(save_dir)
        self._factory = model_factory
        self._extra_listeners: List = []
        restored, cp_path = self._load_newest()
        self.model = restored if restored is not None \
            else model_factory()
        self.resumed = restored is not None
        self._skip_batches = 0
        self._epoch_start_iter = self.model.iteration_count
        if self.resumed:
            faults.note_resume("restart")
            self._apply_resume_meta(cp_path)
        self._listener = _ResumableCheckpointListener(
            self, self.save_dir,
            save_every_n_iterations=save_every_n_iterations,
            save_every_n_epochs=save_every_n_epochs,
            keep_last=keep_last, asynchronous=asynchronous)
        # continue numbering after existing checkpoints
        self._listener.resume_numbering()
        self.model.add_listeners(self._listener)
        # SIGTERM becomes a cooperative flag checked at step boundaries
        self._guard = faults.install_preemption_capture()

    # ------------------------------------------------------------------
    def add_listeners(self, *listeners):
        """Attach extra listeners that survive in-process resume (the
        resume replaces ``self.model`` with a restored instance)."""
        self._extra_listeners.extend(listeners)
        self.model.add_listeners(*listeners)
        return self

    def _load_newest(self):
        """(model, path) of the newest LOADABLE checkpoint — corrupt/
        torn files are skipped with a warning; (None, None) when the
        dir has nothing loadable."""
        for cp in reversed(
                CheckpointListener.available_checkpoints(self.save_dir)):
            try:
                return CheckpointListener._restore_any(cp), cp
            except Exception as e:        # corrupt / partial file
                log.warning("skipping unreadable checkpoint %s: %s",
                            cp, e)
        return None, None

    def _apply_resume_meta(self, cp_path):
        """Set the mid-epoch batch skip from the checkpoint's sidecar
        (only when the sidecar matches the restored counters — a
        fallback past a torn newest file resumes at epoch
        granularity)."""
        meta = _ResumableCheckpointListener.read_meta(cp_path)
        self._skip_batches = 0
        if meta and int(meta.get("iteration_count", -1)) == \
                self.model.iteration_count:
            self._skip_batches = max(
                int(meta.get("iters_into_epoch", 0)), 0)
        self._epoch_start_iter = (self.model.iteration_count
                                  - self._skip_batches)

    # ------------------------------------------------------------------
    def fit(self, data, *, n_epochs: int = 1):
        """Train until ``n_epochs`` TOTAL epochs are done — a resumed
        job runs only the remaining epochs, so crash + re-run converges
        to the same amount of training as an uncrashed run.  Failures
        are retried in-process from the newest valid checkpoint; a
        captured preemption exits via :class:`TrainingPreempted` after
        a final durable checkpoint."""
        attempt = 0
        while True:
            try:
                return self._fit_once(data, n_epochs)
            except faults.TrainingPreempted:
                raise
            except Exception as e:       # noqa: BLE001 — supervised
                attempt += 1
                retries = faults.resume_retries()
                if attempt > retries:
                    raise
                delay = faults.resume_backoff(attempt)
                log.warning(
                    "training attempt failed (%r); resuming from the "
                    "newest checkpoint in %.1fs (retry %d/%d)",
                    e, delay, attempt, retries)
                if delay > 0:
                    time.sleep(delay)
                self._resume_from_disk()

    def _fit_once(self, data, n_epochs: int):
        m = self.model
        if n_epochs - m.epoch_count <= 0:
            log.info("fit: %d epochs already done, nothing to do",
                     m.epoch_count)
            return m
        if callable(getattr(m, "_fit_batch", None)) or \
                callable(getattr(m, "_fit_dataset", None)):
            self._fit_epochs(m, data, n_epochs)
        else:
            # SameDiff-style models own their epoch loop: whole-epoch
            # resume granularity, preemption checked between epochs
            m.fit(data, n_epochs=n_epochs - m.epoch_count)
            if faults.preemption_requested():
                self._preempt_exit(m)
        # final checkpoint — skipped when the epoch-end listener just
        # saved this exact state (don't burn a rotation slot on a dup)
        state = (m.iteration_count, m.epoch_count)
        if getattr(self._listener, "_last_saved_state", None) != state:
            self._listener._save(m)
        self._listener.flush()   # checkpoints durable before return
        return m

    def _fit_epochs(self, m, data, n_epochs: int):
        """Trainer-driven epoch/batch loop for MLN/ComputationGraph —
        mirrors ``model.fit(iterator)`` (listener order, epoch-count
        bump before ``on_epoch_end``) but trains one batch per
        ``model.fit(ds)`` call so preemption is checked and the resume
        sidecar stays exact at every step boundary."""
        while m.epoch_count < n_epochs:
            skip, self._skip_batches = self._skip_batches, 0
            for lis in m.listeners:
                lis.on_epoch_start(m)
            if hasattr(data, "reset"):
                data.reset()
            self._epoch_start_iter = m.iteration_count - skip
            if skip:
                log.info("resuming mid-epoch: skipping %d already-"
                         "trained batches of epoch %d", skip,
                         m.epoch_count)
            for i, ds in enumerate(data):
                if i < skip:
                    continue     # trained before the failure
                m.fit(ds)
                if faults.preemption_requested():
                    self._preempt_exit(m)
            if hasattr(m, "flush_accumulated"):
                m.flush_accumulated()
            m.epoch_count += 1
            # the new epoch starts AFTER the bump: an epoch-end save's
            # sidecar must say iters_into_epoch=0
            self._epoch_start_iter = m.iteration_count
            for lis in m.listeners:
                lis.on_epoch_end(m)
            if faults.preemption_requested():
                self._preempt_exit(m)

    def _preempt_exit(self, m):
        """Coordinated final snapshot + clean resumable exit."""
        state = (m.iteration_count, m.epoch_count)
        if getattr(self._listener, "_last_saved_state", None) != state:
            self._listener._save(m)
        self._listener.flush()
        cm = faults.chaos_monkey()
        if cm is not None:
            cm.maybe_tear(self.save_dir)     # chaos: torn final file
        log.warning("preemption captured at iteration %d (epoch %d); "
                    "final checkpoint durable in %s", state[0],
                    state[1], self.save_dir)
        raise faults.TrainingPreempted(
            f"preempted at iteration {state[0]} (epoch {state[1]}); "
            f"resumable from {self.save_dir}")

    def _resume_from_disk(self):
        """In-process resume: reload the newest valid checkpoint (or a
        fresh model if nothing is loadable), re-attach listeners, and
        account the lost steps."""
        try:
            self._listener.flush()
        except Exception as e:    # noqa: BLE001 — part of the failure
            log.warning("in-flight checkpoint write failed during "
                        "resume: %r", e)
        it_before = getattr(self.model, "iteration_count", 0)
        restored, cp_path = self._load_newest()
        if restored is None:
            log.warning("no loadable checkpoint in %s; restarting "
                        "from a fresh model", self.save_dir)
            restored = self._factory()
        self.model = restored
        faults.note_resume(
            "inprocess",
            lost_steps=max(it_before - restored.iteration_count, 0))
        self._apply_resume_meta(cp_path)
        self._listener.resume_numbering()
        self.model.add_listeners(self._listener,
                                 *self._extra_listeners)


class MultiHostCheckpointManager:
    """Save/resume discipline for a multi-process (jax.distributed)
    world — SURVEY.md §5.4's "async multi-host checkpointing"
    prescription, which the reference's Spark masters get from the
    driver being the single writer.

    Discipline: params are replicated-identical on every process by
    construction (exact synchronous DP — the in-step collectives mean
    no process's step completes before its peers'), so exactly ONE
    process (index 0) writes; a named barrier per ``save`` keeps the
    world aligned on HOW MANY checkpoints exist, and :meth:`flush`
    barriers AFTER the write so no process proceeds believing a
    checkpoint exists before its atomic rename landed.  Resume loads
    the same bytes on ALL processes (shared filesystem, the TPU-pod
    norm)."""

    def __init__(self, save_dir, *, keep_last: int = 3,
                 asynchronous: bool = True):
        self.save_dir = Path(save_dir)
        self.listener = CheckpointListener(
            save_dir, keep_last=keep_last,
            asynchronous=asynchronous).resume_numbering()

    @staticmethod
    def _barrier(name: str):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(name)

    def save(self, model):
        """Barrier, then process 0 snapshots + writes (async).  The
        barrier name uses a manager-level counter that advances on
        EVERY process (the listener's write counter only moves on the
        writer, and barrier names must agree world-wide).

        A write failure on process 0 (disk full, permissions) must
        not become a whole-world hang: the error is held until AFTER
        the barrier, so peers proceed and process 0 raises visibly."""
        n = getattr(self, "_save_calls", 0)
        self._save_calls = n + 1
        err = None
        if jax.process_index() == 0:
            try:
                # listener._save's internal flush() can re-raise the
                # PREVIOUS write's failure — catch it here too
                self.listener._save(model)
            except Exception as e:    # noqa: BLE001 — re-raised below
                err = e
        self._barrier(f"dl4j_ckpt_save_{n}")
        if err is not None:
            raise err

    def flush(self):
        """Join process 0's in-flight write, then barrier so every
        process observes the checkpoint as durable.  As in ``save``,
        a writer-side failure surfaces after the barrier instead of
        deadlocking the world."""
        err = None
        if jax.process_index() == 0:
            try:
                self.listener.flush()
            except Exception as e:    # noqa: BLE001 — re-raised below
                err = e
        self._barrier("dl4j_ckpt_flush")
        if err is not None:
            raise err

    def restore_into(self, model) -> bool:
        """Load the newest loadable checkpoint on EVERY process and
        copy its state into ``model`` (params, persistent states,
        updater state, counters).  Returns True if restored."""
        self._barrier("dl4j_ckpt_restore")
        if not CheckpointListener.available_checkpoints(self.save_dir):
            return False
        restored = CheckpointListener.load_checkpoint(self.save_dir)
        if restored is None:
            return False
        if not model._initialized:
            model.init()
        model.params = restored.params
        model.states = restored.states
        model.updater_states = restored.updater_states
        model.iteration_count = restored.iteration_count
        model.epoch_count = restored.epoch_count
        return True


class MultiHostCheckpointListener(TrainingListener):
    """Epoch-cadence hook driving a :class:`MultiHostCheckpointManager`
    from inside a training loop — every process runs it (the barrier
    in ``save`` needs all of them), only process 0 writes."""

    def __init__(self, manager: MultiHostCheckpointManager,
                 save_every_n_epochs: int = 1):
        self.manager = manager
        self.n_epoch = max(1, int(save_every_n_epochs))

    def on_epoch_end(self, model):
        if model.epoch_count % self.n_epoch == 0:
            self.manager.save(model)
