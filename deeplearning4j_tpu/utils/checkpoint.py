"""CheckpointListener: periodic model saving with rotation.

Reference parity: ``org.deeplearning4j.optimize.listeners.
CheckpointListener`` (SURVEY.md D7, section 5.4): every N iterations /
epochs / minutes, keep-last / keep-every rotation.
"""
from __future__ import annotations

import time
from pathlib import Path
from typing import List, Optional

from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.utils.serializer import ModelSerializer


class CheckpointListener(TrainingListener):
    def __init__(self, save_dir, *, save_every_n_iterations: int = 0,
                 save_every_n_epochs: int = 0,
                 save_every_n_seconds: float = 0.0,
                 keep_last: int = 0, keep_every: int = 0):
        self.dir = Path(save_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.n_iter = save_every_n_iterations
        self.n_epoch = save_every_n_epochs
        self.n_seconds = save_every_n_seconds
        self.keep_last = keep_last
        self.keep_every = keep_every
        self._last_save_time = time.time()
        self._saved: List[Path] = []
        self._counter = 0

    def _save(self, model):
        path = self.dir / f"checkpoint_{self._counter}.zip"
        ModelSerializer.write_model(model, path)
        self._counter += 1
        self._saved.append(path)
        self._rotate()

    def _rotate(self):
        if self.keep_last <= 0:
            return
        keep: set = set(self._saved[-self.keep_last:])
        if self.keep_every > 0:
            for i, p in enumerate(self._saved):
                if i % self.keep_every == 0:
                    keep.add(p)
        for p in self._saved:
            if p not in keep and p.exists():
                p.unlink()
        self._saved = [p for p in self._saved if p in keep or p.exists()]

    def iteration_done(self, model, iteration: int, epoch: int):
        if self.n_iter > 0 and (iteration + 1) % self.n_iter == 0:
            self._save(model)
        elif self.n_seconds > 0 and \
                time.time() - self._last_save_time >= self.n_seconds:
            self._save(model)
            self._last_save_time = time.time()

    def on_epoch_end(self, model):
        if self.n_epoch > 0 and (model.epoch_count + 1) % self.n_epoch == 0:
            self._save(model)

    def last_checkpoint(self) -> Optional[Path]:
        return self._saved[-1] if self._saved else None
