"""Numeric gradient checking (SURVEY.md §4.5:
`org.deeplearning4j.gradientcheck.GradientCheckUtil`).

Central-difference numeric gradients vs the analytic gradients the
jitted train path computes, parameter-by-parameter. Like the
reference, the check runs in DOUBLE precision — `jax.experimental.
enable_x64` scopes f64 to the check (training itself stays f32/bf16)
— so tolerances stay tight and f32 loss quantization can't mask or
fake a mismatch. What it validates: that every layer's backward
composition matches its forward (wrong masking, stop-gradients,
state handling...).
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def _net_loss_fn(net, ds):
    """loss(params) for a MultiLayerNetwork/ComputationGraph on one
    batch, deterministic (no dropout rng, training-mode forward)."""
    multi = hasattr(net, "conf") and hasattr(net.conf, "layers")

    if multi:
        x = jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels)
        out_layer = net.output_layer_conf

        def loss(params):
            out, _ = net._forward(params, net.states, x,
                                  training=True, rng=None,
                                  want_logits=out_layer.wants_logits())
            return (out_layer.compute_loss(
                y, out, from_logits=out_layer.wants_logits())
                + net._regularization(params))
        return loss

    xs = [jnp.asarray(f) for f in (ds.features if isinstance(
        ds.features, list) else [ds.features])]
    ys = [jnp.asarray(l) for l in (ds.labels if isinstance(
        ds.labels, list) else [ds.labels])]
    out_confs = net.output_layer_confs()

    def loss(params):
        acts, _ = net._forward(params, net.states, xs, training=True,
                               rng=None, want_logits=True)
        total = net._regularization(params)
        for i, name in enumerate(net.conf.network_outputs):
            layer = out_confs.get(name)
            if layer is None:
                continue
            total = total + layer.compute_loss(
                ys[i], acts[name], from_logits=layer.wants_logits())
        return total
    return loss


class GradientCheckUtil:
    @staticmethod
    def check_gradients(net, ds, epsilon: float = 1e-5,
                        max_rel_error: float = 1e-4,
                        min_abs_error: float = 1e-8,
                        max_params_per_array: int = 16,
                        seed: int = 0,
                        print_results: bool = False) -> bool:
        """True iff every sampled parameter's numeric gradient matches
        the analytic one (relative error under ``max_rel_error``, with
        ``min_abs_error`` absorbing float32 noise near zero).

        ``max_params_per_array`` random entries are checked per
        parameter tensor (sampling keeps runtime sane with identical
        detection power for systematic backward bugs)."""
        x64 = getattr(jax, "enable_x64", None)
        if x64 is None:                      # older jax spelling
            from jax.experimental import enable_x64 as x64
        with x64():
            return GradientCheckUtil._check_f64(
                net, ds, epsilon, max_rel_error, min_abs_error,
                max_params_per_array, seed, print_results)

    @staticmethod
    def _check_f64(net, ds, epsilon, max_rel_error, min_abs_error,
                   max_params_per_array, seed, print_results) -> bool:
        f64 = lambda t: jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a, np.float64))
            if np.issubdtype(np.asarray(a).dtype, np.floating) else a,
            t)
        params64 = f64(net.params)
        states_save = net.states
        cd_save = net.conf.compute_dtype
        try:
            net.states = f64(net.states)
            # mixed precision must be OFF for the check: _forward
            # would cast the promoted f64 values back down to bf16,
            # reducing the comparison to bf16 rounding noise
            net.conf.compute_dtype = None
            from deeplearning4j_tpu.parallel.mesh import \
                map_dataset_arrays

            def to64(a):
                a = np.asarray(a)
                return a.astype(np.float64) if np.issubdtype(
                    a.dtype, np.floating) else a
            ds = map_dataset_arrays(ds, to64)
            loss_fn = _net_loss_fn(net, ds)
            analytic = jax.grad(loss_fn)(params64)
            rng = np.random.RandomState(seed)
            flat_p, treedef = jax.tree_util.tree_flatten(params64)
            flat_g = jax.tree_util.tree_leaves(analytic)
            failures = []
            checked = 0
            for ai, (p, g) in enumerate(zip(flat_p, flat_g)):
                p_np = np.asarray(p, np.float64)
                g_np = np.asarray(g, np.float64)
                n = p_np.size
                idxs = (range(n) if n <= max_params_per_array else
                        rng.choice(n, max_params_per_array,
                                   replace=False))
                for flat_i in idxs:
                    delta = np.zeros_like(p_np).reshape(-1)
                    delta[flat_i] = epsilon
                    delta = delta.reshape(p_np.shape)

                    def at(offset):
                        newp = jax.tree_util.tree_unflatten(
                            treedef, [jnp.asarray(p_np + offset)
                                      if j == ai else q
                                      for j, q in enumerate(flat_p)])
                        return float(loss_fn(newp))

                    numeric = (at(delta) - at(-delta)) / (2 * epsilon)
                    ana = g_np.reshape(-1)[flat_i]
                    abs_err = abs(numeric - ana)
                    denom = max(abs(numeric), abs(ana))
                    rel = abs_err / denom if denom > 0 else 0.0
                    checked += 1
                    if rel > max_rel_error and abs_err > min_abs_error:
                        failures.append((ai, int(flat_i), float(ana),
                                         float(numeric), float(rel)))
        finally:
            net.states = states_save
            net.conf.compute_dtype = cd_save
        if print_results or failures:
            print(f"GradientCheckUtil: {checked} params checked, "
                  f"{len(failures)} failures")
            for f in failures[:10]:
                print("  array %d idx %d analytic %.6g numeric %.6g "
                      "rel %.3g" % f)
        return not failures
