from deeplearning4j_tpu.utils.serializer import ModelSerializer  # noqa: F401
from deeplearning4j_tpu.utils.checkpoint import (  # noqa: F401
    CheckpointListener, FaultTolerantTrainer,
    MultiHostCheckpointListener, MultiHostCheckpointManager)
