"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up rebuild of the Deeplearning4j ecosystem's capabilities
(reference: ``hilo1988/deeplearning4j``: ND4J ndarray + SameDiff autodiff +
DL4J ``MultiLayerNetwork``/``ComputationGraph`` + DataVec ETL + distributed
training) designed TPU-first on JAX/XLA:

- ndarray + op layer   -> :mod:`deeplearning4j_tpu.ndarray`, :mod:`deeplearning4j_tpu.ops`
  (reference: nd4j ``org.nd4j.linalg.api.ndarray.INDArray`` / ``Nd4j``)
- autodiff graph layer -> :mod:`deeplearning4j_tpu.autodiff`
  (reference: ``org.nd4j.autodiff.samediff.SameDiff``)
- NN API               -> :mod:`deeplearning4j_tpu.nn`
  (reference: ``org.deeplearning4j.nn.multilayer.MultiLayerNetwork``,
  ``org.deeplearning4j.nn.graph.ComputationGraph``)
- data/ETL             -> :mod:`deeplearning4j_tpu.datasets`, :mod:`deeplearning4j_tpu.datavec`
- distributed          -> :mod:`deeplearning4j_tpu.parallel`
  (reference: ``ParallelWrapper`` / Spark ``SharedTrainingMaster`` -> XLA
  collectives over ICI/DCN via jax.sharding)
- model zoo            -> :mod:`deeplearning4j_tpu.models`

Design stance (SURVEY.md section 7): functional core with a mutable facade.
All compute compiles through XLA; there are no hand-written kernels except
Pallas where XLA underperforms. Memory is XLA-owned (donation instead of
workspaces); updaters are pure functions over optimizer-state pytrees.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.common.dtypes import DataType  # noqa: F401
