"""Training listeners: the metric/observability spine of the train loop.

Reference parity: ``org.deeplearning4j.optimize.api.TrainingListener`` and
impls ``ScoreIterationListener``, ``PerformanceListener``,
``CollectScoresListener`` (SURVEY.md D7, section 5.5). CheckpointListener
lives in utils alongside the serializer.
"""
from __future__ import annotations

import logging
import time

log = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    def iteration_done(self, model, iteration: int, epoch: int):
        pass

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def on_forward_pass(self, model, activations):
        pass

    def on_gradient_calculation(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference: same name).

    Emits through the ``deeplearning4j_tpu`` logger only; pass
    ``stdout=True`` to ALSO print (the old behavior double-emitted
    every message via both channels, spamming production stdout)."""

    def __init__(self, print_iterations: int = 10, *,
                 stdout: bool = False):
        self.print_iterations = max(1, int(print_iterations))
        self.stdout = stdout

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration,
                     model.score())
            if self.stdout:
                print(f"Score at iteration {iteration} is "
                      f"{model.score()}")


class PerformanceListener(TrainingListener):
    """Throughput/iteration-time sampling (reference: same name).

    Logs only, like :class:`ScoreIterationListener`; ``stdout=True``
    opts into printing as well."""

    def __init__(self, frequency: int = 10, report_samples: bool = True,
                 *, stdout: bool = False):
        self.frequency = max(1, int(frequency))
        self.report_samples = report_samples
        self.stdout = stdout
        self._last_time = None
        self._last_iter = None
        self._examples = 0

    def iteration_done(self, model, iteration, epoch):
        now = time.perf_counter()
        batch = getattr(model, "last_batch_size", None) or 0
        self._examples += batch
        if iteration % self.frequency == 0:
            if self._last_time is not None:
                dt = now - self._last_time
                iters = iteration - self._last_iter
                msg = (f"iteration {iteration}: {iters / dt:.2f} iters/sec"
                       + (f", {self._examples / dt:.1f} samples/sec"
                          if self.report_samples else ""))
                log.info(msg)
                if self.stdout:
                    print(msg)
            self._last_time = now
            self._last_iter = iteration
            self._examples = 0


class EvaluativeListener(TrainingListener):
    """Evaluate on a held-out iterator every N iterations (reference:
    org.deeplearning4j.optimize.listeners.EvaluativeListener with
    InvocationType.ITERATION_END). Results accumulate in
    ``self.evaluations`` as (iteration, Evaluation) pairs; a
    ``callback(iteration, evaluation)`` hook fires per run."""

    def __init__(self, iterator, frequency: int = 10, callback=None):
        if not (hasattr(iterator, "reset") or
                hasattr(iterator, "features") or
                isinstance(iterator, (list, tuple))):
            iterator = list(iterator)   # one-shot iterable: keep it
        self.iterator = iterator
        self.frequency = max(1, int(frequency))
        self.callback = callback
        self.evaluations = []

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.frequency:
            return
        from deeplearning4j_tpu.evaluation import Evaluation
        e = Evaluation()
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        data = self.iterator
        if hasattr(data, "features"):          # single DataSet
            data = [data]
        for ds in data:
            fmask = getattr(ds, "features_mask", None)
            out = (model.output(ds.features, mask=fmask)
                   if fmask is not None else model.output(ds.features))
            if isinstance(out, (list, tuple)):
                out = out[0]
            e.eval(ds.labels, out,
                   mask=getattr(ds, "labels_mask", None))
        self.evaluations.append((iteration, e))
        log.info("Evaluation at iteration %d: accuracy %.4f", iteration,
                 e.accuracy())
        if self.callback is not None:
            self.callback(iteration, e)


class CollectScoresListener(TrainingListener):
    """Collect (iteration, score) pairs in memory (reference: same name)."""

    def __init__(self):
        self.scores: list[tuple[int, float]] = []

    def iteration_done(self, model, iteration, epoch):
        self.scores.append((iteration, model.score()))
