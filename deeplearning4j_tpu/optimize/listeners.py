"""Training listeners: the metric/observability spine of the train loop.

Reference parity: ``org.deeplearning4j.optimize.api.TrainingListener`` and
impls ``ScoreIterationListener``, ``PerformanceListener``,
``CollectScoresListener`` (SURVEY.md D7, section 5.5). CheckpointListener
lives in utils alongside the serializer.
"""
from __future__ import annotations

import logging
import time

log = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    def iteration_done(self, model, iteration: int, epoch: int):
        pass

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def on_forward_pass(self, model, activations):
        pass

    def on_gradient_calculation(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (reference: same name)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, int(print_iterations))

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration,
                     model.score())
            print(f"Score at iteration {iteration} is {model.score()}")


class PerformanceListener(TrainingListener):
    """Throughput/iteration-time sampling (reference: same name)."""

    def __init__(self, frequency: int = 10, report_samples: bool = True):
        self.frequency = max(1, int(frequency))
        self.report_samples = report_samples
        self._last_time = None
        self._last_iter = None
        self._examples = 0

    def iteration_done(self, model, iteration, epoch):
        now = time.perf_counter()
        batch = getattr(model, "last_batch_size", None) or 0
        self._examples += batch
        if iteration % self.frequency == 0:
            if self._last_time is not None:
                dt = now - self._last_time
                iters = iteration - self._last_iter
                msg = (f"iteration {iteration}: {iters / dt:.2f} iters/sec"
                       + (f", {self._examples / dt:.1f} samples/sec"
                          if self.report_samples else ""))
                log.info(msg)
                print(msg)
            self._last_time = now
            self._last_iter = iteration
            self._examples = 0


class CollectScoresListener(TrainingListener):
    """Collect (iteration, score) pairs in memory (reference: same name)."""

    def __init__(self):
        self.scores: list[tuple[int, float]] = []

    def iteration_done(self, model, iteration, epoch):
        self.scores.append((iteration, model.score()))
