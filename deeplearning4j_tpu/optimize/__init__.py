from deeplearning4j_tpu.optimize.listeners import (  # noqa: F401
    TrainingListener, ScoreIterationListener, PerformanceListener,
    CollectScoresListener)
