from deeplearning4j_tpu.ndarray.ndarray import INDArray  # noqa: F401
from deeplearning4j_tpu.ndarray.factory import Nd4j  # noqa: F401
