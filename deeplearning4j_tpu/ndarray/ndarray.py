"""INDArray: a mutable-facade ndarray over an immutable XLA substrate.

Reference parity: ``org.nd4j.linalg.api.ndarray.INDArray`` /
``BaseNDArray`` (SURVEY.md J1) — the reference API is deeply in-place
(``subi``/``addi``, views aliasing parent buffers). SURVEY.md section 7 ranks
reproducing those semantics on a functional substrate as hard part #1; the
design chosen here:

- A *base* array owns ``_data`` (a jax array). In-place methods compute a new
  functional value and **rebind** ``_data`` — O(1) bookkeeping, XLA reuses
  buffers via donation when jitted.
- A *view* holds ``(_parent, _index)`` and no buffer. Reads re-slice the
  parent lazily (an XLA slice, fused under jit); in-place writes write
  through with ``parent.at[index].set(...)``, recursing to the base. This
  reproduces DL4J's aliasing: mutate the view, the parent sees it — and vice
  versa — without a mutable buffer anywhere.
- Documented divergence: ``reshape``/``transpose``/``broadcast`` return
  fresh base arrays (the reference sometimes returns strided views). Aliasing
  is guaranteed only for basic-indexing views (``__getitem__``, ``get_row``,
  ``slice_view``...), which covers the reference's dominant uses (param/grad
  views, row/column updates).

Every op funnels through :class:`OpExecutioner` for profiling/NaN-panic
parity with ``DefaultOpExecutioner``.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common.dtypes import DataType, to_jnp_dtype
from deeplearning4j_tpu.ops.executioner import OpExecutioner

_exec = OpExecutioner.exec


def _unwrap(x):
    if isinstance(x, INDArray):
        return x.data
    return x


class INDArray:
    """Dense tensor facade. See module docstring for the aliasing model."""

    __slots__ = ("_data", "_parent", "_index")
    __array_priority__ = 100  # beat numpy in mixed dunder dispatch

    def __init__(self, data=None, *, _parent: "INDArray | None" = None,
                 _index=None):
        if _parent is not None:
            self._parent = _parent
            self._index = _index
            self._data = None
        else:
            self._parent = None
            self._index = None
            self._data = jnp.asarray(data)

    # -- buffer plumbing ------------------------------------------------
    @property
    def is_view(self) -> bool:
        return self._parent is not None

    @property
    def data(self) -> jax.Array:
        """The current functional value (jax array)."""
        if self._parent is not None:
            return self._parent.data[self._index]
        return self._data

    def _write(self, value: jax.Array):
        """Rebind (base) or write-through (view)."""
        if self._parent is not None:
            parent_val = self._parent.data
            new_parent = parent_val.at[self._index].set(
                jnp.asarray(value, parent_val.dtype))
            self._parent._write(new_parent)
        else:
            self._data = jnp.asarray(value)

    # -- basic properties ----------------------------------------------
    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.data.shape)

    @property
    def rank(self) -> int:
        return self.data.ndim

    @property
    def ndim(self) -> int:
        return self.data.ndim

    def length(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def size(self, dim: int) -> int:
        return self.shape[dim]

    def data_type(self) -> DataType:
        return DataType.from_any(self.data.dtype)

    @property
    def dtype(self):
        return self.data.dtype

    def is_scalar(self) -> bool:
        return self.data.ndim == 0 or self.length() == 1

    def is_vector(self) -> bool:
        s = [d for d in self.shape if d != 1]
        return self.rank <= 2 and len(s) <= 1

    def is_matrix(self) -> bool:
        return self.rank == 2

    def is_empty(self) -> bool:
        return self.length() == 0

    def rows(self) -> int:
        return self.shape[0]

    def columns(self) -> int:
        return self.shape[1]

    # -- conversion -----------------------------------------------------
    def jax(self) -> jax.Array:
        return self.data

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def __array__(self, dtype=None):
        a = np.asarray(self.data)
        return a.astype(dtype) if dtype is not None else a

    def get_double(self, *idx) -> float:
        return float(self.data[tuple(idx)] if idx else self.data.reshape(-1)[0])

    def get_int(self, *idx) -> int:
        return int(self.data[tuple(idx)] if idx else self.data.reshape(-1)[0])

    def item(self):
        return self.data.reshape(()).item() if self.length() == 1 else \
            self.to_numpy()

    def cast_to(self, dtype) -> "INDArray":
        return INDArray(self.data.astype(to_jnp_dtype(dtype)))

    def astype(self, dtype) -> "INDArray":
        return self.cast_to(dtype)

    # -- copies / assignment --------------------------------------------
    def dup(self) -> "INDArray":
        return INDArray(self.data)

    def assign(self, other) -> "INDArray":
        val = jnp.broadcast_to(jnp.asarray(_unwrap(other), self.dtype),
                               self.shape)
        self._write(val)
        return self

    def put_scalar(self, idx, value) -> "INDArray":
        if not isinstance(idx, (tuple, list)):
            idx = (idx,)
        self._write(self.data.at[tuple(int(i) for i in idx)].set(value))
        return self

    def put(self, idx, value) -> "INDArray":
        self._write(self.data.at[idx].set(jnp.asarray(_unwrap(value))))
        return self

    # -- views ----------------------------------------------------------
    def __getitem__(self, idx) -> "INDArray":
        return INDArray(_parent=self, _index=idx)

    def __setitem__(self, idx, value):
        self._write(self.data.at[idx].set(jnp.asarray(_unwrap(value))))

    def get_row(self, i: int) -> "INDArray":
        return self[i]

    def get_column(self, j: int) -> "INDArray":
        return self[:, j]

    def get_rows(self, rows: Sequence[int]) -> "INDArray":
        return INDArray(self.data[jnp.asarray(list(rows))])

    def get_columns(self, cols: Sequence[int]) -> "INDArray":
        return INDArray(self.data[:, jnp.asarray(list(cols))])

    def slice_view(self, i: int, dim: int = 0) -> "INDArray":
        idx = (slice(None),) * dim + (i,)
        return INDArray(_parent=self, _index=idx)

    def tensor_along_dimension(self, i: int, *dims: int) -> "INDArray":
        """TAD (SURVEY.md N2): the i-th sub-tensor spanning ``dims``."""
        dims = sorted(d % self.rank for d in dims)
        other = [d for d in range(self.rank) if d not in dims]
        # index i enumerates the coordinates over `other` dims, C-order
        osh = [self.shape[d] for d in other]
        coords = np.unravel_index(i, osh) if osh else ()
        idx: list[Any] = [slice(None)] * self.rank
        for d, c in zip(other, coords):
            idx[d] = int(c)
        return INDArray(_parent=self, _index=tuple(idx))

    def tensors_along_dimension(self, *dims: int) -> int:
        dims_ = sorted(d % self.rank for d in dims)
        other = [d for d in range(self.rank) if d not in dims_]
        return int(np.prod([self.shape[d] for d in other])) if other else 1

    # -- shape ops (return fresh base arrays; documented divergence) ----
    def reshape(self, *shape) -> "INDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return INDArray(_exec("reshape", jnp.reshape, self.data,
                              tuple(int(s) for s in shape)))

    def ravel(self) -> "INDArray":
        return self.reshape(-1)

    def flatten(self) -> "INDArray":
        return self.reshape(-1)

    def transpose(self, *axes) -> "INDArray":
        axes = axes or None
        if axes and len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return INDArray(_exec("transpose", jnp.transpose, self.data, axes))

    def permute(self, *axes) -> "INDArray":
        return self.transpose(*axes)

    def swap_axes(self, a: int, b: int) -> "INDArray":
        return INDArray(jnp.swapaxes(self.data, a, b))

    def broadcast(self, *shape) -> "INDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return INDArray(jnp.broadcast_to(self.data, shape))

    def repeat(self, repeats, axis=None) -> "INDArray":
        return INDArray(jnp.repeat(self.data, repeats, axis=axis))

    # -- elementwise math ------------------------------------------------
    def _bin(self, name, fn, other, inplace: bool):
        out = _exec(name, fn, self.data, jnp.asarray(_unwrap(other)))
        if inplace:
            # in-place ops cannot change the buffer dtype or shape
            # (mutable-buffer semantics): cast back, refuse to grow
            self._check_inplace_shape(name, out)
            self._write(out.astype(self.dtype))
            return self
        return INDArray(out)

    def _check_inplace_shape(self, name, out):
        if tuple(out.shape) != self.shape:
            raise ValueError(
                f"in-place op [{name}] would change array shape "
                f"{self.shape} -> {tuple(out.shape)}; a mutable buffer "
                f"cannot be resized (use the out-of-place variant)")

    def add(self, o): return self._bin("add", jnp.add, o, False)
    def addi(self, o): return self._bin("add", jnp.add, o, True)
    def sub(self, o): return self._bin("sub", jnp.subtract, o, False)
    def subi(self, o): return self._bin("sub", jnp.subtract, o, True)
    def mul(self, o): return self._bin("mul", jnp.multiply, o, False)
    def muli(self, o): return self._bin("mul", jnp.multiply, o, True)
    def div(self, o): return self._bin("div", jnp.divide, o, False)
    def divi(self, o): return self._bin("div", jnp.divide, o, True)

    def _rbin(self, name, fn, other, inplace: bool):
        out = _exec(name, fn, jnp.asarray(_unwrap(other)), self.data)
        if inplace:
            self._check_inplace_shape(name, out)
            self._write(out.astype(self.dtype))
            return self
        return INDArray(out)

    def rsub(self, o): return self._rbin("rsub", jnp.subtract, o, False)
    def rsubi(self, o): return self._rbin("rsub", jnp.subtract, o, True)
    def rdiv(self, o): return self._rbin("rdiv", jnp.divide, o, False)
    def rdivi(self, o): return self._rbin("rdiv", jnp.divide, o, True)

    def neg(self):
        return INDArray(_exec("neg", jnp.negative, self.data))

    def negi(self):
        self._write(_exec("neg", jnp.negative, self.data))
        return self

    def fmod(self, o): return self._bin("fmod", jnp.fmod, o, False)

    # -- matrix ops -------------------------------------------------------
    def mmul(self, other) -> "INDArray":
        return INDArray(_exec("mmul", jnp.matmul, self.data,
                              jnp.asarray(_unwrap(other))))

    def mmuli(self, other) -> "INDArray":
        out = _exec("mmul", jnp.matmul, self.data,
                    jnp.asarray(_unwrap(other)))
        self._check_inplace_shape("mmul", out)
        self._write(out)
        return self

    def dot(self, other) -> float:
        return float(jnp.vdot(self.data, jnp.asarray(_unwrap(other))))

    # -- python dunders ---------------------------------------------------
    def __add__(self, o): return self.add(o)
    def __radd__(self, o): return self.add(o)
    def __sub__(self, o): return self.sub(o)
    def __rsub__(self, o): return self.rsub(o)
    def __mul__(self, o): return self.mul(o)
    def __rmul__(self, o): return self.mul(o)
    def __truediv__(self, o): return self.div(o)
    def __rtruediv__(self, o): return self.rdiv(o)
    def __matmul__(self, o): return self.mmul(o)
    def __neg__(self): return self.neg()
    def __pow__(self, o): return self._bin("pow", jnp.power, o, False)
    def __abs__(self): return INDArray(_exec("abs", jnp.abs, self.data))

    def __bool__(self):
        # numpy-style: truth of a multi-element array is ambiguous.
        # Without this, Python falls back to __len__ and `if a == b:`
        # silently answers True for any non-empty comparison result.
        if self.length() != 1:
            raise ValueError(
                "The truth value of an INDArray with more than one element "
                "is ambiguous. Use .any()/.all()/.equals().")
        return bool(self.data.reshape(()))

    def any(self) -> bool:
        return bool(jnp.any(self.data))

    def all(self) -> bool:
        return bool(jnp.all(self.data))

    def __iadd__(self, o): return self.addi(o)
    def __isub__(self, o): return self.subi(o)
    def __imul__(self, o): return self.muli(o)
    def __itruediv__(self, o): return self.divi(o)

    # -- comparisons (bool arrays, reference eq/neq/gt/lt) ---------------
    def eq(self, o): return self._bin("eq", jnp.equal, o, False)
    def neq(self, o): return self._bin("neq", jnp.not_equal, o, False)
    def gt(self, o): return self._bin("gt", jnp.greater, o, False)
    def gte(self, o): return self._bin("gte", jnp.greater_equal, o, False)
    def lt(self, o): return self._bin("lt", jnp.less, o, False)
    def lte(self, o): return self._bin("lte", jnp.less_equal, o, False)

    def __eq__(self, o):  # array-valued, like the reference's eq()
        return self.eq(o)

    def __ne__(self, o):
        return self.neq(o)

    def __lt__(self, o): return self.lt(o)
    def __le__(self, o): return self.lte(o)
    def __gt__(self, o): return self.gt(o)
    def __ge__(self, o): return self.gte(o)

    def __hash__(self):
        return id(self)

    def equals(self, other, eps: float = 1e-5) -> bool:
        other = _unwrap(other)
        if tuple(jnp.shape(other)) != self.shape:
            return False
        if jnp.issubdtype(self.dtype, jnp.floating):
            return bool(jnp.allclose(self.data, other, atol=eps))
        return bool((self.data == other).all())

    def equal_shapes(self, other: "INDArray") -> bool:
        return self.shape == other.shape

    # -- reductions -------------------------------------------------------
    def _red(self, name, fn, dims, keep_dims=False, **kw):
        axis = None
        if dims:
            axis = tuple(d % self.rank for d in dims)
        out = _exec(name, fn, self.data, axis=axis, keepdims=keep_dims, **kw)
        return INDArray(out)

    def sum(self, *dims, keep_dims=False):
        return self._red("reduce_sum", jnp.sum, dims, keep_dims)

    def mean(self, *dims, keep_dims=False):
        return self._red("reduce_mean", jnp.mean, dims, keep_dims)

    def max(self, *dims, keep_dims=False):
        return self._red("reduce_max", jnp.max, dims, keep_dims)

    def min(self, *dims, keep_dims=False):
        return self._red("reduce_min", jnp.min, dims, keep_dims)

    def prod(self, *dims, keep_dims=False):
        return self._red("reduce_prod", jnp.prod, dims, keep_dims)

    def std(self, *dims, bias_corrected=True, keep_dims=False):
        return self._red("reduce_std", jnp.std, dims, keep_dims,
                         ddof=1 if bias_corrected else 0)

    def var(self, *dims, bias_corrected=True, keep_dims=False):
        return self._red("reduce_var", jnp.var, dims, keep_dims,
                         ddof=1 if bias_corrected else 0)

    def norm1(self, *dims, keep_dims=False):
        return self._red("reduce_norm1", lambda x, axis, keepdims:
                         jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims),
                         dims, keep_dims)

    def norm2(self, *dims, keep_dims=False):
        return self._red("reduce_norm2", lambda x, axis, keepdims:
                         jnp.sqrt(jnp.sum(x * x, axis=axis,
                                          keepdims=keepdims)),
                         dims, keep_dims)

    def norm_max(self, *dims, keep_dims=False):
        return self._red("reduce_normmax", lambda x, axis, keepdims:
                         jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims),
                         dims, keep_dims)

    def argmax(self, *dims) -> "INDArray":
        axis = dims[0] if dims else None
        return INDArray(_exec("argmax", jnp.argmax, self.data, axis=axis))

    def argmin(self, *dims) -> "INDArray":
        axis = dims[0] if dims else None
        return INDArray(_exec("argmin", jnp.argmin, self.data, axis=axis))

    def cumsum(self, dim: int = 0) -> "INDArray":
        return INDArray(_exec("cumsum", jnp.cumsum, self.data, axis=dim))

    def sum_number(self) -> float:
        return float(jnp.sum(self.data))

    def mean_number(self) -> float:
        return float(jnp.mean(self.data))

    def max_number(self) -> float:
        return float(jnp.max(self.data))

    def min_number(self) -> float:
        return float(jnp.min(self.data))

    # -- misc -------------------------------------------------------------
    def where(self, cond, other) -> "INDArray":
        return INDArray(jnp.where(jnp.asarray(_unwrap(cond)), self.data,
                                  jnp.asarray(_unwrap(other))))

    def __len__(self):
        return self.shape[0] if self.shape else 1

    def __repr__(self):
        kind = "view" if self.is_view else "base"
        return (f"INDArray({kind}, shape={self.shape}, "
                f"dtype={self.data_type().name},\n{np.asarray(self.data)})")

    def __str__(self):
        return str(np.asarray(self.data))
