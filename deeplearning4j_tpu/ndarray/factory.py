"""Nd4j: the static ndarray factory.

Reference parity: ``org.nd4j.linalg.factory.Nd4j`` (SURVEY.md J1) plus the
RNG surface of ``org.nd4j.linalg.api.rng`` (J12). TPU-first: randomness uses
JAX's splittable threefry keys behind a stateful facade (the reference keeps
stateful Philox streams; we expose the same ``get_random().set_seed`` API but
derive a fresh split per call, which is the idiomatic XLA-safe design).
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common.dtypes import DataType, to_jnp_dtype
from deeplearning4j_tpu.ndarray.ndarray import INDArray, _unwrap


class _Random:
    """Stateful facade over splittable JAX PRNG keys (reference: Nd4j RNG)."""

    def __init__(self, seed: int = 0):
        self._key = jax.random.PRNGKey(seed)
        self._seed = seed

    def set_seed(self, seed: int):
        self._key = jax.random.PRNGKey(int(seed))
        self._seed = int(seed)

    def get_seed(self) -> int:
        return self._seed

    def next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub


_random = _Random(0)


def _shape(args) -> tuple[int, ...]:
    if len(args) == 1 and isinstance(args[0], (tuple, list)):
        return tuple(int(s) for s in args[0])
    return tuple(int(s) for s in args)


class Nd4j:
    """Static factory — mirrors the reference's ``Nd4j`` entry point."""

    # -- RNG ------------------------------------------------------------
    @staticmethod
    def get_random() -> _Random:
        return _random

    # -- creation -------------------------------------------------------
    @staticmethod
    def create(data=None, *shape, dtype=None) -> INDArray:
        if data is None:
            return Nd4j.zeros(*shape, dtype=dtype)
        if isinstance(data, (int,)) and not shape:
            # Nd4j.create(n) -> zero vector of length n (reference behavior)
            return Nd4j.zeros(data, dtype=dtype)
        arr = jnp.asarray(_unwrap(data))
        if shape:
            arr = arr.reshape(_shape(shape))
        if dtype is not None:
            arr = arr.astype(to_jnp_dtype(dtype))
        return INDArray(arr)

    @staticmethod
    def zeros(*shape, dtype=None) -> INDArray:
        return INDArray(jnp.zeros(_shape(shape),
                                  to_jnp_dtype(dtype or "float32")))

    @staticmethod
    def ones(*shape, dtype=None) -> INDArray:
        return INDArray(jnp.ones(_shape(shape),
                                 to_jnp_dtype(dtype or "float32")))

    @staticmethod
    def zeros_like(a) -> INDArray:
        return INDArray(jnp.zeros_like(_unwrap(a)))

    @staticmethod
    def ones_like(a) -> INDArray:
        return INDArray(jnp.ones_like(_unwrap(a)))

    @staticmethod
    def value_array_of(shape, value, dtype=None) -> INDArray:
        return INDArray(jnp.full(_shape([shape]) if isinstance(
            shape, (tuple, list)) else (int(shape),), value,
            to_jnp_dtype(dtype or "float32")))

    @staticmethod
    def scalar(value, dtype=None) -> INDArray:
        return INDArray(jnp.asarray(value, to_jnp_dtype(dtype)
                                    if dtype else None))

    @staticmethod
    def eye(n: int, dtype=None) -> INDArray:
        return INDArray(jnp.eye(n, dtype=to_jnp_dtype(dtype or "float32")))

    @staticmethod
    def arange(*args, dtype=None) -> INDArray:
        return INDArray(jnp.arange(*args,
                                   dtype=to_jnp_dtype(dtype) if dtype else None))

    @staticmethod
    def linspace(start, stop, num, dtype=None) -> INDArray:
        return INDArray(jnp.linspace(start, stop, int(num),
                                     dtype=to_jnp_dtype(dtype or "float32")))

    # -- random ---------------------------------------------------------
    @staticmethod
    def rand(*shape, dtype=None) -> INDArray:
        return INDArray(jax.random.uniform(
            _random.next_key(), _shape(shape),
            to_jnp_dtype(dtype or "float32")))

    @staticmethod
    def randn(*shape, dtype=None) -> INDArray:
        return INDArray(jax.random.normal(
            _random.next_key(), _shape(shape),
            to_jnp_dtype(dtype or "float32")))

    @staticmethod
    def rand_int(maxval, *shape) -> INDArray:
        return INDArray(jax.random.randint(
            _random.next_key(), _shape(shape), 0, int(maxval),
            dtype=jnp.int32))

    @staticmethod
    def bernoulli(p, *shape) -> INDArray:
        return INDArray(jax.random.bernoulli(
            _random.next_key(), p, _shape(shape)))

    @staticmethod
    def shuffle(a: INDArray) -> INDArray:
        perm = jax.random.permutation(_random.next_key(), a.shape[0])
        a._write(a.data[perm])
        return a

    # -- combining ------------------------------------------------------
    @staticmethod
    def concat(dim: int, *arrays) -> INDArray:
        return INDArray(jnp.concatenate([jnp.asarray(_unwrap(a))
                                         for a in arrays], axis=dim))

    @staticmethod
    def stack(dim: int, *arrays) -> INDArray:
        return INDArray(jnp.stack([jnp.asarray(_unwrap(a))
                                   for a in arrays], axis=dim))

    @staticmethod
    def vstack(*arrays) -> INDArray:
        return INDArray(jnp.vstack([jnp.asarray(_unwrap(a))
                                    for a in arrays]))

    @staticmethod
    def hstack(*arrays) -> INDArray:
        return INDArray(jnp.hstack([jnp.asarray(_unwrap(a))
                                    for a in arrays]))

    @staticmethod
    def pile(*arrays) -> INDArray:
        return Nd4j.stack(0, *arrays)

    @staticmethod
    def tile(a, *reps) -> INDArray:
        return INDArray(jnp.tile(jnp.asarray(_unwrap(a)), _shape(reps)))

    # -- linalg / misc ---------------------------------------------------
    @staticmethod
    def gemm(a, b, transpose_a=False, transpose_b=False,
             alpha=1.0, beta=0.0, c=None) -> INDArray:
        """C = alpha*op(A)@op(B) + beta*C. When ``c`` is an INDArray the
        result is also written into it (reference gemm accumulates into C)."""
        A = jnp.asarray(_unwrap(a))
        B = jnp.asarray(_unwrap(b))
        if transpose_a:
            A = A.T
        if transpose_b:
            B = B.T
        out = alpha * (A @ B)
        if c is not None and beta != 0.0:
            out = out + beta * jnp.asarray(_unwrap(c))
        if isinstance(c, INDArray):
            c._write(out)
            return c
        return INDArray(out)

    @staticmethod
    def matmul(a, b) -> INDArray:
        return INDArray(jnp.matmul(jnp.asarray(_unwrap(a)),
                                   jnp.asarray(_unwrap(b))))

    @staticmethod
    def diag(a) -> INDArray:
        return INDArray(jnp.diag(jnp.asarray(_unwrap(a))))

    @staticmethod
    def sort(a, dim: int = -1, ascending: bool = True) -> INDArray:
        out = jnp.sort(jnp.asarray(_unwrap(a)), axis=dim)
        if not ascending:
            out = jnp.flip(out, axis=dim)
        return INDArray(out)

    @staticmethod
    def argsort(a, dim: int = -1) -> INDArray:
        return INDArray(jnp.argsort(jnp.asarray(_unwrap(a)), axis=dim))

    @staticmethod
    def where(cond, x, y) -> INDArray:
        return INDArray(jnp.where(jnp.asarray(_unwrap(cond)),
                                  jnp.asarray(_unwrap(x)),
                                  jnp.asarray(_unwrap(y))))

    @staticmethod
    def pad(a, pad_width, mode="constant", constant_values=0) -> INDArray:
        return INDArray(jnp.pad(jnp.asarray(_unwrap(a)), pad_width,
                                mode=mode,
                                **({"constant_values": constant_values}
                                   if mode == "constant" else {})))

    @staticmethod
    def one_hot(indices, depth: int, dtype=None) -> INDArray:
        return INDArray(jax.nn.one_hot(jnp.asarray(_unwrap(indices)),
                                       depth,
                                       dtype=to_jnp_dtype(dtype or "float32")))

    @staticmethod
    def to_flattened(*arrays) -> INDArray:
        """Flatten+concat — the reference's param-view serialization order."""
        return INDArray(jnp.concatenate(
            [jnp.asarray(_unwrap(a)).reshape(-1) for a in arrays]))
