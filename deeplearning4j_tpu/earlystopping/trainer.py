"""EarlyStoppingTrainer (reference: `org.deeplearning4j.earlystopping.
trainer.EarlyStoppingTrainer` + `EarlyStoppingConfiguration` +
`EarlyStoppingResult`)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .saver import InMemoryModelSaver


class EarlyStoppingConfiguration:
    def __init__(self, score_calculator=None, model_saver=None,
                 epoch_termination_conditions=None,
                 iteration_termination_conditions=None,
                 evaluate_every_n_epochs: int = 1,
                 save_last_model: bool = False):
        self.score_calculator = score_calculator
        self.model_saver = model_saver or InMemoryModelSaver()
        self.epoch_conditions = epoch_termination_conditions or []
        self.iteration_conditions = \
            iteration_termination_conditions or []
        self.evaluate_every_n_epochs = evaluate_every_n_epochs
        self.save_last_model = save_last_model

    class Builder:
        def __init__(self):
            self._kw: Dict[str, Any] = {}

        def score_calculator(self, sc):
            self._kw["score_calculator"] = sc
            return self

        def model_saver(self, ms):
            self._kw["model_saver"] = ms
            return self

        def epoch_termination_conditions(self, *conds):
            self._kw["epoch_termination_conditions"] = list(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._kw["iteration_termination_conditions"] = list(conds)
            return self

        def evaluate_every_n_epochs(self, n):
            self._kw["evaluate_every_n_epochs"] = n
            return self

        def save_last_model(self, b=True):
            self._kw["save_last_model"] = b
            return self

        def build(self):
            return EarlyStoppingConfiguration(**self._kw)


@dataclass
class EarlyStoppingResult:
    termination_reason: str            # "EpochTermination" | ...
    termination_details: str
    score_vs_epoch: Dict[int, float] = field(default_factory=dict)
    best_model_epoch: int = -1
    best_model_score: float = float("nan")
    total_epochs: int = 0
    best_model: Any = None

    def get_best_model(self):
        return self.best_model


class EarlyStoppingTrainer:
    """Train epoch-by-epoch with scoring/checkpointing between epochs."""

    def __init__(self, conf: EarlyStoppingConfiguration, model,
                 train_iterator):
        self.conf = conf
        self.model = model
        self.iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        c = self.conf
        for cond in c.epoch_conditions + c.iteration_conditions:
            cond.initialize()
        best_score: Optional[float] = None
        best_epoch = -1
        scores: Dict[int, float] = {}
        epoch = 0
        reason, details = "Unknown", ""
        minimize = getattr(c.score_calculator, "minimize_score", True)

        while True:
            # -- one training epoch, iteration guards inside ---------
            self.iterator.reset()
            aborted = False
            while self.iterator.has_next():
                ds = self.iterator.next()
                self.model.fit(ds)
                s = float(self.model.score())
                for cond in c.iteration_conditions:
                    if cond.terminate(s):
                        reason = "IterationTermination"
                        details = type(cond).__name__
                        aborted = True
                        break
                if aborted:
                    break
            if aborted:
                break

            # -- score + save best -----------------------------------
            if c.score_calculator is not None and \
                    epoch % c.evaluate_every_n_epochs == 0:
                s = c.score_calculator.calculate_score(self.model)
                scores[epoch] = s
                better = (best_score is None
                          or (s < best_score if minimize
                              else s > best_score))
                if better:
                    best_score = s
                    best_epoch = epoch
                    c.model_saver.save_best_model(self.model, s)
            if c.save_last_model:
                c.model_saver.save_latest_model(
                    self.model, scores.get(epoch, float("nan")))

            # -- epoch termination -----------------------------------
            stop = False
            for cond in c.epoch_conditions:
                if cond.terminate(epoch, scores.get(epoch,
                                                    float("nan")),
                                  minimize):
                    reason = "EpochTermination"
                    details = type(cond).__name__
                    stop = True
                    break
            epoch += 1
            if stop:
                break

        best = c.model_saver.get_best_model()
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            score_vs_epoch=scores,
            best_model_epoch=best_epoch,
            best_model_score=(best_score if best_score is not None
                              else float("nan")),
            total_epochs=epoch,
            best_model=best if best is not None else self.model)
