"""Termination conditions (reference: `org.deeplearning4j.
earlystopping.termination.*` — same class names, same semantics)."""
from __future__ import annotations

import time


class EpochTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float,
                  minimize: bool = True) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score, minimize=True):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(
        EpochTerminationCondition):
    """Stop after ``max_epochs_without_improvement`` stagnant epochs
    (optionally requiring ``min_improvement`` per epoch)."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best = None
        self.stagnant = 0

    def initialize(self):
        self.best = None
        self.stagnant = 0

    def terminate(self, epoch, score, minimize=True):
        import math
        if isinstance(score, float) and math.isnan(score):
            return False          # no evaluation this epoch
        if self.best is None:
            self.best = score
            return False
        improved = (self.best - score if minimize
                    else score - self.best) > self.min_improvement
        if improved:
            self.best = score
            self.stagnant = 0
        else:
            self.stagnant += 1
        return self.stagnant >= self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at least as good as a target."""

    def __init__(self, target: float):
        self.target = target

    def terminate(self, epoch, score, minimize=True):
        return score <= self.target if minimize else \
            score >= self.target


class MaxTimeIterationTerminationCondition(
        IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def initialize(self):
        self._start = time.time()

    def terminate(self, score):
        if self._start is None:
            self.initialize()
        return time.time() - self._start > self.max_seconds


class MaxScoreIterationTerminationCondition(
        IterationTerminationCondition):
    """Abort if the minibatch score explodes past a bound
    (divergence guard)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, score):
        import math
        return score > self.max_score or math.isnan(score)
