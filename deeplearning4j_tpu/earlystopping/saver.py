"""Model savers (reference: `org.deeplearning4j.earlystopping.saver.
{InMemoryModelSaver, LocalFileModelSaver}`)."""
from __future__ import annotations

import os
from typing import Optional


class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score):
        self._best = model.clone()

    def save_latest_model(self, model, score):
        self._latest = model.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver:
    """Zip-format persistence via ModelSerializer (reference keeps
    bestModel.bin / latestModel.bin in a directory)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def save_best_model(self, model, score):
        from ..utils.serializer import ModelSerializer
        ModelSerializer.write_model(model, self._path("bestModel.bin"))

    def save_latest_model(self, model, score):
        from ..utils.serializer import ModelSerializer
        ModelSerializer.write_model(model,
                                    self._path("latestModel.bin"))

    def get_best_model(self):
        from ..utils.serializer import ModelSerializer
        p = self._path("bestModel.bin")
        if not os.path.exists(p):
            return None
        return ModelSerializer.restore_model(p)

    def get_latest_model(self):
        from ..utils.serializer import ModelSerializer
        return ModelSerializer.restore_model(
            self._path("latestModel.bin"))
